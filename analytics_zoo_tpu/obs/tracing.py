"""End-to-end request tracing for the serving pipeline.

A request that enters the data plane under an active trace context
carries its trace id through the ``AZT1`` wire blob (``__trace__`` meta
key, serving/queues.py), and each pipeline stage the request crosses --
``decode``, ``dispatch``, ``finalize`` in the worker, ``http_request``
in the frontend -- records a span against that id. Spans land in a
bounded process-wide collector and export as Chrome trace-event JSON
loadable in perfetto / chrome://tracing.

Tracing is config-gated (``zoo.obs.trace.enabled``, default **false**)
and designed so the disabled path costs nothing measurable: producers
only read a thread-local (no config lookup per request), and the worker
skips span emission entirely for requests that carry no trace id.

Usage::

    from analytics_zoo_tpu.obs import tracing
    with tracing.maybe_trace("client_request") as trace_id:
        input_queue.enqueue(uri, x=tensor)   # blob carries trace_id
    ...
    tracing.get_tracer().dump_chrome_trace("trace.json")
"""

from __future__ import annotations

import collections
import json
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.common.config import get_config

_state = threading.local()


def enabled() -> bool:
    """Whether tracing is switched on (``zoo.obs.trace.enabled``). Read
    once per *request entry point* (HTTP handler, client context), not
    per queue operation -- the data plane consults only the
    thread-local."""
    return bool(get_config().get("zoo.obs.trace.enabled", False))


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace_id() -> Optional[str]:
    """The trace id active on this thread (None when tracing is off or
    no context is open). A single thread-local read: cheap enough for
    the enqueue hot path."""
    return getattr(_state, "trace_id", None)


@contextmanager
def trace_context(trace_id: Optional[str]):
    """Bind ``trace_id`` to this thread for the duration of the block
    (requests enqueued inside inherit it on the wire)."""
    prev = getattr(_state, "trace_id", None)
    _state.trace_id = trace_id
    try:
        yield trace_id
    finally:
        _state.trace_id = prev


@contextmanager
def maybe_trace(name: str, trace_id: Optional[str] = None, **args):
    """Open a traced region when tracing is enabled: yields the trace id
    (fresh unless given) with the context bound to this thread, and
    records a span named ``name`` over the block. When tracing is
    disabled, yields None and touches nothing but one config read."""
    if not enabled():
        yield None
        return
    tid = trace_id or new_trace_id()
    tracer = get_tracer()
    t0 = time.perf_counter()
    with trace_context(tid):
        try:
            yield tid
        finally:
            tracer.add_span(name, tid, t0, time.perf_counter(), **args)


class Tracer:
    """Bounded collector of finished spans.

    A span is a dict: ``name``, ``trace_id``, ``t0``/``t1`` (module
    perf_counter seconds), ``thread`` (recording thread's name), plus
    free-form args. The ring holds ``max_spans`` (config
    ``zoo.obs.trace.max_spans``); older spans fall off -- tracing is a
    flight recorder, not an archive."""

    def __init__(self, max_spans: Optional[int] = None):
        if max_spans is None:
            max_spans = int(get_config().get("zoo.obs.trace.max_spans",
                                             8192))
        self._spans: collections.deque = collections.deque(
            maxlen=max_spans)
        self._lock = threading.Lock()
        # perf_counter anchor so exported timestamps start near zero
        self._epoch = time.perf_counter()

    def add_span(self, name: str, trace_id: str, t0: float, t1: float,
                 **args) -> None:
        span = {"name": name, "trace_id": trace_id, "t0": t0, "t1": t1,
                "thread": threading.current_thread().name}
        if args:
            span["args"] = args
        with self._lock:
            self._spans.append(span)

    def spans(self, trace_id: Optional[str] = None) -> List[Dict]:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # --------------------------------------------------------- export --
    def chrome_trace(self, trace_id: Optional[str] = None
                     ) -> Dict[str, Any]:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}``
        object format): complete events ("ph": "X") with microsecond
        timestamps, one row per recording thread, trace ids in args.
        Load in chrome://tracing or https://ui.perfetto.dev."""
        events: List[Dict[str, Any]] = []
        threads: Dict[str, int] = {}
        for s in self.spans(trace_id):
            tid = threads.setdefault(s["thread"], len(threads) + 1)
            args = dict(s.get("args") or {})
            args["trace_id"] = s["trace_id"]
            events.append({
                "name": s["name"],
                "cat": "serving",
                "ph": "X",
                "ts": round((s["t0"] - self._epoch) * 1e6, 3),
                "dur": round((s["t1"] - s["t0"]) * 1e6, 3),
                "pid": 1,
                "tid": tid,
                "args": args,
            })
        for tname, tid in threads.items():
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": {"name": tname}})
        return {"traceEvents": events,
                "displayTimeUnit": "ms"}

    def dump_chrome_trace(self, path: str,
                          trace_id: Optional[str] = None) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(trace_id), f)
        return path


_global_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _global_tracer
    with _tracer_lock:
        if _global_tracer is None:
            _global_tracer = Tracer()
        return _global_tracer
