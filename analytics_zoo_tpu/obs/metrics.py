"""Process-wide metrics registry with Prometheus + JSON export.

The unification layer ISSUE-2 asked for: the reference platform exposes
serving ``Timer`` stats to a dashboard (ref: zoo/.../serving/engine/
Timer.scala:24-90 published via Supportive) and BigDL training exposes
``Metrics`` counters; our rebuild had three disconnected instrumentation
islands (serving/timer.py, common/log.py TimerStat, learn/profiler.py)
with no export surface. This module is the single vocabulary:

- :class:`StatCore` -- the one implementation of the per-stage stat math
  (count/total/max/min/top-10, optional raw-sample ring for percentiles,
  optional fixed histogram buckets). ``serving.timer.Timer`` and
  ``common.log.TimerStat`` are thin shims over it.
- :class:`Counter` / :class:`Gauge` / :class:`Histogram` -- registry
  instruments, optionally labelled (``family.labels(stage="decode")``).
- :class:`MetricsRegistry` -- named-family registry with idempotent
  registration, a JSON snapshot (``snapshot()``), and Prometheus text
  exposition (``prometheus_text()``, format 0.0.4) served by
  ``HttpFrontend`` at ``GET /metrics``.

Naming convention (enforced by ``tests/test_metric_names.py``):
``zoo_<subsystem>_<name>_<unit>`` with unit one of ``total`` (counters),
``seconds``, ``bytes``, ``items``, ``ratio``, ``info``.

No third-party dependencies and no jax import: the registry must be
importable from the batcher/queue layer and from client processes.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# latency-shaped default buckets (seconds); chosen to straddle the
# serving pipeline's observed range: ~0.5 ms stage times to multi-second
# first-compile stalls
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_UNITS = ("total", "seconds", "bytes", "items", "ratio", "info")
METRIC_NAME_RE = re.compile(
    r"^zoo_[a-z][a-z0-9]*_[a-z0-9_]+_(%s)$" % "|".join(_UNITS))
_LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")


def check_metric_name(name: str, kind: str = "") -> None:
    """Raise ValueError unless ``name`` follows the
    ``zoo_<subsystem>_<name>_<unit>`` convention (counters must end in
    ``_total``)."""
    if not METRIC_NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} breaks the zoo_<subsystem>_<name>_"
            f"<unit> convention (unit one of {', '.join(_UNITS)})")
    if kind == "counter" and not name.endswith("_total"):
        raise ValueError(f"counter {name!r} must end in _total")
    if kind != "counter" and name.endswith("_total"):
        raise ValueError(f"{kind or 'metric'} {name!r} must not end in "
                         "_total (reserved for counters)")


class StatCore:
    """Accumulated stats for one observed series: count/total/max/min/
    top-10, an optional raw-sample ring (percentiles), and optional
    fixed cumulative-histogram buckets. NOT thread-safe -- owners
    serialize access (registry children and both Timer shims hold their
    own locks)."""

    __slots__ = ("count", "total", "max", "min", "_top", "_top_k",
                 "_samples", "_cap", "_bounds", "_bucket_counts")

    def __init__(self, keep_samples: int = 0,
                 buckets: Optional[Sequence[float]] = None,
                 top_k: int = 10):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self._top: List[float] = []  # k largest, kept sorted ascending
        self._top_k = top_k
        self._samples: Optional[List[float]] = ([] if keep_samples
                                                else None)
        self._cap = keep_samples
        self._bounds = tuple(buckets) if buckets else None
        self._bucket_counts = ([0] * (len(self._bounds) + 1)
                               if self._bounds else None)

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v
        if v < self.min:
            self.min = v
        top = self._top
        if len(top) < self._top_k:
            bisect.insort(top, v)
        elif top and v > top[0]:
            top[0] = v
            top.sort()
        if self._samples is not None:
            if len(self._samples) >= self._cap:
                self._samples[self.count % self._cap] = v
            else:
                self._samples.append(v)
        if self._bounds is not None:
            self._bucket_counts[bisect.bisect_left(self._bounds, v)] += 1

    # ------------------------------------------------------- summaries --
    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def top(self, n: int = 10) -> List[float]:
        return self._top[::-1][:n]

    def percentile(self, q: float) -> Optional[float]:
        """From the raw-sample ring; None when sampling is off/empty."""
        if not self._samples:
            return None
        ordered = sorted(self._samples)
        return ordered[min(len(ordered) - 1, int(len(ordered) * q))]

    def summary(self, suffix: str = "") -> Dict[str, float]:
        """The stat dict shape of the historical serving Timer: count,
        total/avg/max/min (+ ``suffix``, e.g. ``_s``), top-10 average,
        and p50/p99 when the sample ring is on."""
        out = {
            "count": self.count,
            "total" + suffix: self.total,
            "avg" + suffix: self.avg,
            "max" + suffix: self.max,
            "min" + suffix: self.min if self.count else 0.0,
            "top10_avg" + suffix: (sum(self._top) / len(self._top)
                                   if self._top else 0.0),
        }
        p50 = self.percentile(0.50)
        if p50 is not None:
            out["p50" + suffix] = p50
            out["p99" + suffix] = self.percentile(0.99)
        return out

    def bucket_counts(self) -> Optional[List[Tuple[float, int]]]:
        """Cumulative (le, count) pairs ending with (+inf, count)."""
        if self._bounds is None:
            return None
        out, acc = [], 0
        for le, c in zip(self._bounds, self._bucket_counts):
            acc += c
            out.append((le, acc))
        out.append((math.inf, acc + self._bucket_counts[-1]))
        return out


# ------------------------------------------------------------------ #
# instruments                                                         #
# ------------------------------------------------------------------ #
class _Family:
    """Base for labelled instrument families: ``labels(**kv)`` returns
    the child for that label combination (created on first use)."""

    kind = ""

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        check_metric_name(name, self.kind)
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln):
                raise ValueError(f"bad label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not self.labelnames:  # unlabelled: one implicit child
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **kv) -> Any:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(kv)}, declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _items(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())

    def _only_child(self):
        """The implicit child of an unlabelled family (what the
        convenience methods operate on); labelled families get a
        self-diagnosing error instead of a bare KeyError."""
        child = self._children.get(())
        if child is None:
            raise ValueError(
                f"{self.name} is labelled {self.labelnames}; use "
                ".labels(...) to pick a series")
        return child


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Family):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    # unlabelled conveniences
    def inc(self, n: float = 1.0) -> None:
        self._only_child().inc(n)

    @property
    def value(self) -> float:
        return self._only_child().value


class _GaugeChild:
    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        """Scrape-time callback (queue depths): evaluated at snapshot/
        exposition; a raising callback reads as the last set() value."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            v = self._value
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return v
        return v


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self._only_child().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._only_child().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._only_child().dec(n)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        self._only_child().set_function(fn)

    @property
    def value(self) -> float:
        return self._only_child().value


class _HistogramChild:
    __slots__ = ("_core", "_lock")

    def __init__(self, buckets: Sequence[float], keep_samples: int):
        self._core = StatCore(keep_samples=keep_samples, buckets=buckets)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._core.observe(float(v))

    def time(self):
        """Context manager observing the elapsed seconds."""
        return _HistTimer(self)

    def summary(self, suffix: str = "") -> Dict[str, float]:
        with self._lock:
            return self._core.summary(suffix)

    def snapshot(self, with_buckets: bool = True) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "count": self._core.count,
                "sum": self._core.total,
                "avg": self._core.avg,
                "max": self._core.max,
                "min": self._core.min if self._core.count else 0.0,
            }
            p50 = self._core.percentile(0.50)
            if p50 is not None:
                out["p50"] = p50
                out["p99"] = self._core.percentile(0.99)
            if with_buckets:
                bc = self._core.bucket_counts()
                if bc is not None:
                    out["buckets"] = [
                        ["+Inf" if math.isinf(le) else le, c]
                        for le, c in bc]
            return out

    def _expo(self) -> Tuple[List[Tuple[float, int]], float, int]:
        with self._lock:
            return (self._core.bucket_counts() or [],
                    self._core.total, self._core.count)


class _HistTimer:
    __slots__ = ("_child", "_t0")

    def __init__(self, child: _HistogramChild):
        self._child = child

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._child.observe(time.perf_counter() - self._t0)
        return False


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 keep_samples: int = 0):
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self.keep_samples = keep_samples
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self.buckets, self.keep_samples)

    def observe(self, v: float) -> None:
        self._only_child().observe(v)

    def time(self):
        return self._only_child().time()

    def snapshot(self, with_buckets: bool = True) -> Dict[str, Any]:
        return self._only_child().snapshot(with_buckets)


# ------------------------------------------------------------------ #
# registry                                                            #
# ------------------------------------------------------------------ #
def _escape_label(v: str) -> str:
    return (v.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Named instrument families. Registration is idempotent: asking for
    an existing name with the same kind + labelnames returns the
    existing family (per-instance wiring in workers/frontends re-runs
    freely); a kind or label mismatch raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # --------------------------------------------------- registration --
    def _register(self, cls, name: str, help: str, labelnames,
                  **kwargs) -> Any:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or \
                        fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind} with labels {fam.labelnames}")
                if isinstance(fam, Histogram) and (
                        fam.buckets != tuple(sorted(
                            kwargs.get("buckets", DEFAULT_BUCKETS)))
                        or fam.keep_samples != kwargs.get(
                            "keep_samples", 0)):
                    # silently handing back a family with different
                    # buckets would put the caller's observations on
                    # boundaries it never declared
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam.buckets}, keep_samples "
                        f"{fam.keep_samples}")
                return fam
            fam = cls(name, help, labelnames, **kwargs)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  keep_samples: int = 0) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets, keep_samples=keep_samples)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # --------------------------------------------------------- export --
    def snapshot(self, with_buckets: bool = True) -> Dict[str, Any]:
        """JSON-able registry state; ``with_buckets=False`` drops the
        per-bucket arrays (the compact form bench lines embed)."""
        out: Dict[str, Any] = {}
        for fam in self.families():
            series: Dict[str, Any] = {}
            for key, child in fam._items():
                label = ",".join(
                    f"{ln}={lv}"
                    for ln, lv in zip(fam.labelnames, key)) or ""
                if fam.kind == "histogram":
                    series[label] = child.snapshot(with_buckets)
                else:
                    series[label] = child.value
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": series}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} "
                             + fam.help.replace("\n", " "))
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam._items():
                pairs = [f'{ln}="{_escape_label(lv)}"'
                         for ln, lv in zip(fam.labelnames, key)]
                base = "{" + ",".join(pairs) + "}" if pairs else ""
                if fam.kind == "histogram":
                    buckets, total, count = child._expo()
                    for le, c in buckets:
                        lp = pairs + [f'le="{_fmt(le)}"']
                        lines.append(f"{fam.name}_bucket"
                                     "{" + ",".join(lp) + "}" + f" {c}")
                    lines.append(f"{fam.name}_sum{base} {_fmt(total)}")
                    lines.append(f"{fam.name}_count{base} {count}")
                else:
                    lines.append(f"{fam.name}{base} "
                                 f"{_fmt(child.value)}")
        return "\n".join(lines) + "\n"


def snapshot_delta(before: Dict[str, Any], after: Dict[str, Any]
                   ) -> Dict[str, Any]:
    """Interval view between two ``snapshot(with_buckets=False)``
    dicts: counter deltas, histogram interval ``count``/``avg``,
    gauges as last observed. Series idle over the interval (zero
    counter delta, zero new histogram observations, zero gauge) are
    dropped -- the registry is process-global and cumulative, so any
    per-window reading (the reporter's rollup, the perf harness's
    per-engine numbers) must diff snapshots rather than read
    absolutes. Cumulative fields that cannot be diffed (min/max/
    percentiles) are intentionally omitted: they would blend in
    activity from before the interval."""
    out: Dict[str, Any] = {}
    for name, fam in after.items():
        prev = before.get(name, {"values": {}})
        series: Dict[str, Any] = {}
        for label, val in fam["values"].items():
            pval = prev["values"].get(label)
            if fam["type"] == "counter":
                delta = val - (pval or 0)
                if delta:
                    series[label] = delta
            elif fam["type"] == "gauge":
                if val:
                    series[label] = val
            else:  # histogram
                dcount = val["count"] - (pval or {}).get("count", 0)
                if dcount > 0:
                    dsum = val["sum"] - (pval or {}).get("sum", 0.0)
                    series[label] = {"count": dcount,
                                     "avg": dsum / dcount}
        if series:
            out[name] = {"type": fam["type"], "values": series}
    return out


_global_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem wires into (the
    scrape surface of ``HttpFrontend``'s ``/metrics``)."""
    global _global_registry
    with _registry_lock:
        if _global_registry is None:
            _global_registry = MetricsRegistry()
        return _global_registry
