"""Unified observability layer: metrics registry, Prometheus/JSON
export, request tracing, the background rollup reporter, and the
flight recorder (structured event log + crash postmortems).

One vocabulary for serving AND training instrumentation (the reference
split this between the serving ``Timer``/dashboard publisher and BigDL
training ``Metrics``): every subsystem registers
``zoo_<subsystem>_<name>_<unit>`` instruments in the process-wide
registry; ``HttpFrontend`` exposes it at ``GET /metrics`` (Prometheus
text) and ``GET /metrics.json``; spans ride requests through the
serving pipeline and export as Chrome trace-event JSON; typed events
(obs.events, one vocabulary in ``EVENT_TYPES``) land in a bounded ring
served at ``GET /debug/events``, and on crash obs.flight dumps a
postmortem bundle (events + metrics + spans + in-flight request ids +
config). See docs/observability.md.
"""

from analytics_zoo_tpu.obs.events import (  # noqa: F401
    EVENT_TYPES,
    EventLog,
    RecompileDetector,
    check_event_type,
    emit,
    get_event_log,
    get_recompile_detector,
    instrument_compiles,
    is_warming,
    record_compile,
    register_event_type,
    warming,
)
from analytics_zoo_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    InflightRequests,
    get_flight_recorder,
    get_inflight,
    install_flight_recorder,
    uninstall_flight_recorder,
)
from analytics_zoo_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    METRIC_NAME_RE,
    MetricsRegistry,
    StatCore,
    check_metric_name,
    get_registry,
)
from analytics_zoo_tpu.obs.tracing import (  # noqa: F401
    Tracer,
    current_trace_id,
    get_tracer,
    maybe_trace,
    new_trace_id,
    trace_context,
)

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
    "METRIC_NAME_RE", "MetricsRegistry", "StatCore",
    "check_metric_name", "get_registry",
    "Tracer", "current_trace_id", "get_tracer", "maybe_trace",
    "new_trace_id", "trace_context",
    "EVENT_TYPES", "EventLog", "RecompileDetector", "check_event_type",
    "emit", "get_event_log", "get_recompile_detector",
    "instrument_compiles", "is_warming", "record_compile",
    "register_event_type", "warming",
    "FlightRecorder", "InflightRequests", "get_flight_recorder",
    "get_inflight", "install_flight_recorder",
    "uninstall_flight_recorder",
]
