"""Unified observability layer: metrics registry, Prometheus/JSON
export, request tracing, and the background rollup reporter.

One vocabulary for serving AND training instrumentation (the reference
split this between the serving ``Timer``/dashboard publisher and BigDL
training ``Metrics``): every subsystem registers
``zoo_<subsystem>_<name>_<unit>`` instruments in the process-wide
registry; ``HttpFrontend`` exposes it at ``GET /metrics`` (Prometheus
text) and ``GET /metrics.json``; spans ride requests through the
serving pipeline and export as Chrome trace-event JSON. See
docs/observability.md.
"""

from analytics_zoo_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    METRIC_NAME_RE,
    MetricsRegistry,
    StatCore,
    check_metric_name,
    get_registry,
)
from analytics_zoo_tpu.obs.tracing import (  # noqa: F401
    Tracer,
    current_trace_id,
    get_tracer,
    maybe_trace,
    new_trace_id,
    trace_context,
)

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
    "METRIC_NAME_RE", "MetricsRegistry", "StatCore",
    "check_metric_name", "get_registry",
    "Tracer", "current_trace_id", "get_tracer", "maybe_trace",
    "new_trace_id", "trace_context",
]
