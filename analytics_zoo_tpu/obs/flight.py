"""Flight recorder: crash hooks + postmortem bundles.

When a serving process dies -- uncaught exception on any thread, fatal
signal, SIGTERM from the orchestrator -- the dashboards of obs.metrics
go dark with it. This module writes the black box instead: on crash it
dumps a **postmortem bundle** (a directory) containing

- ``manifest.json``   reason, timestamp, pid, thread, exception +
                      traceback, python/platform info, uptime
- ``events.jsonl``    the last N structured events (obs.events)
- ``metrics.json``    a full metrics-registry snapshot
- ``spans.json``      active/collected trace spans (obs.tracing)
- ``inflight.json``   request ids dispatched but not yet answered
- ``config.json``     the resolved layered config

into ``zoo.obs.postmortem.dir``, turning "rerun and hope" into a
readable artifact. Installation is explicit (:func:`install`, done by
the serving launcher when ``zoo.obs.flight.enabled``); the hooks chain
to whatever was installed before them, and ``faulthandler`` covers the
failures Python never sees (segfault in a native lib, deadlock dump
via SIGABRT) by streaming C-level tracebacks into the same directory.

The in-flight request registry lives here too: the serving worker
registers every dispatched-but-unanswered uri, so a postmortem names
exactly which requests were lost -- the first question after a prod
crash.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.obs import events as _events
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.obs.tracing import get_tracer

# stdlib logger: same import-order constraint as obs.events
logger = logging.getLogger(__name__)


class InflightRequests:
    """Process-wide set of request ids dispatched but not yet answered.
    The worker adds a batch's uris at dispatch and discards them at
    finalize -- two lock trips per *batch*, not per request, so the
    hot path cost is negligible."""

    def __init__(self):
        self._ids: set = set()
        self._lock = threading.Lock()

    def add(self, ids) -> None:
        with self._lock:
            self._ids.update(ids)

    def discard(self, ids) -> None:
        with self._lock:
            self._ids.difference_update(ids)

    def snapshot(self) -> List[str]:
        with self._lock:
            return sorted(self._ids)

    def clear(self) -> None:
        with self._lock:
            self._ids.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ids)


_inflight = InflightRequests()


def get_inflight() -> InflightRequests:
    return _inflight


class FlightRecorder:
    """Installs crash hooks and writes postmortem bundles.

    Args:
      out_dir: bundle directory root (None reads
        ``zoo.obs.postmortem.dir``; ``~`` expands).
      max_events: events.jsonl length (None reads
        ``zoo.obs.postmortem.max_events``).
    """

    def __init__(self, out_dir: Optional[str] = None,
                 max_events: Optional[int] = None):
        cfg = get_config()
        if out_dir is None:
            out_dir = str(cfg.get(
                "zoo.obs.postmortem.dir",
                "~/.cache/analytics-zoo-tpu/postmortems"))
        self.out_dir = os.path.expanduser(out_dir)
        self.max_events = int(cfg.get("zoo.obs.postmortem.max_events",
                                      512)
                              if max_events is None else max_events)
        self._installed = False
        self._signals_installed = False
        self._prev_excepthook = None
        self._prev_thread_hook = None
        self._prev_sigterm = None
        self._fault_file = None
        self._fault_was_enabled = False
        self._started_at = time.time()
        # re-entrancy guard: a crash inside postmortem writing (disk
        # full, broken registry) must not recurse into another bundle
        self._writing = threading.Lock()

    # -------------------------------------------------------- bundles --
    def write_postmortem(self, reason: str,
                         exc: Optional[BaseException] = None,
                         thread: Optional[str] = None
                         ) -> Optional[str]:
        """Write one bundle; returns its path, or None when a write is
        already in progress (re-entrant crash) or the dump itself
        failed. Never raises: the recorder runs inside excepthooks
        where a second exception would mask the first."""
        if not self._writing.acquire(blocking=False):
            return None
        try:
            return self._write_bundle(reason, exc, thread)
        except Exception as e:  # pragma: no cover - last-resort path
            logger.error("postmortem write failed: %s", e)
            return None
        finally:
            self._writing.release()

    def _write_bundle(self, reason, exc, thread) -> str:
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        path = os.path.join(self.out_dir,
                            f"postmortem-{stamp}-pid{os.getpid()}")
        n = 1
        while os.path.exists(path if n == 1 else f"{path}.{n}"):
            n += 1
        if n > 1:
            path = f"{path}.{n}"
        os.makedirs(path)

        def dump(name: str, obj: Any) -> None:
            # one file failing (unserializable corner, disk hiccup)
            # must not void the rest of the bundle
            try:
                with open(os.path.join(path, name), "w") as f:
                    if name.endswith(".jsonl"):
                        f.write(obj)
                    else:
                        json.dump(_events.to_jsonable(obj), f, indent=2,
                                  sort_keys=True)
            except Exception as e:
                logger.error("postmortem: %s failed: %s", name, e)

        manifest: Dict[str, Any] = {
            "reason": reason,
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self._started_at, 3),
            "python": sys.version.split()[0],
            "platform": sys.platform,
            "argv": list(sys.argv),
            "thread": thread or threading.current_thread().name,
            "threads_alive": sorted(t.name
                                    for t in threading.enumerate()),
        }
        if exc is not None:
            manifest["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }
        dump("manifest.json", manifest)
        log = _events.get_event_log()
        dump("events.jsonl", log.to_jsonl(self.max_events))
        dump("metrics.json", get_registry().snapshot())
        dump("spans.json", get_tracer().spans())
        dump("inflight.json", {"request_ids": _inflight.snapshot(),
                               "count": len(_inflight)})
        dump("config.json", get_config().as_dict())
        # recorded AFTER the bundle so the bundle's own event tail
        # describes the pre-crash world, not the dump
        try:
            log.emit("postmortem_written", "obs", path=path,
                     reason=reason)
        except Exception as e:
            # the bundle on disk is already complete; only the event-
            # ring echo failed (e.g. a broken metrics backend mid-
            # crash). Log it: a crash-reporting path must not itself
            # fail without evidence
            logger.debug("postmortem_written event emit failed: %s", e)
        logger.error("postmortem bundle written: %s (%s)", path, reason)
        return path

    # ---------------------------------------------------------- hooks --
    def _on_uncaught(self, exc_type, exc, tb) -> None:
        try:
            _events.emit("uncaught_exception", "obs",
                         error=f"{exc_type.__name__}: {exc}",
                         thread=threading.current_thread().name)
            self.write_postmortem("uncaught_exception", exc=exc)
        finally:
            if self._prev_excepthook is not None:
                self._prev_excepthook(exc_type, exc, tb)

    def _on_thread_exception(self, args) -> None:
        if args.exc_type is SystemExit:  # interpreter-driven exits
            return
        try:
            tname = args.thread.name if args.thread else "?"
            _events.emit("uncaught_exception", "obs",
                         error=f"{args.exc_type.__name__}: "
                               f"{args.exc_value}",
                         thread=tname)
            self.write_postmortem("thread_exception",
                                  exc=args.exc_value, thread=tname)
        finally:
            if self._prev_thread_hook is not None:
                self._prev_thread_hook(args)

    def _on_sigterm(self, signum, frame) -> None:
        import signal as _signal

        _events.emit("fatal_signal", "obs", signum=int(signum))
        self.write_postmortem(f"signal_{int(signum)}")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == _signal.SIG_IGN:
            # the host deliberately ignored this signal; our hook must
            # only add the bundle, not turn an ignored signal fatal
            return
        else:  # SIG_DFL: restore + re-raise so the process still dies
            _signal.signal(signum, _signal.SIG_DFL)
            _signal.raise_signal(signum)

    def install(self, signals: bool = False) -> "FlightRecorder":
        """Install ``sys.excepthook`` + ``threading.excepthook`` +
        ``faulthandler`` (and, with ``signals=True``, a SIGTERM hook
        that writes a bundle then chains to the previous handler).
        Idempotent, except that a later ``signals=True`` upgrades a
        signal-less install (library code installs plain; the
        entrypoint, which owns the main thread, opts into the SIGTERM
        hook afterwards)."""
        if not self._installed:
            try:
                os.makedirs(self.out_dir, exist_ok=True)
            except OSError as e:
                # unwritable bundle root (read-only container, unset
                # HOME): the crash-observability add-on must never BE
                # the crash -- degrade to hooks-only (dumps will log
                # their own failure), same stance as the compile
                # cache's dir creation (common.context)
                logger.warning("postmortem dir %s unavailable (%s); "
                               "bundles will fail until it exists",
                               self.out_dir, e)
            # pin the bound methods: attribute access mints a fresh
            # bound-method object each time, so uninstall()'s
            # are-we-still-installed identity checks need the exact
            # objects that went into the hooks
            self._hook_uncaught = self._on_uncaught
            self._hook_thread = self._on_thread_exception
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._hook_uncaught
            self._prev_thread_hook = threading.excepthook
            threading.excepthook = self._hook_thread
            try:
                import faulthandler

                self._fault_was_enabled = faulthandler.is_enabled()
                self._fault_file = open(
                    os.path.join(
                        self.out_dir,
                        f"faulthandler-pid{os.getpid()}.log"), "w")
                faulthandler.enable(self._fault_file, all_threads=True)
            except Exception as e:
                logger.warning("faulthandler unavailable: %s", e)
                self._fault_file = None
            self._installed = True
            _events.emit("flight_installed", "obs", dir=self.out_dir,
                         signals=bool(signals))
        if signals and not self._signals_installed:
            import signal as _signal

            self._prev_sigterm = _signal.signal(_signal.SIGTERM,
                                                self._on_sigterm)
            self._signals_installed = True
        return self

    def uninstall(self) -> None:
        """Restore whatever the hooks replaced (tests; embedded use)."""
        if not self._installed:
            return
        if sys.excepthook is self._hook_uncaught:
            sys.excepthook = self._prev_excepthook
        if threading.excepthook is self._hook_thread:
            threading.excepthook = self._prev_thread_hook
        if self._signals_installed:
            import signal as _signal

            try:
                _signal.signal(_signal.SIGTERM,
                               self._prev_sigterm or _signal.SIG_DFL)
            except ValueError:  # not the main thread
                pass
            self._prev_sigterm = None
            self._signals_installed = False
        if self._fault_file is not None:
            try:
                import faulthandler

                if self._fault_was_enabled:
                    # somebody (pytest, PYTHONFAULTHANDLER) had it on
                    # before us: hand it back to stderr rather than
                    # leaving the process with no hard-crash traceback
                    faulthandler.enable(all_threads=True)
                else:
                    faulthandler.disable()
                self._fault_file.close()
            except Exception as e:
                # uninstall() must not raise (tests tear down in
                # finally blocks), but a faulthandler left half-
                # restored is worth a breadcrumb
                logger.debug("faulthandler restore failed: %s", e)
            self._fault_file = None
        self._installed = False


_global_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The installed process recorder, or None before install()."""
    return _global_recorder


def install_flight_recorder(out_dir: Optional[str] = None,
                            signals: bool = False) -> FlightRecorder:
    """Install (or return) the process-wide recorder. The serving
    launcher calls this when ``zoo.obs.flight.enabled``; entrypoints
    that own the main thread pass ``signals=True`` for the SIGTERM
    bundle."""
    global _global_recorder
    with _recorder_lock:
        if _global_recorder is None:
            _global_recorder = FlightRecorder(out_dir=out_dir)
        return _global_recorder.install(signals=signals)


def uninstall_flight_recorder() -> None:
    global _global_recorder
    with _recorder_lock:
        if _global_recorder is not None:
            _global_recorder.uninstall()
            _global_recorder = None
