"""Background metrics reporter: periodic rate/latency rollups to the log.

The analog of the reference serving engine's periodic ``Timer`` print
(ref: zoo/.../serving/engine/Timer.scala:70-90 prints per-stage stats on
a cadence) -- here driven off the unified registry, so the rollup covers
counters (as rates), gauges (current value), and histograms (interval
count + interval mean) across serving AND training.

Config-gated: ``zoo.obs.report.interval`` seconds between rollups;
``0`` (the default) disables the thread entirely.
"""

from __future__ import annotations

import atexit
import logging
import threading
import time
from typing import Dict, Optional, Tuple

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.obs.metrics import (
    MetricsRegistry, get_registry, snapshot_delta)


def format_rollup(prev: Dict, cur: Dict, dt: float) -> str:
    """One log line from two registry snapshots ``dt`` seconds apart:
    counter deltas as rates, histogram interval mean latency, gauge
    current values. Families idle over the interval are omitted
    (the diff itself is :func:`obs.metrics.snapshot_delta` -- shared
    with the perf harness so the two interval views cannot drift)."""
    parts = []
    for name, fam in sorted(snapshot_delta(prev, cur).items()):
        for label, val in sorted(fam["values"].items()):
            tag = f"{name}{{{label}}}" if label else name
            if fam["type"] == "counter":
                parts.append(f"{tag}: {val / dt:.1f}/s")
            elif fam["type"] == "gauge":
                parts.append(f"{tag}: {val:g}")
            else:  # histogram: ms only for duration families;
                # occupancy/ratio report their interval mean as-is
                unit = (f"{val['avg'] * 1e3:.2f}ms"
                        if name.endswith("_seconds")
                        else f"{val['avg']:.2f}")
                parts.append(f"{tag}: n={val['count']} mean={unit}")
    return "; ".join(parts) if parts else "idle"


class Reporter:
    """Daemon thread logging registry rollups every ``interval``
    seconds (None reads ``zoo.obs.report.interval``)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval: Optional[float] = None,
                 logger: Optional[logging.Logger] = None):
        if interval is None:
            interval = float(get_config().get("zoo.obs.report.interval",
                                              0.0))
        self.registry = registry if registry is not None else \
            get_registry()
        self.interval = interval
        self._log = logger or logging.getLogger(
            "analytics_zoo_tpu.obs.reporter")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev = self.registry.snapshot(with_buckets=False)
        self._prev_t = time.monotonic()
        self._atexit_registered = False

    def tick(self, dt: Optional[float] = None) -> str:
        """One rollup (also the unit-testable core): snapshot, diff
        against the previous snapshot, log, and roll the baseline.
        Rates divide by the MEASURED time since the last tick (a
        delayed/overslept cycle must not overstate rates), unless an
        explicit ``dt`` is given."""
        now = time.monotonic()
        cur = self.registry.snapshot(with_buckets=False)
        line = format_rollup(self._prev, cur,
                             dt if dt else max(now - self._prev_t,
                                               1e-9))
        self._prev = cur
        self._prev_t = now
        if line != "idle":
            self._log.info("obs rollup: %s", line)
        return line

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # a reporting bug must never take down
                self._log.exception("obs reporter tick failed")

    def start(self) -> "Reporter":
        if self.interval <= 0:
            raise ValueError("reporter interval must be > 0 to start")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="obs-reporter")
        self._thread.start()
        if not self._atexit_registered:
            # a daemon thread dies wherever the interpreter catches it
            # -- mid-interval, rollup lost. The atexit hook turns every
            # process exit into a clean stop()+final flush, so the last
            # partial interval still reaches the log (deployments read
            # it as the run's closing line). stop() unregisters.
            atexit.register(self.stop)
            self._atexit_registered = True
        return self

    def stop(self, join_timeout: float = 5.0,
             flush: bool = True) -> None:
        """Stop the rollup thread; with ``flush`` (default) log one
        final rollup covering the partial interval since the last
        tick."""
        if self._atexit_registered:
            atexit.unregister(self.stop)
            self._atexit_registered = False
        was_running = self._thread is not None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(join_timeout)
            self._thread = None
        if flush and was_running:
            try:
                line = self.tick()  # tick() logs the rollup itself
                from analytics_zoo_tpu.obs.events import emit

                emit("reporter_final", "obs", rollup=line[:500])
            except Exception as e:
                # atexit path: interpreter teardown may have dismantled
                # the registry/event log under us. The logging module
                # shuts down after atexit hooks run (its own hook was
                # registered first, LIFO), so a debug line is still safe
                self._log.debug("final rollup flush failed: %s", e)


def maybe_start_reporter() -> Optional[Reporter]:
    """Start a reporter iff ``zoo.obs.report.interval`` > 0; the
    serving launcher calls this so deployments opt in by config."""
    interval = float(get_config().get("zoo.obs.report.interval", 0.0))
    if interval <= 0:
        return None
    return Reporter(interval=interval).start()
