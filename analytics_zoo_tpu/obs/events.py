"""Process-wide structured event log + recompile-storm detection.

The flight-recorder substrate ISSUE-3 asked for: metrics (obs.metrics)
answer "how much / how fast", but when a process dies or silently
degrades there is no *history* to read. This module is the black box:

- a fixed vocabulary of **typed events** (:data:`EVENT_TYPES` -- the one
  place event types are registered, linted by
  ``tests/test_metric_names.py`` the same way metric names are);
- :class:`EventLog` -- a bounded in-memory ring of
  ``{ts, seq, type, subsystem, fields}`` records, always on and
  allocation-cheap (one dict + one deque append per emit; no I/O, no
  formatting until somebody asks), rendered as JSON lines on demand;
- a **recompile-storm detector**: every instrumented compile boundary
  (``inference_model.predict_async`` bucket misses, the Estimator's
  jitted steps, graph-executor signatures) reports
  ``(fn, shapes, wall_s)`` here; >= K distinct shapes for one fn inside
  a sliding window raises a ``recompile_storm`` warning event and bumps
  ``zoo_obs_recompile_storms_total`` -- the failure mode that quietly
  dominates TPU serving cost (fixed-shape bucketing exists precisely to
  avoid it).

The tail is served at ``GET /debug/events`` (http_frontend) and the
last N events land in every crash postmortem (obs.flight).

No jax import at module level: the event log must be importable from
the batcher/queue layer and from client processes (same constraint as
obs.metrics).
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.obs.metrics import get_registry

# stdlib logger (not common.log.get_logger): common.log itself imports
# obs -- the event log must sit below every other subsystem
logger = logging.getLogger(__name__)

EVENT_TYPE_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")

# ------------------------------------------------------------------ #
# vocabulary                                                          #
# ------------------------------------------------------------------ #
# THE event-type registry: every emit() anywhere in the package must
# use a type listed here (lower_snake_case; enforced at emit time and
# by the tests/test_metric_names.py collected lint). Keeping the
# vocabulary in one module is what keeps postmortems greppable -- a
# type invented inline in some subsystem would never be documented,
# dashboarded, or filtered on.
EVENT_TYPES: Dict[str, str] = {
    # compile boundaries
    "compile": "a new XLA program / shape bucket was compiled "
               "(fields: fn, shapes, wall_s)",
    "recompile_storm": ">= threshold distinct shapes for one fn inside "
                       "the sliding window (fields: fn, distinct, "
                       "window_s, shapes)",
    # serving lifecycle
    "worker_start": "serving worker thread started",
    "worker_stop": "serving worker stopped (fields: served)",
    "worker_crash": "serving worker thread died on an uncaught "
                    "exception (fields: error)",
    "pipeline_abort": "pipelined engine exited abnormally, dropping "
                      "decoded requests (fields: dropped)",
    "batch_cap_change": "adaptive batcher grew/shrank its cap "
                        "(fields: cap, prev, depth)",
    "serving_error": "a per-request error reply was pushed "
                     "(fields: uri, error)",
    # serving resilience (ISSUE-5)
    "worker_restart": "supervisor restarting a dead or wedged serving "
                      "worker (fields: reason, restarts, backoff_s, "
                      "requeued)",
    "supervisor_giveup": "supervisor hit its restart cap and stopped "
                         "supervising (fields: restarts)",
    "circuit_open": "circuit breaker opened after consecutive backend "
                    "failures (fields: failures)",
    "circuit_half_open": "circuit breaker allowing one half-open "
                         "probe dispatch",
    "circuit_closed": "circuit breaker closed again after a "
                      "successful probe",
    "request_shed": "admission control started shedding a priority "
                    "class (one per shed episode per class; fields: "
                    "depth, shed_depth, priority, cost)",
    "deadline_exceeded": "a request missed its deadline and was "
                         "rejected with a structured error "
                         "(fields: uri, error)",
    "redis_reconnect": "redis adapter result drain lost its queue "
                       "backend and is retrying with backoff "
                       "(fields: error, backoff_s)",
    "chaos_injected": "a configured fault injector fired "
                      "(fields: seam, kind)",
    "frontend_start": "HTTP frontend listening (fields: address)",
    "frontend_stop": "HTTP frontend stopped",
    "serving_launch": "launcher assembled a deployment "
                      "(fields: queue, pipelined, http, shard_mode)",
    "shard_attached": "a serving shard plan committed the model onto "
                      "a device mesh (fields: mode, axis, devices, "
                      "recipe, quantized_collectives)",
    "serving_stop": "launcher deployment stopped",
    "launch_failed": "launcher aborted mid-assembly (fields: error)",
    # serving fleet (ISSUE-9)
    "replica_start": "fleet controller spawned a replica process "
                     "(fields: name, pid)",
    "replica_healthy": "a replica's /healthz went green "
                       "(fields: name, address)",
    "replica_unhealthy": "a replica failed its health check "
                         "(fields: name, status)",
    "replica_exit": "a replica process exited (fields: name, pid, "
                    "returncode, reason)",
    "replica_killed": "the controller SIGKILLed a replica "
                      "(chaos drill or stuck drain; fields: name, "
                      "pid, reason)",
    "fleet_scale": "autoscaler (or scale_to) changed the replica "
                   "count (fields: direction, n_from, n_to, reason)",
    "rolling_restart": "rolling-restart progress (fields: phase, "
                       "name; phase=slo_blocked aborts the restart)",
    "replica_reprobe": "a targeted re-probe re-admitted an unhealthy "
                       "replica between health sweeps (ISSUE-15; "
                       "fields: name, outcome, failures)",
    "slo_breach": "the fleet sample crossed a zoo.serving.slo.* "
                  "target (edge-triggered, one per breach episode; "
                  "fields: signals, p99_ms, ttft_p99_ms, "
                  "inter_token_p99_ms)",
    "drain_begin": "deployment started draining: no new pulls, "
                   "in-flight work finishing (fields: deadline_ms)",
    "drain_complete": "drain finished or hit its deadline "
                      "(fields: ok, waited_s)",
    "stream_reclaim": "a consumer reclaimed pending stream entries "
                      "owned by a dead/stalled consumer "
                      "(fields: stream, group, n)",
    # disaggregated fleet (ISSUE-20)
    "broker_unreachable": "the stream broker failed its PING liveness "
                          "probe after capped-backoff retries "
                          "(fields: address, retries, waited_s)",
    "kv_handoff": "a prefill (or draining decode) replica exported a "
                  "stream's KV pages + replay state and published it "
                  "on the handoff stream (fields: uri, slot, "
                  "prompt_len; inline_kv=0 means the snapshot was "
                  "dropped for size and the decode side re-prefills; "
                  "moved=1 marks a drain-time re-handoff)",
    "kv_import": "a decode replica restored a handed-off stream "
                 "(fields: uri, slot, produced; regenerated=1 means "
                 "the KV snapshot was absent/unusable and the stream "
                 "was deterministically re-prefilled)",
    # generation serving (ISSUE-10)
    "generation_admit": "a generate request joined the running decode "
                        "batch: prefill done, slot + KV pages "
                        "committed (fields: uri, slot, prompt_len, "
                        "bucket)",
    "generation_complete": "a generation stream finished and released "
                           "its slot (fields: uri, slot, tokens, "
                           "reason)",
    "generation_overflow": "a generate request was refused at "
                           "admission: the paged KV cache had no free "
                           "slot/pages (fields: uri, need_pages, "
                           "free_pages, free_slots)",
    # vectorized population / automl (ISSUE-13)
    "population_cohort": "a vectorized trial cohort ran as one "
                         "population dispatch (fields: name, members, "
                         "active, epochs, continued)",
    "automl_search_start": "SearchEngine.run() entered (fields: name, "
                           "trials, executor, scheduler)",
    "automl_search_trial": "one search trial finished (fields: name, "
                           "index, ok, reward, rung)",
    "automl_search_stop": "a search ended (fields: name, reason, "
                          "trials, failed, total_epochs)",
    # learn lifecycle
    "train_start": "estimator fit() entered (fields: epochs, "
                   "batch_size)",
    "train_stop": "estimator fit() returned (fields: epochs_run)",
    "train_failure": "mid-epoch training failure being retried "
                     "(fields: error, failures)",
    # obs / process lifecycle
    "reporter_final": "rollup reporter flushed its final report at "
                      "shutdown",
    "uncaught_exception": "sys/threading excepthook fired "
                          "(fields: error, thread)",
    "fatal_signal": "fatal signal hook fired (fields: signum)",
    "postmortem_written": "a postmortem bundle was written "
                          "(fields: path, reason)",
    "flight_installed": "flight recorder hooks installed",
}

_M_EVENTS = get_registry().counter(
    "zoo_obs_events_total", "Structured events emitted, by type",
    labelnames=("type",))
_M_STORMS = get_registry().counter(
    "zoo_obs_recompile_storms_total",
    "Recompile storms detected (one fn crossing the distinct-shape "
    "threshold inside the sliding window)")


def register_event_type(name: str, description: str) -> None:
    """Extend the vocabulary (plugins/tests). Names must be
    lower_snake_case; re-registering an existing name with a different
    description raises -- one type, one meaning."""
    if not EVENT_TYPE_RE.match(name):
        raise ValueError(
            f"event type {name!r} is not lower_snake_case")
    existing = EVENT_TYPES.get(name)
    if existing is not None and existing != description:
        raise ValueError(f"event type {name!r} already registered: "
                         f"{existing!r}")
    EVENT_TYPES[name] = description


def check_event_type(name: str) -> None:
    """Raise ValueError unless ``name`` is lower_snake_case and
    registered in :data:`EVENT_TYPES` (the test_metric_names lint calls
    this for every literal ``emit("...")`` in the package)."""
    if not EVENT_TYPE_RE.match(name):
        raise ValueError(f"event type {name!r} is not lower_snake_case")
    if name not in EVENT_TYPES:
        raise ValueError(
            f"event type {name!r} is not registered in "
            "obs.events.EVENT_TYPES (the one event vocabulary module)")


def to_jsonable(v: Any) -> Any:
    """Best-effort scalar coercion for event fields (numpy scalars,
    tuples of shapes, exceptions) so JSON rendering never raises --
    shared by the jsonl renderer, the postmortem dumper, and the
    /debug/events endpoint."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [to_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): to_jsonable(x) for k, x in v.items()}
    item = getattr(v, "item", None)
    if item is not None:
        try:
            return item()
        except Exception as e:
            # non-scalar .item() (size != 1 array) or a lazy backend
            # refusing the sync: fall through to str(), but leave a
            # trace -- a coercion path that fails silently hides the
            # exact field the postmortem reader needed
            logger.debug("to_jsonable: .item() on %s failed: %s",
                         type(v).__name__, e)
    return str(v)


class EventLog:
    """Bounded ring of structured events.

    An event is ``{"ts": epoch_seconds, "seq": n, "type": ...,
    "subsystem": ...}`` plus a ``fields`` dict when the emitter passed
    any. ``max_events`` bounds memory (``zoo.obs.events.max_events``);
    older events fall off -- like the span ring, this is a flight
    recorder, not an archive. emit() is the only hot-ish operation and
    does no I/O and no string formatting."""

    def __init__(self, max_events: Optional[int] = None):
        if max_events is None:
            max_events = int(get_config().get(
                "zoo.obs.events.max_events", 2048))
        self._ring: collections.deque = collections.deque(
            maxlen=max_events)
        self._lock = threading.Lock()
        self._seq = 0

    def emit(self, type: str, subsystem: str, **fields) -> Dict[str, Any]:
        check_event_type(type)
        ev: Dict[str, Any] = {"ts": time.time(), "type": type,
                              "subsystem": subsystem}
        if fields:
            ev["fields"] = fields
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        _M_EVENTS.labels(type=type).inc()
        return ev

    # ------------------------------------------------------------ read --
    def tail(self, n: Optional[int] = None, type: Optional[str] = None,
             subsystem: Optional[str] = None) -> List[Dict[str, Any]]:
        """The newest events, oldest-first; filter before truncation so
        ``tail(5, type="compile")`` means the last 5 compiles, not
        compiles among the last 5 events."""
        with self._lock:
            out = list(self._ring)
        if type is not None:
            out = [e for e in out if e["type"] == type]
        if subsystem is not None:
            out = [e for e in out if e["subsystem"] == subsystem]
        if n is not None:
            n = int(n)
            # guard the falsy-zero slice: out[-0:] is the WHOLE list
            out = out[-n:] if n > 0 else []
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ---------------------------------------------------------- render --
    @staticmethod
    def render_jsonl(events: List[Dict[str, Any]]) -> str:
        """One JSON object per line (the postmortem bundle format);
        unserializable field values stringify rather than raise."""
        return "\n".join(
            json.dumps(to_jsonable(e), sort_keys=True) for e in events)

    def to_jsonl(self, n: Optional[int] = None, **filters) -> str:
        return self.render_jsonl(self.tail(n, **filters))


# ------------------------------------------------------------------ #
# recompile-storm detection                                           #
# ------------------------------------------------------------------ #
_warming_state = threading.local()


@contextlib.contextmanager
def warming():
    """Mark this thread's compiles as *intentional* (warm-up walking a
    bucket ladder): ``record_compile`` still logs them (``warm: true``)
    but the storm detector ignores them. Process-level by design --
    every compile boundary the warm-up traces through (InferenceModel's
    bucket cache, a GraphFunction's feed signatures, nested jits)
    inherits the flag without each site threading its own."""
    prev = getattr(_warming_state, "active", False)
    _warming_state.active = True
    try:
        yield
    finally:
        _warming_state.active = prev


def is_warming() -> bool:
    return getattr(_warming_state, "active", False)


def shape_signature(x) -> Tuple:
    """(shape, dtype) per leaf of a pytree -- the compile key compile
    events carry. Imports jax lazily so the module stays importable
    from jax-free processes."""
    import jax

    return tuple((tuple(getattr(l, "shape", ()) or ()),
                  str(getattr(l, "dtype", "")))
                 for l in jax.tree_util.tree_leaves(x))


def _shape_str(shapes: Any) -> str:
    """Compact printable form of a shape signature for event fields:
    ``(8,224,224,3):uint8|(8,):int32``."""
    try:
        return "|".join(
            "(" + ",".join(str(d) for d in s) + "):" + (dt or "?")
            for s, dt in shapes)
    except Exception:
        return str(shapes)


class RecompileDetector:
    """Sliding-window distinct-shape tracker per compiled fn.

    Every reported compile is remembered as ``(t, shape_str)``; when one
    fn accumulates >= ``threshold`` *distinct* shapes inside
    ``window_s`` seconds, a ``recompile_storm`` warning event is
    emitted (at most once per window per fn -- the detector must not
    itself storm) and ``zoo_obs_recompile_storms_total`` increments.
    """

    def __init__(self, window_s: Optional[float] = None,
                 threshold: Optional[int] = None,
                 log: Optional["EventLog"] = None):
        cfg = get_config()
        self.window_s = float(cfg.get("zoo.obs.recompile.window_s", 60.0)
                              if window_s is None else window_s)
        self.threshold = int(cfg.get("zoo.obs.recompile.threshold", 8)
                             if threshold is None else threshold)
        self._log = log
        self._lock = threading.Lock()
        self._by_fn: Dict[str, collections.deque] = {}
        self._last_warn: Dict[str, float] = {}

    def record_compile(self, fn: str, shapes: Any = None,
                       wall_s: float = 0.0,
                       subsystem: str = "inference",
                       warm: bool = False) -> bool:
        """Log one compile event and update the storm window; returns
        True when this compile tipped fn over the threshold.

        ``warm=True`` (or an enclosing :func:`warming` context) marks
        an *intentional* compile (warm_up walking the bucket ladder
        pre-compiles every power-of-two shape in seconds): logged as a
        ``compile`` event but excluded from the storm window --
        otherwise every healthy deployment launch would cry storm and
        teach operators to ignore the signal."""
        warm = warm or is_warming()
        now = time.monotonic()
        shape_s = _shape_str(shapes) if shapes is not None else ""
        # explicit None check: an EMPTY EventLog is falsy (__len__),
        # and `or` would silently reroute a dedicated log's events to
        # the global one
        log = self._log if self._log is not None else get_event_log()
        log.emit("compile", subsystem, fn=fn, shapes=shape_s,
                 wall_s=round(float(wall_s), 6), warm=bool(warm))
        if warm:
            return False
        with self._lock:
            ring = self._by_fn.get(fn)
            if ring is None:
                ring = self._by_fn[fn] = collections.deque()
            ring.append((now, shape_s))
            cutoff = now - self.window_s
            while ring and ring[0][0] < cutoff:
                ring.popleft()
            distinct = {s for _, s in ring}
            stormy = len(distinct) >= self.threshold
            if stormy and now - self._last_warn.get(fn, -1e18) \
                    < self.window_s:
                return False  # already warned for this window
            if stormy:
                self._last_warn[fn] = now
                sample = sorted(distinct)[:8]
        if not stormy:
            return False
        _M_STORMS.inc()
        log.emit("recompile_storm", subsystem, fn=fn,
                 distinct=len(distinct), window_s=self.window_s,
                 shapes=sample)
        logger.warning(
            "recompile storm: %s compiled %d distinct shapes inside "
            "%.0fs -- requests are paying XLA compile stalls; check "
            "input bucketing (e.g. %s)", fn, len(distinct),
            self.window_s, "; ".join(sample[:3]))
        return True

    def reset(self) -> None:
        with self._lock:
            self._by_fn.clear()
            self._last_warn.clear()


def instrument_compiles(fn, name: str, subsystem: str = "learn"):
    """Wrap a jitted callable so each call that triggers a trace +
    compile is timed and reported (jax compiles lazily at first call
    per signature, so that call's wall time ~= the compile stall).

    The hot path must stay hot: a jit fn exposes its signature-cache
    size, so compile detection is one int compare per call -- no
    pytree walk over a 100M-param variables tree per training step.
    The expensive ``shape_signature`` runs only on the calls that
    actually compiled. Non-jit callables (tests, duck-typed models)
    fall back to a seen-signature set."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        seen: set = set()
        lock = threading.Lock()

        def wrapper(*args, **kwargs):
            key = shape_signature((args,
                                   tuple(sorted(kwargs.items()))))
            with lock:
                new = key not in seen
                if new:
                    seen.add(key)
            if not new:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            record_compile(name, key, time.perf_counter() - t0,
                           subsystem=subsystem)
            return out
    else:
        def wrapper(*args, **kwargs):
            try:
                before = probe()
            except Exception:
                before = -1
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if before >= 0:
                try:
                    compiled = probe() > before
                except Exception:
                    compiled = False
                if compiled:
                    record_compile(
                        name,
                        shape_signature(
                            (args, tuple(sorted(kwargs.items())))),
                        time.perf_counter() - t0,
                        subsystem=subsystem)
            return out

    wrapper.__name__ = getattr(fn, "__name__", name)
    wrapper.__wrapped__ = fn
    return wrapper


# ------------------------------------------------------------------ #
# process-wide singletons                                             #
# ------------------------------------------------------------------ #
_global_log: Optional[EventLog] = None
_global_detector: Optional[RecompileDetector] = None
_singleton_lock = threading.Lock()


def get_event_log() -> EventLog:
    """The process-wide event log every subsystem emits into (tail
    served at ``GET /debug/events``; last N land in postmortems)."""
    global _global_log
    with _singleton_lock:
        if _global_log is None:
            _global_log = EventLog()
        return _global_log


def get_recompile_detector() -> RecompileDetector:
    global _global_detector
    with _singleton_lock:
        if _global_detector is None:
            _global_detector = RecompileDetector()
        return _global_detector


def emit(type: str, subsystem: str, **fields) -> Dict[str, Any]:
    """Module-level convenience: emit into the process-wide log."""
    return get_event_log().emit(type, subsystem, **fields)


def record_compile(fn: str, shapes: Any = None, wall_s: float = 0.0,
                   subsystem: str = "inference",
                   warm: bool = False) -> bool:
    """Module-level convenience: report a compile to the process-wide
    detector (which also emits the ``compile`` event)."""
    return get_recompile_detector().record_compile(
        fn, shapes=shapes, wall_s=wall_s, subsystem=subsystem,
        warm=warm)
