/* Native host-side kernels for the data/observability hot paths.
 *
 * The reference keeps its native code in external zoo-core artifacts
 * (MKL kernels, PMEM allocator -- SURVEY.md section 2.4); the TPU
 * rebuild's device math lives in XLA/Pallas, so the remaining native
 * surface is host-side IO: TFRecord frame scanning for the data loader
 * (ref: TFRecord framing used by tfpark datasets) and the masked
 * crc32c that both TFRecord and the TensorBoard event writer frame
 * records with (ref: zoo/.../tensorboard/EventWriter.scala:32-80).
 *
 * Built at first use via `cc -O3 -shared -fPIC` (see native/__init__.py)
 * and bound with ctypes; everything has a pure-Python fallback.
 */

#include <stddef.h>
#include <stdint.h>

/* ----------------------------- crc32c (Castagnoli), slicing-by-8 ---- */

static uint32_t crc_table[8][256];

/* Filled once at library load (constructor) -- lazy init guarded by a
 * plain flag was a C data race when the event-writer thread and data
 * loader threads both hit the first call concurrently. */
static void init_table(void) __attribute__((constructor));

static void init_table(void) {
    uint32_t poly = 0x82F63B78u; /* reflected 0x1EDC6F41 */
    for (int i = 0; i < 256; i++) {
        uint32_t crc = (uint32_t)i;
        for (int j = 0; j < 8; j++)
            crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
        crc_table[0][i] = crc;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t crc = crc_table[0][i];
        for (int s = 1; s < 8; s++) {
            crc = crc_table[0][crc & 0xFF] ^ (crc >> 8);
            crc_table[s][i] = crc;
        }
    }
}

uint32_t zoo_crc32c(const uint8_t *buf, size_t len) {
    uint32_t crc = 0xFFFFFFFFu;
    while (len >= 8) {
        crc ^= (uint32_t)buf[0] | ((uint32_t)buf[1] << 8) |
               ((uint32_t)buf[2] << 16) | ((uint32_t)buf[3] << 24);
        uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8) |
                      ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
        crc = crc_table[7][crc & 0xFF] ^ crc_table[6][(crc >> 8) & 0xFF] ^
              crc_table[5][(crc >> 16) & 0xFF] ^
              crc_table[4][(crc >> 24) & 0xFF] ^
              crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
              crc_table[1][(hi >> 16) & 0xFF] ^
              crc_table[0][(hi >> 24) & 0xFF];
        buf += 8;
        len -= 8;
    }
    while (len--) crc = crc_table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

static uint32_t masked(uint32_t crc) {
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

/* ------------------------------------- TFRecord frame scanning ------ */
/* Record: u64le length | u32le masked_crc(length) | payload
 *         | u32le masked_crc(payload)
 * Fills offsets/lengths (payload positions) up to max_records.
 * Returns the number of records found; negative on corruption when
 * verify != 0 (-(index+1) of the bad record). */

int64_t zoo_scan_tfrecords(const uint8_t *buf, uint64_t n,
                           uint64_t *offsets, uint64_t *lengths,
                           uint64_t max_records, int verify) {
    uint64_t pos = 0, count = 0;
    while (n - pos >= 16 && count < max_records) {
        uint64_t len = 0;
        for (int i = 0; i < 8; i++) len |= (uint64_t)buf[pos + i] << (8 * i);
        /* subtraction form: an addition like pos+12+len+4 could wrap
         * modulo 2^64 for a corrupt length and pass the bound check */
        if (len > n - pos - 16) break; /* truncated or corrupt tail */
        if (verify) {
            uint32_t lc = (uint32_t)buf[pos + 8] |
                          ((uint32_t)buf[pos + 9] << 8) |
                          ((uint32_t)buf[pos + 10] << 16) |
                          ((uint32_t)buf[pos + 11] << 24);
            if (masked(zoo_crc32c(buf + pos, 8)) != lc)
                return -((int64_t)count + 1);
            const uint8_t *payload = buf + pos + 12;
            uint32_t pc = (uint32_t)payload[len] |
                          ((uint32_t)payload[len + 1] << 8) |
                          ((uint32_t)payload[len + 2] << 16) |
                          ((uint32_t)payload[len + 3] << 24);
            if (masked(zoo_crc32c(payload, len)) != pc)
                return -((int64_t)count + 1);
        }
        offsets[count] = pos + 12;
        lengths[count] = len;
        count++;
        pos += 12 + len + 4;
    }
    return (int64_t)count;
}
