"""Native host-side kernels: build-on-first-use C library + ctypes.

The compute path is XLA/Pallas; this is the native RUNTIME surface the
reference keeps in its zoo-core artifacts (SURVEY.md section 2.4) --
host-side IO hot loops. ``cc -O3`` compiles ``zoo_native.c`` into a
per-user 0700 cache keyed by source hash; every entry point has a
pure-Python fallback, so the framework works without a compiler.

API:
- ``available() -> bool``     (blocks for the one-time build)
- ``ready() -> bool``         (non-blocking; kicks the build off in the
  background -- hot paths use this so the first call never stalls)
- ``crc32c(data: bytes) -> int``           (Castagnoli, slicing-by-8)
- ``scan_tfrecords(buf, verify=False) -> list[(offset, length)]``
  (``buf``: bytes or any writable buffer, e.g. an ACCESS_COPY mmap)
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import stat
import subprocess
import threading
from typing import List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "zoo_native.c")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_thread: Optional[threading.Thread] = None
_done = threading.Event()


def _cache_dir() -> str:
    base = os.environ.get("ZOO_NATIVE_CACHE")
    if base is None:
        base = os.path.join(
            os.environ.get("XDG_CACHE_HOME",
                           os.path.expanduser("~/.cache")),
            "analytics_zoo_tpu")
    os.makedirs(base, mode=0o700, exist_ok=True)
    os.chmod(base, 0o700)
    return base


def _build_and_load() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    cache = _cache_dir()
    so_path = os.path.join(cache, f"zoo_native_{tag}.so")
    if not os.path.isfile(so_path):
        tmp = so_path + f".build{os.getpid()}"
        for cc in ("cc", "gcc", "clang"):
            try:
                r = subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    capture_output=True, timeout=120)
            except (FileNotFoundError, subprocess.TimeoutExpired):
                continue
            if r.returncode == 0:
                os.replace(tmp, so_path)
                break
        else:
            return None
    # refuse to load a library this user doesn't own (the cache dir is
    # 0700, but ZOO_NATIVE_CACHE may point anywhere)
    st = os.stat(so_path)
    if st.st_uid != os.getuid() or (st.st_mode & stat.S_IWOTH):
        return None
    lib = ctypes.CDLL(so_path)
    lib.zoo_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.zoo_crc32c.restype = ctypes.c_uint32
    lib.zoo_scan_tfrecords.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64, ctypes.c_int]
    lib.zoo_scan_tfrecords.restype = ctypes.c_int64
    return lib


def _builder() -> None:
    global _lib
    try:
        _lib = _build_and_load()
    except Exception:
        _lib = None
    finally:
        _done.set()


def _kick() -> None:
    global _build_thread
    with _lock:
        if _build_thread is None:
            _build_thread = threading.Thread(target=_builder,
                                             daemon=True)
            _build_thread.start()


def ready() -> bool:
    """Non-blocking: True once the native library is loaded. The first
    call starts the build in the background; hot paths (event writer)
    use the Python fallback until it completes."""
    _kick()
    return _done.is_set() and _lib is not None


def available() -> bool:
    """Blocking: waits for the one-time build, then reports it."""
    _kick()
    _done.wait()
    return _lib is not None


def _as_ptr(buf):
    """(void*, keepalive) for bytes or any buffer-protocol object."""
    if isinstance(buf, (bytes, bytearray)):
        keep = ctypes.create_string_buffer(bytes(buf), len(buf)) \
            if isinstance(buf, bytearray) else buf
        return ctypes.cast(ctypes.c_char_p(keep), ctypes.c_void_p), keep
    view = (ctypes.c_ubyte * len(buf)).from_buffer(buf)
    return ctypes.cast(view, ctypes.c_void_p), view


def crc32c(data: bytes) -> int:
    if available():
        ptr, keep = _as_ptr(data)
        out = int(_lib.zoo_crc32c(ptr, len(data)))
        del keep
        return out
    from analytics_zoo_tpu.utils.summary import crc32c as py_crc32c

    return py_crc32c(data)


def crc32c_if_ready(data: bytes) -> Optional[int]:
    """Native crc32c when the library is ready, else None (caller uses
    its Python path) -- never blocks on the build."""
    if not ready():
        return None
    ptr, keep = _as_ptr(data)
    out = int(_lib.zoo_crc32c(ptr, len(data)))
    del keep
    return out


class CorruptRecordError(ValueError):
    pass


# per-pass entry cap: two u64 arrays at 64Ki entries = 1 MB resident,
# independent of shard size (a worst-case cap of len(buf)//16 would
# allocate host memory on the order of the file itself for multi-GB
# shards, defeating the mmap'd O(1)-resident scan)
_SCAN_CAP = 65536


def scan_tfrecords(buf, verify: bool = False) -> List[Tuple[int, int]]:
    """All (payload_offset, payload_length) frames in a TFRecord
    buffer. ``verify=True`` checks both masked CRCs per record and
    raises CorruptRecordError naming the first bad record. Scans in
    fixed-size passes (bounded host allocation), resuming after the
    last complete record of each pass."""
    if not available():
        return _py_scan(buf, verify)
    n = len(buf)
    ptr, keep = _as_ptr(buf)
    out: List[Tuple[int, int]] = []
    try:
        cap = min(max(n // 16, 1), _SCAN_CAP)
        offs = (ctypes.c_uint64 * cap)()
        lens = (ctypes.c_uint64 * cap)()
        base = ctypes.cast(ptr, ctypes.c_void_p).value
        pos = 0
        while pos < n:
            got = _lib.zoo_scan_tfrecords(
                ctypes.c_void_p(base + pos), n - pos, offs, lens, cap,
                1 if verify else 0)
            if got < 0:
                raise CorruptRecordError(
                    f"record {len(out) + (-got - 1)} failed crc check")
            for i in range(got):
                out.append((pos + int(offs[i]), int(lens[i])))
            if got < cap:
                break  # tail reached (or trailing partial record)
            last_off, last_len = out[-1]
            pos = last_off + last_len + 4  # skip trailing payload crc
    finally:
        was_view = not isinstance(buf, (bytes, bytearray))
        del ptr, keep
        if was_view:
            # ctypes' buffer export is released at GC, not refcount
            # drop; collect now so the caller's mmap can close
            import gc

            gc.collect()
    return out


def _py_scan(buf, verify: bool) -> List[Tuple[int, int]]:
    import struct

    from analytics_zoo_tpu.utils.summary import _masked_crc

    out: List[Tuple[int, int]] = []
    pos = 0
    n = len(buf)
    while n - pos >= 16:
        (length,) = struct.unpack_from("<Q", buf, pos)
        if length > n - pos - 16:
            break
        if verify:
            (lc,) = struct.unpack_from("<I", buf, pos + 8)
            (pc,) = struct.unpack_from("<I", buf, pos + 12 + length)
            if (_masked_crc(bytes(buf[pos:pos + 8])) != lc or
                    _masked_crc(bytes(buf[pos + 12:pos + 12 + length]))
                    != pc):
                raise CorruptRecordError(
                    f"record {len(out)} failed crc check")
        out.append((pos + 12, length))
        pos += 12 + length + 4
    return out
