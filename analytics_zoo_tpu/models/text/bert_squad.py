"""BERT-SQuAD: extractive question answering fine-tune workflow.

The analog of the TFPark BERT-SQuAD estimator (ref: pyzoo/zoo/tfpark/
text/estimator/bert_squad.py:78 -- BERT encoder + a dense span head
emitting start/end logits, trained with mean start/end cross-entropy;
model_fn pattern in bert_base.py:115-134). North-star workload #4.

TPU notes: the encoder runs through the flash-attention dispatch (no
[L, L] score matrix in HBM); pass ``dtype="bfloat16"`` to keep the MXU
on its native precision (params stay fp32).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.layers.transformer import BERTModule
from analytics_zoo_tpu.models.common import ZooModel, register_model


def squad_span_loss(preds, labels):
    """Mean of start/end cross-entropies (ref: bert_squad.py loss).

    preds: (start_logits [B, L], end_logits [B, L]);
    labels: [B, 2] int (start, end) positions.
    """
    start_logits, end_logits = preds
    labels = labels.astype(jnp.int32)
    start_ll = jax.nn.log_softmax(start_logits.astype(jnp.float32), -1)
    end_ll = jax.nn.log_softmax(end_logits.astype(jnp.float32), -1)
    b = start_logits.shape[0]
    rows = jnp.arange(b)
    start_loss = -start_ll[rows, labels[:, 0]]
    end_loss = -end_ll[rows, labels[:, 1]]
    return jnp.mean((start_loss + end_loss) / 2.0)


class BERTForSQuAD(nn.Module):
    """BERT encoder + span head -> (start_logits, end_logits).

    The encoder+head wiring is the shared ``_BERTHeadModule``
    (per-token, 2 classes); this wrapper only splits the [B, L, 2]
    logits into the (start, end) pair the SQuAD loss consumes."""

    vocab: int
    hidden_size: int = 768
    n_block: int = 12
    n_head: int = 12
    intermediate_size: int = 3072
    max_position_len: int = 512
    hidden_dropout: float = 0.1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        from analytics_zoo_tpu.models.text.bert_estimators import (
            _BERTHeadModule)

        logits = _BERTHeadModule(
            vocab=self.vocab, num_classes=2, per_token=True,
            hidden_size=self.hidden_size, n_block=self.n_block,
            n_head=self.n_head,
            intermediate_size=self.intermediate_size,
            max_position_len=self.max_position_len,
            hidden_dropout=self.hidden_dropout, dtype=self.dtype,
            name="squad")(x, train=train)
        start, end = jnp.split(logits, 2, axis=-1)
        return start.squeeze(-1), end.squeeze(-1)


@register_model
class BERTSQuAD(ZooModel):
    """(ref: bert_squad.py BERTSQuADEstimator). fit expects
    x = {"input_ids", optional "token_type_ids"/"attention_mask"},
    y = [B, 2] (start, end) positions; predict returns span logits."""

    default_loss = staticmethod(squad_span_loss)
    default_optimizer = "adam"
    default_metrics = ()

    def __init__(self, vocab: int, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12,
                 intermediate_size: int = 3072,
                 max_position_len: int = 512,
                 hidden_dropout: float = 0.1, dtype: str = "float32"):
        super().__init__(vocab=vocab, hidden_size=hidden_size,
                         n_block=n_block, n_head=n_head,
                         intermediate_size=intermediate_size,
                         max_position_len=max_position_len,
                         hidden_dropout=hidden_dropout, dtype=dtype)

    def _build_module(self):
        c = self._config
        return BERTForSQuAD(
            vocab=c["vocab"], hidden_size=c["hidden_size"],
            n_block=c["n_block"], n_head=c["n_head"],
            intermediate_size=c["intermediate_size"],
            max_position_len=c["max_position_len"],
            hidden_dropout=c["hidden_dropout"],
            dtype=jnp.dtype(c["dtype"]))

    def _example_input(self):
        return {"input_ids": np.zeros((1, 16), np.int32)}

    @staticmethod
    def decode_spans(start_logits, end_logits,
                     max_answer_len: int = 30) -> np.ndarray:
        """Best (start, end) span per sample with end >= start and
        length <= max_answer_len (ref: squad postprocessing)."""
        start_logits = np.asarray(start_logits)
        end_logits = np.asarray(end_logits)
        b, l = start_logits.shape
        out = np.zeros((b, 2), np.int32)
        for i in range(b):
            scores = start_logits[i][:, None] + end_logits[i][None, :]
            valid = np.triu(np.ones((l, l), bool))
            valid &= ~np.triu(np.ones((l, l), bool), k=max_answer_len)
            scores = np.where(valid, scores, -np.inf)
            flat = int(np.argmax(scores))
            out[i] = divmod(flat, l)
        return out
