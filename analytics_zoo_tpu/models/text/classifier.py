"""Text classification model.

The analog of ``TextClassifier`` (ref: zoo/.../models/textclassification/
TextClassifier.scala, pyzoo/zoo/models/textclassification): token-id
sequences -> embedding (optionally pretrained/frozen) -> CNN / LSTM / GRU
encoder -> dense -> class logits.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model


class TextClassifierNet(nn.Module):
    class_num: int
    vocab: int
    embed_dim: int
    encoder: str = "cnn"
    encoder_output_dim: int = 256
    sequence_length: int = 500

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab + 1, self.embed_dim,
                     name="embedding")(x.astype(jnp.int32))
        if self.encoder == "cnn":
            h = nn.relu(nn.Conv(self.encoder_output_dim, (5,),
                                name="conv")(h))
            h = jnp.max(h, axis=1)  # global max pool over time
        elif self.encoder == "lstm":
            h = nn.RNN(nn.OptimizedLSTMCell(self.encoder_output_dim),
                       name="lstm")(h)[:, -1]
        elif self.encoder == "gru":
            h = nn.RNN(nn.GRUCell(self.encoder_output_dim),
                       name="gru")(h)[:, -1]
        else:
            raise ValueError(f"unknown encoder {self.encoder!r}")
        h = nn.Dropout(0.2, deterministic=not train)(h)
        h = nn.relu(nn.Dense(128, name="fc")(h))
        return nn.Dense(self.class_num, name="head")(h)


@register_model
class TextClassifier(ZooModel):
    """(ref: TextClassifier.scala). Labels are 0-based class ids."""

    default_loss = "sparse_categorical_crossentropy"
    default_optimizer = "adam"
    default_metrics = ("accuracy",)

    def __init__(self, class_num: int, vocab: int = 20000,
                 embed_dim: int = 200, sequence_length: int = 500,
                 encoder: str = "cnn", encoder_output_dim: int = 256):
        super().__init__(class_num=class_num, vocab=vocab,
                         embed_dim=embed_dim,
                         sequence_length=sequence_length, encoder=encoder,
                         encoder_output_dim=encoder_output_dim)

    def _build_module(self):
        c = self._config
        return TextClassifierNet(
            class_num=c["class_num"], vocab=c["vocab"],
            embed_dim=c["embed_dim"], encoder=c["encoder"],
            encoder_output_dim=c["encoder_output_dim"],
            sequence_length=c["sequence_length"])

    def _example_input(self):
        return np.ones((1, self._config["sequence_length"]), np.int32)
