"""Text models (ref: zoo/.../models/{textclassification,textmatching})."""

from analytics_zoo_tpu.models.text.classifier import (  # noqa: F401
    TextClassifier,
)
from analytics_zoo_tpu.models.text.knrm import KNRM  # noqa: F401
from analytics_zoo_tpu.models.text.bert_estimators import (  # noqa: F401
    BERTClassifier,
    BERTNER,
)
from analytics_zoo_tpu.models.text.bert_squad import (  # noqa: F401
    BERTSQuAD,
)
