"""BERT fine-tune estimators: sequence classification and NER.

The analog of the TFPark BERT estimator family
(ref: pyzoo/zoo/tfpark/text/estimator/bert_classifier.py -- pooled
[CLS] -> dense classes; bert_ner.py -- per-token dense tags; both built
on the model_fn pattern of bert_base.py:115-134; the SQuAD sibling
lives in bert_squad.py). Same flash-attention encoder and bf16 story
as BERTSQuAD.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.layers.transformer import BERTModule
from analytics_zoo_tpu.models.common import ZooModel, register_model


class _BERTHeadModule(nn.Module):
    """BERT encoder + a classification head: pooled [CLS] (sequence
    tasks) or every token (NER)."""

    vocab: int
    num_classes: int
    per_token: bool
    hidden_size: int = 768
    n_block: int = 12
    n_head: int = 12
    intermediate_size: int = 3072
    max_position_len: int = 512
    hidden_dropout: float = 0.1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        seq, pooled = BERTModule(
            vocab=self.vocab, hidden_size=self.hidden_size,
            n_block=self.n_block, n_head=self.n_head,
            intermediate_size=self.intermediate_size,
            max_position_len=self.max_position_len,
            hidden_dropout=self.hidden_dropout, attn_dropout=0.0,
            dtype=self.dtype, name="bert")(x, train=train)
        h = seq if self.per_token else pooled
        h = nn.Dropout(self.hidden_dropout,
                       deterministic=not train)(h)
        return nn.Dense(self.num_classes, name="head")(
            h.astype(jnp.float32))


class _BERTEstimatorBase(ZooModel):
    default_loss = "sparse_categorical_crossentropy"
    default_optimizer = "adam"
    default_metrics = ("accuracy",)
    per_token = False

    def __init__(self, num_classes: int, vocab: int,
                 hidden_size: int = 768, n_block: int = 12,
                 n_head: int = 12, intermediate_size: int = 3072,
                 max_position_len: int = 512,
                 hidden_dropout: float = 0.1, dtype: str = "float32"):
        super().__init__(num_classes=num_classes, vocab=vocab,
                         hidden_size=hidden_size, n_block=n_block,
                         n_head=n_head,
                         intermediate_size=intermediate_size,
                         max_position_len=max_position_len,
                         hidden_dropout=hidden_dropout, dtype=dtype)

    def _build_module(self):
        c = self._config
        return _BERTHeadModule(
            vocab=c["vocab"], num_classes=c["num_classes"],
            per_token=self.per_token, hidden_size=c["hidden_size"],
            n_block=c["n_block"], n_head=c["n_head"],
            intermediate_size=c["intermediate_size"],
            max_position_len=c["max_position_len"],
            hidden_dropout=c["hidden_dropout"],
            dtype=jnp.dtype(c["dtype"]))

    def _example_input(self):
        return {"input_ids": np.zeros((1, 16), np.int32)}


@register_model
class BERTClassifier(_BERTEstimatorBase):
    """Sequence classification over the pooled [CLS]
    (ref: bert_classifier.py BERTClassifier). fit expects
    x = {"input_ids", optional "token_type_ids"/"attention_mask"},
    y = [B] int class ids."""

    per_token = False


IGNORE_INDEX = -1


def token_cross_entropy(preds, labels):
    """Per-token mean CE: preds [B, L, C] logits, labels [B, L] ids.
    Positions labelled ``IGNORE_INDEX`` (-1) -- padding -- contribute
    nothing to the loss."""
    import jax

    c = preds.shape[-1]
    logp = jax.nn.log_softmax(
        preds.astype(jnp.float32).reshape(-1, c), -1)
    ids = jnp.asarray(labels).reshape(-1).astype(jnp.int32)
    keep = (ids != IGNORE_INDEX).astype(jnp.float32)
    safe = jnp.maximum(ids, 0)
    nll = -jnp.take_along_axis(logp, safe[:, None], -1)[:, 0]
    return jnp.sum(nll * keep) / jnp.maximum(jnp.sum(keep), 1.0)


@register_model
class BERTNER(_BERTEstimatorBase):
    """Token-level tagging (ref: bert_ner.py BERTNER). fit expects
    y = [B, L] int tag ids, with padding positions labelled
    ``IGNORE_INDEX`` (-1); predictions are [B, L, num_classes]
    logits."""

    per_token = True
    default_loss = staticmethod(token_cross_entropy)
    default_metrics = ()  # per-token; see token_accuracy

    @staticmethod
    def decode_tags(logits) -> np.ndarray:
        """[B, L, C] logits -> [B, L] argmax tag ids."""
        return np.argmax(np.asarray(logits), axis=-1)

    @staticmethod
    def token_accuracy(logits, labels) -> float:
        """Accuracy over real tokens only (labels == IGNORE_INDEX are
        padding and excluded)."""
        tags = BERTNER.decode_tags(logits)
        labels = np.asarray(labels)
        keep = labels != IGNORE_INDEX
        total = max(int(keep.sum()), 1)
        return float(((tags == labels) & keep).sum() / total)
