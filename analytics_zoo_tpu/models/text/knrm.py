"""KNRM: kernel-pooling neural ranking model.

The analog of ``KNRM`` (ref: zoo/.../models/textmatching/KNRM.scala,
pyzoo/zoo/models/textmatching/knrm.py; Xiong et al. 2017): query/doc token
ids -> shared embedding -> cosine translation matrix -> RBF kernel pooling
-> dense score. Used with rank_hinge loss on (pos, neg) pair batches for
ranking, or sigmoid BCE for classification.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model


class KNRMNet(nn.Module):
    text1_length: int
    text2_length: int
    vocab: int
    embed_dim: int
    kernel_num: int = 21
    sigma: float = 0.1
    exact_sigma: float = 0.001
    target_mode: str = "ranking"

    @nn.compact
    def __call__(self, x):
        # x: int32 [B, text1_length + text2_length] (query ++ doc,
        # matching the reference's concatenated input, KNRM.scala input),
        # or [B, 2, L1+L2] (pos, neg) pairs for ranking training -- pairs
        # must live inside one sample so epoch shuffling cannot split them
        ids = x.astype(jnp.int32)
        paired = ids.ndim == 3
        if paired:
            b, two, ll = ids.shape
            ids = ids.reshape(b * two, ll)
        q_ids = ids[:, :self.text1_length]
        d_ids = ids[:, self.text1_length:]
        emb = nn.Embed(self.vocab + 1, self.embed_dim, name="embedding")
        q = emb(q_ids)
        d = emb(d_ids)
        qn = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True),
                             1e-8)
        dn = d / jnp.maximum(jnp.linalg.norm(d, axis=-1, keepdims=True),
                             1e-8)
        # translation matrix [B, Lq, Ld]
        sim = jnp.einsum("bqe,bde->bqd", qn, dn)
        # RBF kernels: mus spread over [-1, 1], last kernel exact-match
        ks = self.kernel_num
        mus = jnp.asarray(
            [1.0 if i == ks - 1 else -1.0 + (2 * i + 1) / (ks - 1)
             for i in range(ks)], jnp.float32)
        sigmas = jnp.asarray(
            [self.exact_sigma if i == ks - 1 else self.sigma
             for i in range(ks)], jnp.float32)
        # [B, Lq, Ld, K]
        k = jnp.exp(-jnp.square(sim[..., None] - mus) /
                    (2 * jnp.square(sigmas)))
        # mask padding tokens (id 0)
        qmask = (q_ids > 0).astype(jnp.float32)[:, :, None, None]
        dmask = (d_ids > 0).astype(jnp.float32)[:, None, :, None]
        k = k * qmask * dmask
        # soft-TF: sum over doc, log, sum over query
        soft_tf = jnp.sum(k, axis=2)                       # [B, Lq, K]
        log_k = jnp.log(jnp.clip(soft_tf, 1e-10)) * 0.01
        log_k = log_k * qmask[:, :, 0]
        phi = jnp.sum(log_k, axis=1)                       # [B, K]
        score = nn.Dense(1, name="head")(phi)
        if self.target_mode == "classification":
            return jnp.concatenate([jnp.zeros_like(score), score], -1)
        if paired:
            return score.reshape(b, two)  # rank_hinge sees (pos, neg)
        return score


@register_model
class KNRM(ZooModel):
    """(ref: KNRM.scala). ``target_mode``: "ranking" (score head, use
    rank_hinge on pos/neg pairs) or "classification" (2-class logits)."""

    default_loss = "rank_hinge"
    default_optimizer = "adam"

    def __init__(self, text1_length: int, text2_length: int,
                 vocab: int = 20000, embed_dim: int = 50,
                 kernel_num: int = 21, sigma: float = 0.1,
                 exact_sigma: float = 0.001,
                 target_mode: str = "ranking"):
        if target_mode not in ("ranking", "classification"):
            raise ValueError(f"bad target_mode {target_mode!r}")
        if target_mode == "classification":
            self.default_loss = "sparse_categorical_crossentropy"
        super().__init__(text1_length=text1_length,
                         text2_length=text2_length, vocab=vocab,
                         embed_dim=embed_dim, kernel_num=kernel_num,
                         sigma=sigma, exact_sigma=exact_sigma,
                         target_mode=target_mode)

    def _build_module(self):
        c = self._config
        return KNRMNet(
            text1_length=c["text1_length"], text2_length=c["text2_length"],
            vocab=c["vocab"], embed_dim=c["embed_dim"],
            kernel_num=c["kernel_num"], sigma=c["sigma"],
            exact_sigma=c["exact_sigma"], target_mode=c["target_mode"])

    def _example_input(self):
        c = self._config
        return np.ones((1, c["text1_length"] + c["text2_length"]),
                       np.int32)

    # ------------------------------------------------- ranking metrics --
    def evaluate_ndcg(self, query_doc_ids, labels, k: int = 5,
                      batch_size: int = 256) -> float:
        """NDCG@k over grouped (query, [docs]) relations
        (ref: common/Ranker.scala evaluateNDCG). ``query_doc_ids`` is
        [N, L1+L2] with one row per (q, d) pair; ``labels`` is a list of
        per-query relevance lists aligned with contiguous row groups."""
        scores = np.asarray(self.predict(query_doc_ids,
                                         batch_size=batch_size)).reshape(-1)
        return float(np.mean([_ndcg(scores[lo:hi], rel, k)
                              for lo, hi, rel in _groups(labels)]))

    def evaluate_map(self, query_doc_ids, labels,
                     batch_size: int = 256) -> float:
        """(ref: common/Ranker.scala evaluateMAP)."""
        scores = np.asarray(self.predict(query_doc_ids,
                                         batch_size=batch_size)).reshape(-1)
        return float(np.mean([_ap(scores[lo:hi], rel)
                              for lo, hi, rel in _groups(labels)]))


def _groups(labels):
    lo = 0
    for rel in labels:
        hi = lo + len(rel)
        yield lo, hi, np.asarray(rel, np.float32)
        lo = hi


def _ndcg(scores, rel, k):
    order = np.argsort(-scores)[:k]
    gains = (2 ** rel[order] - 1) / np.log2(np.arange(2, len(order) + 2))
    ideal_order = np.argsort(-rel)[:k]
    ideal = (2 ** rel[ideal_order] - 1) / np.log2(
        np.arange(2, len(ideal_order) + 2))
    denom = ideal.sum()
    return gains.sum() / denom if denom > 0 else 0.0


def _ap(scores, rel):
    order = np.argsort(-scores)
    rel_sorted = rel[order] > 0
    if not rel_sorted.any():
        return 0.0
    precision = np.cumsum(rel_sorted) / np.arange(1, len(rel_sorted) + 1)
    return float((precision * rel_sorted).sum() / rel_sorted.sum())
