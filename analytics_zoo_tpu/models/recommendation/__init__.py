"""Recommendation models (ref: zoo/.../models/recommendation)."""

from analytics_zoo_tpu.models.recommendation.base import (  # noqa: F401
    Recommender,
    UserItemFeature,
    UserItemPrediction,
)
from analytics_zoo_tpu.models.recommendation.ncf import NeuralCF  # noqa: F401
from analytics_zoo_tpu.models.recommendation.wide_and_deep import (  # noqa: F401
    ColumnFeatureInfo,
    WideAndDeep,
)
from analytics_zoo_tpu.models.recommendation.session_recommender import (  # noqa: F401
    SessionRecommender,
)
