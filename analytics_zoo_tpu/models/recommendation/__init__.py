"""Recommendation models (ref: zoo/.../models/recommendation)."""

from analytics_zoo_tpu.models.recommendation.base import (  # noqa: F401
    Recommender,
    UserItemFeature,
    UserItemPrediction,
)
from analytics_zoo_tpu.models.recommendation.ncf import NeuralCF  # noqa: F401
