"""Neural Collaborative Filtering (NCF).

The analog of ``NeuralCF`` (ref: zoo/.../models/recommendation/
NeuralCF.scala:45 -- GMF + MLP dual-branch architecture;
pyzoo/zoo/models/recommendation/neuralcf.py) re-designed TPU-first:

- embeddings + MLP as one fused flax module executing on the MXU;
- embedding tables may be sharded over the mesh's "model" axis for
  tables too big to replicate (the reference replicates on every worker,
  SURVEY.md section 7 "hard parts: embedding-heavy recommenders");
- training goes through the single SPMD Estimator (the reference runs
  this model on BigDL's two-Spark-jobs-per-iteration allreduce).

North-star workload #1 (BASELINE.md: NCF on MovieLens-1M).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import register_model
from analytics_zoo_tpu.models.recommendation.base import Recommender


class NeuralCFNet(nn.Module):
    """Flax module: GMF (elementwise product of mf embeddings) + MLP
    (concat embeddings -> hidden stack), concatenated into class logits
    (ref: NeuralCF.scala:45-120 buildModel)."""

    user_count: int
    item_count: int
    class_num: int = 2
    user_embed: int = 20
    item_embed: int = 20
    hidden_layers: Tuple[int, ...] = (40, 20, 10)
    include_mf: bool = True
    mf_embed: int = 20

    @nn.compact
    def __call__(self, x):
        # x: int32 [B, 2] of 1-based (user, item) ids
        user, item = x[..., 0], x[..., 1]
        mlp_u = nn.Embed(self.user_count + 1, self.user_embed,
                         name="mlp_user_embed")(user)
        mlp_i = nn.Embed(self.item_count + 1, self.item_embed,
                         name="mlp_item_embed")(item)
        h = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for k, units in enumerate(self.hidden_layers):
            h = nn.relu(nn.Dense(units, name=f"mlp_dense_{k}")(h))
        if self.include_mf:
            mf_u = nn.Embed(self.user_count + 1, self.mf_embed,
                            name="mf_user_embed")(user)
            mf_i = nn.Embed(self.item_count + 1, self.mf_embed,
                            name="mf_item_embed")(item)
            h = jnp.concatenate([h, mf_u * mf_i], axis=-1)
        return nn.Dense(self.class_num, name="head")(h)


@register_model
class NeuralCF(Recommender):
    """NCF recommender (ref: NeuralCF.scala:45, neuralcf.py).

    Labels are 1-based ratings in ``[1, class_num]`` (matching the
    reference's MovieLens explicit-feedback convention); internally
    shifted to 0-based classes.
    """

    default_loss = staticmethod(
        lambda preds, labels: _shifted_ce(preds, labels))
    default_optimizer = "adam"

    @property
    def default_metrics(self):
        return (_RatingAccuracy(),)

    def __init__(self, user_count: int, item_count: int, class_num: int = 2,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        super().__init__(user_count=user_count, item_count=item_count,
                         class_num=class_num, user_embed=user_embed,
                         item_embed=item_embed,
                         hidden_layers=list(hidden_layers),
                         include_mf=include_mf, mf_embed=mf_embed)

    def _build_module(self):
        c = self._config
        return NeuralCFNet(
            user_count=c["user_count"], item_count=c["item_count"],
            class_num=c["class_num"], user_embed=c["user_embed"],
            item_embed=c["item_embed"],
            hidden_layers=tuple(c["hidden_layers"]),
            include_mf=c["include_mf"], mf_embed=c["mf_embed"])

    def _example_input(self):
        return np.ones((1, 2), np.int32)


def _shifted_ce(preds, labels):
    """Cross entropy with 1-based rating labels."""
    from analytics_zoo_tpu.learn.objectives import (
        sparse_categorical_crossentropy)

    labels = jnp.asarray(labels).reshape(-1).astype(jnp.int32) - 1
    return sparse_categorical_crossentropy(preds, labels)


from analytics_zoo_tpu.learn.metrics import Metric


class _RatingAccuracy(Metric):
    """Accuracy against 1-based rating labels."""

    name = "accuracy"
    greater_is_better = True

    def __init__(self):
        from analytics_zoo_tpu.learn.metrics import Accuracy

        self._inner = Accuracy()

    def empty(self):
        return self._inner.empty()

    def update(self, state, preds, labels, weights=None):
        labels = jnp.asarray(labels).astype(jnp.int32) - 1
        return self._inner.update(state, preds, labels, weights)

    def result(self, state):
        return self._inner.result(state)
