"""Wide & Deep recommender.

The analog of ``WideAndDeep`` (ref: zoo/.../models/recommendation/
WideAndDeep.scala:101, pyzoo/zoo/models/recommendation/wide_and_deep.py):
a linear "wide" path over sparse crossed features + a "deep" MLP over
embeddings/indicators/continuous features. North-star workload #2
(BASELINE.md: wide_n_deep.ipynb).

Feature dict convention (replacing the reference's SparseTensor rows):
- ``wide``      int32 [B, n_wide]   -- active indices into the summed
                                        wide dimension (pad with 0)
- ``embed``     int32 [B, n_embed]  -- one id per embedding column
- ``indicator`` float32 [B, sum(indicator_dims)] -- multi-hot block
- ``continuous`` float32 [B, n_cont]
Missing keys are allowed if the corresponding columns are empty.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import register_model
from analytics_zoo_tpu.models.recommendation.base import Recommender
from analytics_zoo_tpu.models.recommendation.ncf import (
    _RatingAccuracy, _shifted_ce)


@dataclass
class ColumnFeatureInfo:
    """(ref: recommendation/WideAndDeep.scala ColumnFeatureInfo)."""

    wide_base_cols: List[str] = field(default_factory=list)
    wide_base_dims: List[int] = field(default_factory=list)
    wide_cross_cols: List[str] = field(default_factory=list)
    wide_cross_dims: List[int] = field(default_factory=list)
    indicator_cols: List[str] = field(default_factory=list)
    indicator_dims: List[int] = field(default_factory=list)
    embed_cols: List[str] = field(default_factory=list)
    embed_in_dims: List[int] = field(default_factory=list)
    embed_out_dims: List[int] = field(default_factory=list)
    continuous_cols: List[str] = field(default_factory=list)

    @property
    def wide_dim(self) -> int:
        return sum(self.wide_base_dims) + sum(self.wide_cross_dims)

    @property
    def indicator_dim(self) -> int:
        return sum(self.indicator_dims)


class WideAndDeepNet(nn.Module):
    model_type: str
    class_num: int
    wide_dim: int
    embed_in_dims: Tuple[int, ...]
    embed_out_dims: Tuple[int, ...]
    indicator_dim: int
    n_continuous: int
    hidden_layers: Tuple[int, ...] = (40, 20, 10)

    @nn.compact
    def __call__(self, x):
        logits = None
        if self.model_type in ("wide_n_deep", "wide"):
            # linear over sparse active indices == embedding-sum with a
            # [wide_dim, class_num] weight table (one extra pad row 0)
            wide_idx = x["wide"].astype(jnp.int32)
            table = self.param(
                "wide_weight", nn.initializers.zeros,
                (self.wide_dim + 1, self.class_num))
            gathered = jnp.take(table, wide_idx, axis=0)
            # zero out pad slots (index 0) so predictions are independent
            # of how many pads a row carries
            gathered = gathered * (wide_idx > 0)[..., None]
            logits = jnp.sum(gathered, axis=1)
            logits = logits + self.param(
                "wide_bias", nn.initializers.zeros, (self.class_num,))
        if self.model_type in ("wide_n_deep", "deep"):
            parts = []
            if self.embed_in_dims:
                ids = x["embed"].astype(jnp.int32)
                for i, (din, dout) in enumerate(
                        zip(self.embed_in_dims, self.embed_out_dims)):
                    parts.append(nn.Embed(din + 1, dout,
                                          name=f"embed_{i}")(ids[:, i]))
            if self.indicator_dim:
                parts.append(x["indicator"].astype(jnp.float32))
            if self.n_continuous:
                parts.append(x["continuous"].astype(jnp.float32))
            if not parts:
                raise ValueError("deep path has no input columns")
            h = jnp.concatenate(parts, axis=-1)
            for k, units in enumerate(self.hidden_layers):
                h = nn.relu(nn.Dense(units, name=f"dense_{k}")(h))
            deep_logits = nn.Dense(self.class_num, name="deep_head")(h)
            logits = (deep_logits if logits is None
                      else logits + deep_logits)
        return logits


@register_model
class WideAndDeep(Recommender):
    """(ref: WideAndDeep.scala:101). Labels are 1-based ratings."""

    default_loss = staticmethod(_shifted_ce)
    default_optimizer = "adam"

    @property
    def default_metrics(self):
        return (_RatingAccuracy(),)

    def __init__(self, model_type: str = "wide_n_deep", class_num: int = 2,
                 column_info: ColumnFeatureInfo = None,
                 hidden_layers: Sequence[int] = (40, 20, 10), **ci_kwargs):
        if model_type not in ("wide_n_deep", "wide", "deep"):
            raise ValueError(f"unknown model_type {model_type!r}")
        info = column_info or ColumnFeatureInfo(**ci_kwargs)
        if isinstance(info, dict):
            info = ColumnFeatureInfo(**info)
        self.column_info = info
        super().__init__(
            model_type=model_type, class_num=class_num,
            column_info=info.__dict__, hidden_layers=list(hidden_layers))

    def _build_module(self):
        c = self._config
        info = ColumnFeatureInfo(**c["column_info"])
        return WideAndDeepNet(
            model_type=c["model_type"], class_num=c["class_num"],
            wide_dim=info.wide_dim,
            embed_in_dims=tuple(info.embed_in_dims),
            embed_out_dims=tuple(info.embed_out_dims),
            indicator_dim=info.indicator_dim,
            n_continuous=len(info.continuous_cols),
            hidden_layers=tuple(c["hidden_layers"]))

    # pair-based Recommender methods need a user/item -> feature-dict
    # builder. The reference assembles features from DataFrame rows
    # (ref: WideAndDeep.scala recommendForUser via assemblyFeature);
    # here the assembly step is a pluggable function so candidates can
    # be scored from any feature source (feature table, join, ...).
    def set_feature_assembler(self, assembler) -> "WideAndDeep":
        """``assembler(user_ids [N], item_ids [N]) -> feature dict``
        (the wide/embed/indicator/continuous convention of ``fit``) --
        the analog of the reference's assemblyFeature. Enables
        predict_user_item_pair / recommend_for_user / recommend_for_item.
        """
        self._assembler = assembler
        return self

    def _pair_features(self, users, items):
        """Candidate pairs -> feature dict via the assembler; the base
        ``Recommender`` ranking methods drive this hook (W&D defines no
        user/item universe, so those methods also require explicit
        candidates -- see ``Recommender._candidate_range``)."""
        if getattr(self, "_assembler", None) is None:
            raise RuntimeError(
                "WideAndDeep scores feature dicts; call "
                "set_feature_assembler(fn) first (fn(user_ids, "
                "item_ids) -> feature dict), or build features and "
                "call predict directly")
        return self._assembler(np.asarray(users, np.int32),
                               np.asarray(items, np.int32))

    def _example_input(self):
        info = self.column_info
        x = {}
        if self._config["model_type"] in ("wide_n_deep", "wide"):
            x["wide"] = np.zeros(
                (1, max(len(info.wide_base_cols)
                        + len(info.wide_cross_cols), 1)), np.int32)
        if info.embed_cols:
            x["embed"] = np.zeros((1, len(info.embed_cols)), np.int32)
        if info.indicator_dim:
            x["indicator"] = np.zeros((1, info.indicator_dim), np.float32)
        if info.continuous_cols:
            x["continuous"] = np.zeros(
                (1, len(info.continuous_cols)), np.float32)
        return x
