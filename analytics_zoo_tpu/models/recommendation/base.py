"""Recommender base: user/item pair scoring and top-K recommendation.

The analog of ``Recommender`` (ref: zoo/.../models/recommendation/
Recommender.scala -- predictUserItemPair, recommendForUser,
recommendForItem) with the Spark RDD surface replaced by numpy batches
scored through one jitted forward.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel


@dataclass
class UserItemFeature:
    """(ref: recommendation/UserItemFeature.scala)."""

    user_id: int
    item_id: int
    label: int = 0


@dataclass
class UserItemPrediction:
    """(ref: recommendation/UserItemPrediction.scala)."""

    user_id: int
    item_id: int
    prediction: int
    probability: float


class Recommender(ZooModel):
    """Subclasses score (user, item) int pairs via predict()."""

    def _pair_matrix(self, users, items) -> np.ndarray:
        return np.stack([np.asarray(users, np.int32),
                         np.asarray(items, np.int32)], axis=1)

    def _pair_features(self, users, items):
        """Model input for (user, item) candidate pairs. Default: the
        raw id matrix; models that score richer features (W&D)
        override this with their assembly step."""
        return self._pair_matrix(users, items)

    def _candidate_range(self, count_attr: str, what: str) -> np.ndarray:
        count = getattr(self, count_attr, None)
        if count is None:
            raise ValueError(
                f"{type(self).__name__}.recommend needs explicit "
                f"candidate_{what} (the model defines no {what} "
                "universe)")
        return np.arange(1, count + 1)

    def predict_user_item_pair(
            self, pairs: Sequence[UserItemFeature],
            batch_size: int = 1024) -> List[UserItemPrediction]:
        """(ref: Recommender.scala predictUserItemPair)."""
        users = [p.user_id for p in pairs]
        items = [p.item_id for p in pairs]
        probs = self.predict(self._pair_features(users, items),
                             batch_size=batch_size)
        return [self._to_prediction(u, i, p)
                for u, i, p in zip(users, items, probs)]

    def recommend_for_user(self, user_id: int, max_items: int,
                           candidate_items: Sequence[int] = None,
                           batch_size: int = 1024
                           ) -> List[UserItemPrediction]:
        """Top-K items for one user (ref: Recommender.scala
        recommendForUser)."""
        items = np.asarray(candidate_items if candidate_items is not None
                           else self._candidate_range("item_count",
                                                      "items"), np.int32)
        users = np.full_like(items, user_id)
        probs = self.predict(self._pair_features(users, items),
                             batch_size=batch_size)
        preds = [self._to_prediction(int(u), int(i), p)
                 for u, i, p in zip(users, items, probs)]
        preds.sort(key=lambda r: -r.probability)
        return preds[:max_items]

    def recommend_for_item(self, item_id: int, max_users: int,
                           candidate_users: Sequence[int] = None,
                           batch_size: int = 1024
                           ) -> List[UserItemPrediction]:
        """(ref: Recommender.scala recommendForItem)."""
        users = np.asarray(candidate_users if candidate_users is not None
                           else self._candidate_range("user_count",
                                                      "users"), np.int32)
        items = np.full_like(users, item_id)
        probs = self.predict(self._pair_features(users, items),
                             batch_size=batch_size)
        preds = [self._to_prediction(int(u), int(i), p)
                 for u, i, p in zip(users, items, probs)]
        preds.sort(key=lambda r: -r.probability)
        return preds[:max_users]

    def _to_prediction(self, user, item, probs) -> UserItemPrediction:
        from analytics_zoo_tpu.models.common import softmax_probs

        probs = np.asarray(probs).reshape(-1)
        if probs.shape[0] > 1:  # class logits -> softmax
            sm = softmax_probs(probs[None])[0]
            cls = int(np.argmax(sm))
            # class index c encodes label c+1 (ratings are 1-based,
            # ref: NeuralCFSpec label handling)
            return UserItemPrediction(int(user), int(item), cls + 1,
                                      float(sm[cls]))
        score = float(1.0 / (1.0 + np.exp(-probs[0])))
        return UserItemPrediction(int(user), int(item),
                                  int(score > 0.5), score)
