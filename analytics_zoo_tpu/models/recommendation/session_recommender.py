"""Session-based recommender (GRU4Rec-style).

The analog of ``SessionRecommender`` (ref: zoo/.../models/recommendation/
SessionRecommender.scala, pyzoo session_recommender.py): item-embedding +
GRU over the session sequence, optionally fused with an MLP over the
user's longer purchase history, softmax over the item catalog.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import register_model
from analytics_zoo_tpu.models.recommendation.base import Recommender


class SessionRecommenderNet(nn.Module):
    item_count: int
    item_embed: int
    rnn_hidden_layers: Tuple[int, ...]
    include_history: bool
    mlp_hidden_layers: Tuple[int, ...]

    @nn.compact
    def __call__(self, x):
        if isinstance(x, dict):
            session, history = x["session"], x.get("history")
        else:
            session, history = x, None
        emb = nn.Embed(self.item_count + 1, self.item_embed,
                       name="item_embed")
        h = emb(session.astype(jnp.int32))
        for i, units in enumerate(self.rnn_hidden_layers):
            h = nn.RNN(nn.GRUCell(units), name=f"gru_{i}")(h)
        h = h[:, -1]
        if self.include_history and history is not None:
            hist = emb(history.astype(jnp.int32)).sum(axis=1)
            for i, units in enumerate(self.mlp_hidden_layers):
                hist = nn.relu(nn.Dense(units, name=f"mlp_{i}")(hist))
            h = jnp.concatenate([h, hist], axis=-1)
        return nn.Dense(self.item_count + 1, name="head")(h)


@register_model
class SessionRecommender(Recommender):
    """(ref: SessionRecommender.scala). Item ids are 1-based; labels are
    the next item id."""

    default_loss = staticmethod(
        lambda preds, labels: _next_item_ce(preds, labels))
    default_optimizer = "adam"
    default_metrics = ("top5",)

    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 10, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 5):
        self.item_count = item_count
        super().__init__(
            item_count=item_count, item_embed=item_embed,
            rnn_hidden_layers=list(rnn_hidden_layers),
            session_length=session_length,
            include_history=include_history,
            mlp_hidden_layers=list(mlp_hidden_layers),
            history_length=history_length)

    def _build_module(self):
        c = self._config
        return SessionRecommenderNet(
            item_count=c["item_count"], item_embed=c["item_embed"],
            rnn_hidden_layers=tuple(c["rnn_hidden_layers"]),
            include_history=c["include_history"],
            mlp_hidden_layers=tuple(c["mlp_hidden_layers"]))

    def _example_input(self):
        c = self._config
        x = {"session": np.ones((1, c["session_length"]), np.int32)}
        if c["include_history"]:
            x["history"] = np.ones((1, c["history_length"]), np.int32)
        return x

    def recommend_for_session(self, sessions, max_items: int = 5,
                              zero_based_label: bool = False,
                              batch_size: int = 256):
        """Top-K next items per session (ref: SessionRecommender.scala
        recommendForSession). Returns [(item_id, prob), ...] per row;
        ``zero_based_label`` shifts reported ids to a 0-based catalog."""
        from analytics_zoo_tpu.models.common import (
            softmax_probs, topk_with_probs)

        logits = self.predict(sessions, batch_size=batch_size)
        probs = softmax_probs(logits)
        probs[:, 0] = 0.0  # id 0 is padding, never recommend
        top = topk_with_probs(probs, max_items)
        if zero_based_label:
            top = [[(i - 1, p) for i, p in row] for row in top]
        return top

    # the session API replaces pair scoring; inherited Recommender pair
    # methods would silently embed user ids as items
    def predict_user_item_pair(self, pairs, batch_size: int = 1024):
        raise NotImplementedError(
            "SessionRecommender recommends from item sessions; use "
            "recommend_for_session (ref: SessionRecommender.scala)")

    def recommend_for_user(self, *a, **k):
        raise NotImplementedError(
            "SessionRecommender has no user ids; use "
            "recommend_for_session")

    def recommend_for_item(self, *a, **k):
        raise NotImplementedError(
            "SessionRecommender has no user ids; use "
            "recommend_for_session")


def _next_item_ce(preds, labels):
    from analytics_zoo_tpu.learn.objectives import (
        sparse_categorical_crossentropy)

    return sparse_categorical_crossentropy(
        preds, jnp.asarray(labels).reshape(-1).astype(jnp.int32))
