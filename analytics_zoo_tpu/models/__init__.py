"""Built-in model zoo.

The analog of the reference's ``models/`` families
(ref: zoo/src/main/scala/com/intel/analytics/zoo/models -- SURVEY.md
section 2.1 "built-in models JVM" and 2.2 "models py"): recommendation
(NeuralCF, WideAndDeep, SessionRecommender), text classification, text
matching (KNRM), seq2seq, anomaly detection, image classification and
object detection.
"""

from analytics_zoo_tpu.models.common import ZooModel  # noqa: F401
from analytics_zoo_tpu.models.recommendation import (  # noqa: F401
    NeuralCF,
    Recommender,
    UserItemFeature,
    UserItemPrediction,
)
