"""Built-in model zoo.

The analog of the reference's ``models/`` families
(ref: zoo/src/main/scala/com/intel/analytics/zoo/models -- SURVEY.md
section 2.1 "built-in models JVM" and 2.2 "models py"): recommendation
(NeuralCF, WideAndDeep, SessionRecommender), text classification, text
matching (KNRM), seq2seq, anomaly detection, image classification and
object detection.
"""

from analytics_zoo_tpu.models.common import ZooModel  # noqa: F401
from analytics_zoo_tpu.models.recommendation import (  # noqa: F401
    ColumnFeatureInfo,
    NeuralCF,
    Recommender,
    SessionRecommender,
    UserItemFeature,
    UserItemPrediction,
    WideAndDeep,
)
from analytics_zoo_tpu.models.text import KNRM, TextClassifier  # noqa: F401
from analytics_zoo_tpu.models.seq2seq import Seq2seq  # noqa: F401
from analytics_zoo_tpu.models.anomaly import AnomalyDetector  # noqa: F401
from analytics_zoo_tpu.models.image import (  # noqa: F401
    ImageClassifier,
    ObjectDetector,
    ResNet18,
    ResNet50,
)
