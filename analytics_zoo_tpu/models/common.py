"""ZooModel: base class for the built-in model zoo.

The analog of ``common/ZooModel`` (ref: zoo/.../models/common/
ZooModel.scala:38-160 -- save/load/predict base) with the Estimator as the
training/inference engine. A saved model directory holds ``config.json``
(model class + constructor kwargs) and an Estimator checkpoint, so
``ZooModel.load(path)`` reconstructs the exact model.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence, Type

import jax
import numpy as np

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.learn.estimator import Estimator

logger = get_logger(__name__)

_MODEL_REGISTRY: Dict[str, Type["ZooModel"]] = {}


class ZooModel:
    """Base: subclasses define ``_build_module() -> flax module`` plus the
    loss/optimizer/metrics defaults, and register with @register_model."""

    # subclasses override
    default_loss: Any = None
    default_optimizer: Any = "adam"
    default_metrics: Sequence[Any] = ()

    def __init__(self, **kwargs):
        self._config = dict(kwargs)
        self.module = self._build_module()
        self.estimator = Estimator(
            self.module, loss=self.default_loss,
            optimizer=self.default_optimizer,
            metrics=self.default_metrics)

    def _build_module(self):
        raise NotImplementedError

    # ------------------------------------------------------------ engine --
    def compile(self, loss=None, optimizer=None, metrics=None, **kwargs):
        """Re-configure the training engine (Keras-style); trained weights
        carry over (recompiling changes the optimizer, not the model)."""
        from analytics_zoo_tpu.learn.estimator import recompiled

        self.estimator = recompiled(
            self.estimator, self.module,
            loss=loss if loss is not None else self.default_loss,
            optimizer=(optimizer if optimizer is not None
                       else self.default_optimizer),
            metrics=metrics if metrics is not None else self.default_metrics,
            **kwargs)
        return self

    def fit(self, data, batch_size: int = 256, epochs: int = 1, **kwargs):
        return self.estimator.fit(data, batch_size=batch_size,
                                  epochs=epochs, **kwargs)

    def evaluate(self, data, batch_size: int = 256):
        return self.estimator.evaluate(data, batch_size=batch_size)

    def predict(self, data, batch_size: int = 256):
        return self.estimator.predict(data, batch_size=batch_size)

    # ------------------------------------------------------- persistence --
    def save_model(self, path: str) -> None:
        """(ref: ZooModel.scala saveModel)."""
        if self.estimator.variables is None:
            self._build_for_load()  # fresh-model save: init then save
        os.makedirs(path, exist_ok=True)
        if jax.process_index() == 0:
            with open(os.path.join(path, "config.json"), "w") as f:
                json.dump({"class": type(self).__name__,
                           "config": self._config}, f)
        self.estimator.save(os.path.join(path, "weights"))

    @staticmethod
    def load_model(path: str) -> "ZooModel":
        """(ref: ZooModel.scala loadModel)."""
        with open(os.path.join(path, "config.json")) as f:
            meta = json.load(f)
        cls = _MODEL_REGISTRY.get(meta["class"])
        if cls is None:
            raise ValueError(f"unknown model class {meta['class']!r}; "
                             f"known: {sorted(_MODEL_REGISTRY)}")
        model = cls(**meta["config"])
        model._build_for_load()
        model.estimator.load(os.path.join(path, "weights"))
        return model

    def _build_for_load(self) -> None:
        """Initialize variables with a dummy batch so load() has a
        template. Subclasses provide ``_example_input()``."""
        x = self._example_input()
        self.estimator._ensure_built(x)

    def _example_input(self):
        raise NotImplementedError

    def summary(self) -> str:
        lines = [f"{type(self).__name__}("]
        for k, v in self._config.items():
            lines.append(f"  {k}={v},")
        lines.append(")")
        if self.estimator.variables is not None:
            n = sum(int(np.prod(l.shape)) for l in
                    jax.tree_util.tree_leaves(
                        self.estimator.variables.get("params", {})))
            lines.append(f"total params: {n:,}")
        return "\n".join(lines)


def register_model(cls: Type[ZooModel]) -> Type[ZooModel]:
    _MODEL_REGISTRY[cls.__name__] = cls
    return cls


def softmax_probs(logits: np.ndarray) -> np.ndarray:
    """Numerically-stable softmax over the last axis (host-side)."""
    logits = np.asarray(logits, np.float32)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def topk_with_probs(probs: np.ndarray, k: int):
    """Per-row top-k: [[(index, prob), ...], ...]."""
    probs = np.asarray(probs)
    top = np.argsort(-probs, axis=-1)[:, :k]
    return [[(int(c), float(probs[i, c])) for c in row]
            for i, row in enumerate(top)]
