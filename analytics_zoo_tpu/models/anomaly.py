"""Anomaly detection via LSTM forecasting.

The analog of ``AnomalyDetector`` (ref: zoo/.../models/anomalydetection/
AnomalyDetector.scala, pyzoo/zoo/models/anomalydetection): stacked LSTMs
predict the next value of a feature sequence; the top-N largest
|y - y_hat| distances are flagged anomalous (unsupervised).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model


class AnomalyDetectorNet(nn.Module):
    hidden_layers: Tuple[int, ...]
    dropouts: Tuple[float, ...]

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x
        for i, (units, rate) in enumerate(
                zip(self.hidden_layers, self.dropouts)):
            h = nn.RNN(nn.OptimizedLSTMCell(units), name=f"lstm_{i}")(h)
            h = nn.Dropout(rate, deterministic=not train)(h)
        return nn.Dense(1, name="head")(h[:, -1])


@register_model
class AnomalyDetector(ZooModel):
    """(ref: AnomalyDetector.scala). Input [B, unroll, features];
    regression target is the next value."""

    default_loss = "mse"
    default_optimizer = "rmsprop"
    default_metrics = ("mse",)

    def __init__(self, feature_shape: Sequence[int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        if len(hidden_layers) != len(dropouts):
            raise ValueError("hidden_layers and dropouts must align")
        super().__init__(feature_shape=list(feature_shape),
                         hidden_layers=list(hidden_layers),
                         dropouts=list(dropouts))

    def _build_module(self):
        c = self._config
        return AnomalyDetectorNet(hidden_layers=tuple(c["hidden_layers"]),
                                  dropouts=tuple(c["dropouts"]))

    def _example_input(self):
        return np.zeros((1,) + tuple(self._config["feature_shape"]),
                        np.float32)

    @staticmethod
    def unroll(data, unroll_length: int):
        """Sliding windows: [N, F] -> (x [M, unroll, F], y [M])
        (ref: AnomalyDetector.scala unroll)."""
        data = np.asarray(data, np.float32)
        if data.ndim == 1:
            data = data[:, None]
        n = len(data) - unroll_length
        if n <= 0:
            raise ValueError("series shorter than unroll_length")
        x = np.stack([data[i:i + unroll_length] for i in range(n)])
        y = data[unroll_length:, 0]
        return x, y

    @staticmethod
    def detect_anomalies(y_true, y_pred, anomaly_size: int):
        """Indices + threshold of the top-``anomaly_size`` forecast errors
        (ref: AnomalyDetector.scala detectAnomalies)."""
        y_true = np.asarray(y_true).reshape(-1)
        y_pred = np.asarray(y_pred).reshape(-1)
        dist = np.abs(y_true - y_pred)
        idx = np.argsort(-dist)[:anomaly_size]
        threshold = float(dist[idx[-1]]) if len(idx) else float("inf")
        return np.sort(idx), threshold
