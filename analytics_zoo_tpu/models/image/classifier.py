"""Image classification model wrapper.

The analog of ``ImageClassifier`` (ref: zoo/.../models/image/
imageclassification/ImageClassifier.scala -- load-and-predict of
pretrained zoo models with an ``ImageConfigure`` preprocessing spec;
here the backbone is trainable JAX ResNet, and predict applies the same
normalize-resize preprocessing).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as _nn
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.models.image.backbones import (
    AlexNet, DenseNet, InceptionV1, InceptionV3, MobileNetV1,
    MobileNetV2, SqueezeNet, VGG16, VGG19, densenet161)
from analytics_zoo_tpu.models.image.resnet import ResNet18, ResNet50

# the reference's full pretrained family (ref: docs/docs/
# ProgrammingGuide/image-classification.md:60-80 -- alexnet,
# inception-v1/v3, vgg-16/19, resnet-50, densenet-161, mobilenet,
# mobilenet-v2, squeezenet), every member trainable here
_BACKBONES = {"resnet18": ResNet18, "resnet50": ResNet50,
              "inception-v1": InceptionV1, "inception-v3": InceptionV3,
              "mobilenet": MobileNetV1, "mobilenet-v2": MobileNetV2,
              "vgg16": VGG16, "vgg19": VGG19, "alexnet": AlexNet,
              "squeezenet": SqueezeNet, "densenet121": DenseNet,
              "densenet161": densenet161}

# ImageNet channel stats (the reference's ImageChannelNormalize defaults)
_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


class _NormalizedBackbone(_nn.Module):
    """Backbone wrapper: raw uint8 images normalize ON DEVICE.

    Serving clients send uint8 [N, H, W, 3]; transferring those and
    fusing /255-mean/std into the XLA program moves 4x fewer bytes
    across the host->device link than host-side float32 preprocessing
    (the reference normalizes on CPU before feeding the engine,
    ref: zoo/.../feature/image/ImageChannelNormalize.scala). float
    inputs pass through untouched (assumed already normalized); the
    dtype test is trace-static, so each input dtype compiles its own
    (correct) program.
    """

    backbone: Any

    @_nn.compact
    def __call__(self, x, train: bool = False):
        import jax.numpy as jnp

        if jnp.issubdtype(x.dtype, jnp.integer):
            x = (x.astype(jnp.float32) / 255.0
                 - jnp.asarray(_MEAN)) / jnp.asarray(_STD)
        return self.backbone(x, train=train)


@register_model
class ImageClassifier(ZooModel):
    """Trainable classifier over a ResNet backbone. Accepts normalized
    float images or raw uint8 (normalized on device -- see
    ``_NormalizedBackbone``)."""

    default_loss = "sparse_categorical_crossentropy"
    default_optimizer = "adam"
    default_metrics = ("accuracy", "top5")

    def __init__(self, class_num: int, backbone: str = "resnet50",
                 image_size: int = 224, dtype: str = "float32"):
        if backbone not in _BACKBONES:
            raise ValueError(f"unknown backbone {backbone!r}; "
                             f"known: {sorted(_BACKBONES)}")
        super().__init__(class_num=class_num, backbone=backbone,
                         image_size=image_size, dtype=dtype)

    def _build_module(self):
        import jax.numpy as jnp

        c = self._config
        backbone = _BACKBONES[c["backbone"]](
            num_classes=c["class_num"], dtype=jnp.dtype(c["dtype"]))
        return _NormalizedBackbone(backbone=backbone)

    def _example_input(self):
        s = self._config["image_size"]
        return np.zeros((1, s, s, 3), np.float32)

    @staticmethod
    def preprocess(images: np.ndarray) -> np.ndarray:
        """uint8 [N, H, W, 3] -> normalized float32 (ref:
        ImageChannelNormalize + MatToTensor chain)."""
        x = np.asarray(images, np.float32) / 255.0
        return (x - _MEAN) / _STD

    def predict_classes(self, images, batch_size: int = 32,
                        top_k: int = 1):
        """Top-k (class, score) per image (ref: ImageClassifier
        predictImageSet + topN postprocessing). Integer images go to
        the device raw (normalization is fused on device, 4x less
        transfer); float images are assumed raw 0-255 and keep the
        host-side preprocess for backward compatibility."""
        from analytics_zoo_tpu.models.common import (
            softmax_probs, topk_with_probs)

        images = np.asarray(images)
        x = (images if np.issubdtype(images.dtype, np.integer)
             else self.preprocess(images))
        logits = self.predict(x, batch_size=batch_size)
        return topk_with_probs(softmax_probs(logits), top_k)
