"""SSD-style object detection: model, anchors, predict pipeline.

The analog of the reference's object-detection family
(ref: zoo/src/main/scala/com/intel/analytics/zoo/models/image/objectdetection/ --
``ObjectDetector.loadModel`` + ``Predictor`` load-and-predict pipeline,
SSD anchors/decode in ``common/BboxUtil.scala``, ``Visualizer.scala``
box drawing; python surface pyzoo/zoo/models/image/objectdetection.py).

TPU-first shape discipline: one NHWC forward producing every scale's
class/box heads as static-shape tensors; all dynamic-size work (NMS,
thresholding) happens host-side in numpy on the decoded outputs --
XLA never sees a data-dependent shape.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.models.image.detection import (
    clip_boxes, decode_boxes, detect_per_class)


def generate_anchors(image_size: int, feature_sizes: Sequence[int],
                     scales: Sequence[float],
                     aspect_ratios: Sequence[Sequence[float]]
                     ) -> np.ndarray:
    """SSD prior boxes [N, 4] (x1, y1, x2, y2 in pixels)
    (ref: objectdetection SSD prior-box generation in BboxUtil/SSD
    graph). One anchor per (cell, scale x ratio) on every feature map;
    an extra geometric-mean scale anchor per cell mirrors SSD's
    ``extra prior``."""
    anchors: List[Tuple[float, float, float, float]] = []
    for fsize, scale, ratios, next_scale in zip(
            feature_sizes, scales, aspect_ratios,
            list(scales[1:]) + [1.0]):
        step = image_size / fsize
        sizes = [(scale, scale),
                 (float(np.sqrt(scale * next_scale)),
                  float(np.sqrt(scale * next_scale)))]
        for r in ratios:
            sizes.append((scale * float(np.sqrt(r)),
                          scale / float(np.sqrt(r))))
        for i, j in itertools.product(range(fsize), repeat=2):
            cx = (j + 0.5) * step
            cy = (i + 0.5) * step
            for w, h in sizes:
                pw, ph = w * image_size, h * image_size
                anchors.append((cx - pw / 2, cy - ph / 2,
                                cx + pw / 2, cy + ph / 2))
    return np.asarray(anchors, np.float32)


def multibox_loss(preds, targets, neg_pos_ratio: float = 3.0):
    """SSD MultiBox loss: softmax cross-entropy over classes with
    hard-negative mining (``neg_pos_ratio`` negatives per positive) +
    smooth-L1 on positive-anchor box deltas, normalized by positive
    count (ref: the reference trains SSD in BigDL with
    MultiBoxLoss; here it is a jit-compiled static-shape function --
    the mining top-k runs on sorted losses, no dynamic shapes).

    preds: (class_logits [B, N, C+1], box_deltas [B, N, 4]);
    targets: (class_targets [B, N] int, box_targets [B, N, 4]) from
    :func:`~analytics_zoo_tpu.models.image.detection.match_anchors`.
    """
    import jax

    cls_logits, box_deltas = preds
    cls_t, box_t = (targets[0].astype(jnp.int32),
                    targets[1].astype(jnp.float32))
    b, n, _ = cls_logits.shape
    pos = cls_t > 0
    n_pos = jnp.maximum(jnp.sum(pos, axis=1), 1)        # [B]

    logp = jax.nn.log_softmax(cls_logits.astype(jnp.float32), -1)
    ce = -jnp.take_along_axis(logp, cls_t[..., None], -1)[..., 0]

    # hard negative mining: rank background anchors by loss, keep the
    # worst ratio*n_pos of them (static-shape: sort + rank compare)
    neg_ce = jnp.where(pos, -jnp.inf, ce)
    rank = jnp.argsort(jnp.argsort(-neg_ce, axis=1), axis=1)
    neg = rank < (neg_pos_ratio * n_pos)[:, None]
    cls_loss = jnp.sum(jnp.where(pos | neg, ce, 0.0), axis=1)

    diff = jnp.abs(box_deltas.astype(jnp.float32) - box_t)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    box_loss = jnp.sum(jnp.where(pos[..., None], sl1, 0.0), axis=(1, 2))
    return jnp.mean((cls_loss + box_loss) / n_pos)


class _ConvBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (3, 3), strides=(self.stride,
                                                    self.stride),
                    use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        return nn.relu(x)


class SSDModule(nn.Module):
    """Small SSD: conv backbone + multi-scale class/box heads.

    Input [B, S, S, 3] -> (class_logits [B, N, C+1], box_deltas [B, N, 4])
    where N = total anchors over the feature pyramid and column 0 of the
    class axis is background (the reference's SSD output contract).
    """

    class_num: int           # foreground classes (background added)
    image_size: int = 128
    widths: Sequence[int] = (32, 64, 128)
    anchors_per_cell: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        cls_outs, box_outs = [], []
        h = x
        # stem halves twice; each pyramid level halves again
        h = _ConvBlock(self.widths[0])(h, train=train)
        h = _ConvBlock(self.widths[0], stride=2)(h, train=train)
        h = _ConvBlock(self.widths[0], stride=2)(h, train=train)
        a = self.anchors_per_cell
        for w in self.widths:
            h = _ConvBlock(w, stride=2)(h, train=train)
            cls = nn.Conv(a * (self.class_num + 1), (3, 3),
                          padding="SAME")(h)
            box = nn.Conv(a * 4, (3, 3), padding="SAME")(h)
            cls_outs.append(cls.reshape(b, -1, self.class_num + 1))
            box_outs.append(box.reshape(b, -1, 4))
        return (jnp.concatenate(cls_outs, axis=1),
                jnp.concatenate(box_outs, axis=1))


@register_model
class ObjectDetector(ZooModel):
    """SSD pipeline (ref: ObjectDetector.scala + Predictor.scala):
    ``detect(images)`` returns per-image lists of
    (class_id, score, [x1, y1, x2, y2]) after decode + per-class NMS;
    trainable end-to-end via ``fit(images, prepare_targets(gt))`` with
    the MultiBox loss."""

    default_loss = staticmethod(multibox_loss)
    default_optimizer = "adam"

    def __init__(self, class_num: int, image_size: int = 128,
                 widths: Sequence[int] = (32, 64, 128),
                 anchors_per_cell: int = 4,
                 label_map: Optional[Dict[Any, str]] = None):
        # keys normalize to int; stored str-keyed in the json config so
        # the map survives save_model/load_model
        self._label_map = {int(k): v for k, v in (label_map or {}).items()}
        ratio_bank = [2.0, 0.5, 3.0, 1.0 / 3.0]
        if not 3 <= anchors_per_cell <= 2 + len(ratio_bank):
            raise ValueError(
                f"anchors_per_cell must be in [3, {2 + len(ratio_bank)}] "
                "(2 square priors + up to 4 aspect ratios)")
        super().__init__(class_num=class_num, image_size=image_size,
                         widths=tuple(widths),
                         anchors_per_cell=anchors_per_cell,
                         label_map={str(k): v for k, v in
                                    (label_map or {}).items()})
        # SAME-padded stride-2 convs produce ceil(s/2) grids; mirror
        # that exactly so anchor count always matches the head outputs
        s = -(-image_size // 2)   # stem block 1
        s = -(-s // 2)            # stem block 2
        feature_sizes = []
        for _ in widths:
            s = -(-s // 2)
            feature_sizes.append(s)
        n_scales = len(widths)
        scales = [0.15 + 0.55 * i / max(n_scales - 1, 1)
                  for i in range(n_scales)]
        # 2 square priors per cell; remaining slots are aspect ratios
        ratios = [ratio_bank[:anchors_per_cell - 2]] * n_scales
        self.anchors = generate_anchors(image_size, feature_sizes,
                                        scales, ratios)

    def _build_module(self):
        c = self._config
        return SSDModule(class_num=c["class_num"],
                         image_size=c["image_size"],
                         widths=c["widths"],
                         anchors_per_cell=c["anchors_per_cell"])

    def _example_input(self):
        s = self._config["image_size"]
        return np.zeros((1, s, s, 3), np.float32)

    def prepare_targets(self, ground_truth: Sequence[Tuple[np.ndarray,
                                                           np.ndarray]],
                        iou_threshold: float = 0.5
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-image (gt_boxes [G, 4], gt_labels [G] foreground ids
        >= 1) -> stacked (class_targets [B, N], box_targets [B, N, 4])
        ready for ``fit``; runs the anchor matcher host-side so the
        training step keeps static shapes."""
        from analytics_zoo_tpu.models.image.detection import (
            match_anchors)

        cls_list, box_list = [], []
        for boxes, labels in ground_truth:
            c, bx = match_anchors(self.anchors, boxes, labels,
                                  iou_threshold=iou_threshold)
            cls_list.append(c)
            box_list.append(bx)
        return np.stack(cls_list), np.stack(box_list)

    def detect(self, images: np.ndarray, batch_size: int = 8,
               score_threshold: float = 0.3, iou_threshold: float = 0.45,
               top_k: int = 100
               ) -> List[List[Tuple[int, float, np.ndarray]]]:
        """Full predict pipeline on [B, S, S, 3] images."""
        import jax

        cls_logits, box_deltas = self.estimator.predict(
            np.asarray(images, np.float32), batch_size=batch_size)
        probs = np.asarray(jax.nn.softmax(jnp.asarray(cls_logits), -1))
        deltas = np.asarray(box_deltas)
        size = self._config["image_size"]
        results = []
        for b in range(probs.shape[0]):
            boxes = clip_boxes(decode_boxes(self.anchors, deltas[b]),
                               size, size)
            results.append(detect_per_class(
                boxes, probs[b], score_threshold=score_threshold,
                iou_threshold=iou_threshold, top_k=top_k))
        return results

    def label_of(self, class_id: int) -> str:
        return self._label_map.get(class_id, str(class_id))


def visualize(image: np.ndarray,
              detections: Sequence[Tuple[int, float, np.ndarray]],
              label_map: Optional[Dict[int, str]] = None) -> np.ndarray:
    """Draw detection boxes + labels onto an image (ref:
    objectdetection/visualization/Visualizer.scala). Returns HWC uint8."""
    from PIL import Image, ImageDraw

    img = Image.fromarray(np.clip(image, 0, 255).astype(np.uint8))
    draw = ImageDraw.Draw(img)
    palette = [(255, 64, 64), (64, 200, 64), (64, 64, 255),
               (255, 200, 0), (200, 0, 200), (0, 200, 200)]
    for class_id, score, box in detections:
        color = palette[class_id % len(palette)]
        x1, y1, x2, y2 = [float(v) for v in box]
        draw.rectangle([x1, y1, x2, y2], outline=color, width=2)
        name = (label_map or {}).get(class_id, str(class_id))
        draw.text((x1 + 2, max(y1 - 10, 0)), f"{name}:{score:.2f}",
                  fill=color)
    return np.asarray(img)
