"""ResNet family (v1.5, channels-last, bf16-friendly).

The TPU-native backbone for north-star workload #3 (ResNet-50, BASELINE.md
config #3; ref workload: pyzoo/zoo/examples/orca/learn/tf2/resnet/
resnet-50-imagenet.py -- the reference ships ResNet as a TF2 example and
as pretrained load-and-predict models, zoo/.../imageclassification).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.normalization import batch_norm


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    projection: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = batch_norm(train, self.dtype, momentum=0.9,
                          epsilon=1e-5)
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = nn.Conv(4 * self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj_conv")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    projection: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = batch_norm(train, self.dtype, momentum=0.9,
                          epsilon=1e-5)
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = nn.Conv(self.filters, (3, 3), use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = norm(name="bn2", scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj_conv")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """stage_sizes e.g. (3, 4, 6, 3) for ResNet-50."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    block: Any = BottleneckBlock
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3),
                    (3, 3)], use_bias=False, dtype=self.dtype,
                    name="stem_conv")(x)
        x = nn.relu(batch_norm(train, self.dtype, momentum=0.9,
                               epsilon=1e-5)(name="stem_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(self.num_filters * 2 ** i, strides=strides,
                               projection=(j == 0), dtype=self.dtype,
                               name=f"stage{i}_block{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="head")(x)


def ResNet18(num_classes: int = 1000,
             dtype: Any = jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes,
                  block=BasicBlock, dtype=dtype)


def ResNet50(num_classes: int = 1000,
             dtype: Any = jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                  block=BottleneckBlock, dtype=dtype)
