"""Vision models (ref: zoo/.../models/image/{imageclassification,
objectdetection})."""

from analytics_zoo_tpu.models.image.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet50,
)
from analytics_zoo_tpu.models.image.backbones import (  # noqa: F401
    AlexNet,
    DenseNet,
    InceptionV1,
    InceptionV3,
    MobileNetV1,
    MobileNetV2,
    SqueezeNet,
    VGG16,
    VGG19,
    densenet161,
)
from analytics_zoo_tpu.models.image.classifier import (  # noqa: F401
    ImageClassifier,
)
from analytics_zoo_tpu.models.image import detection  # noqa: F401
from analytics_zoo_tpu.models.image.object_detection import (  # noqa: F401
    ObjectDetector,
    SSDModule,
    generate_anchors,
    visualize,
)
