"""Classic CNN backbones: Inception-v1 (GoogLeNet), MobileNet v1, VGG-16.

The reference's image-classification zoo spans these families as
pretrained load-and-predict models (ref: pyzoo/zoo/models/image/
imageclassification/image_classifier.py -- Inception-v1/MobileNet/VGG/
DenseNet variants listed in the model-zoo table) and ships Inception-v1
as its flagship distributed-training example (ref: zoo/src/main/scala/
com/intel/analytics/zoo/examples/inception/Train.scala /
Inception.scala). Here each is a trainable flax module, channels-last,
bf16-friendly, exposed through ``ImageClassifier``.

Design notes (TPU): all three are plain conv stacks XLA maps straight
onto the MXU; batch-norm everywhere (including the VGG variant, the
standard modern recipe) keeps activations bf16-stable; MobileNet's
depthwise convs use ``feature_group_count`` so XLA emits the fused
depthwise kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


def _norm(train: bool, dtype):
    return partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-3, dtype=dtype)


class InceptionBlock(nn.Module):
    """One GoogLeNet mixed block: 1x1 | 1x1-3x3 | 1x1-5x5 | pool-1x1
    branches concatenated on channels (ref: Inception.scala's
    inceptionLayerV1 branch structure)."""

    b1: int          # 1x1 branch filters
    b3_reduce: int   # 3x3 branch bottleneck
    b3: int
    b5_reduce: int   # 5x5 branch bottleneck
    b5: int
    pool_proj: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

        def unit(h, filters, kernel, name):
            h = conv(filters, kernel, name=f"{name}_conv")(h)
            return nn.relu(norm(name=f"{name}_bn")(h))

        br1 = unit(x, self.b1, (1, 1), "b1")
        br3 = unit(x, self.b3_reduce, (1, 1), "b3r")
        br3 = unit(br3, self.b3, (3, 3), "b3")
        br5 = unit(x, self.b5_reduce, (1, 1), "b5r")
        br5 = unit(br5, self.b5, (5, 5), "b5")
        brp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        brp = unit(brp, self.pool_proj, (1, 1), "bp")
        return jnp.concatenate([br1, br3, br5, brp], axis=-1)


# GoogLeNet table: (b1, b3_reduce, b3, b5_reduce, b5, pool_proj)
_INCEPTION_CFG = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class InceptionV1(nn.Module):
    """GoogLeNet with batch-norm (the reference's distributed-training
    flagship; ref: examples/inception/Inception.scala Inception_v1).
    The train-time auxiliary heads are omitted -- they existed to aid
    pre-BN optimization and modern BN training does not need them."""

    num_classes: int = 1000
    dropout_rate: float = 0.4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = conv(64, (7, 7), (2, 2), name="stem_conv1")(x)
        x = nn.relu(norm(name="stem_bn1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = conv(64, (1, 1), name="stem_conv2")(x)
        x = nn.relu(norm(name="stem_bn2")(x))
        x = conv(192, (3, 3), name="stem_conv3")(x)
        x = nn.relu(norm(name="stem_bn3")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for key in ("3a", "3b"):
            x = InceptionBlock(*_INCEPTION_CFG[key], dtype=self.dtype,
                               name=f"mixed{key}")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for key in ("4a", "4b", "4c", "4d", "4e"):
            x = InceptionBlock(*_INCEPTION_CFG[key], dtype=self.dtype,
                               name=f"mixed{key}")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for key in ("5a", "5b"):
            x = InceptionBlock(*_INCEPTION_CFG[key], dtype=self.dtype,
                               name=f"mixed{key}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class _SeparableBlock(nn.Module):
    """Depthwise 3x3 + pointwise 1x1, each BN-relu (MobileNet v1 unit)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        channels = x.shape[-1]
        x = nn.Conv(channels, (3, 3), self.strides, use_bias=False,
                    feature_group_count=channels, dtype=self.dtype,
                    name="dw_conv")(x)
        x = nn.relu(norm(name="dw_bn")(x))
        x = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="pw_conv")(x)
        return nn.relu(norm(name="pw_bn")(x))


class MobileNetV1(nn.Module):
    """MobileNet v1 with a width multiplier (ref model-zoo family:
    image_classifier.py "mobilenet" variants)."""

    num_classes: int = 1000
    width: float = 1.0
    dropout_rate: float = 0.001
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(f):
            return max(8, int(f * self.width))

        norm = _norm(train, self.dtype)
        x = nn.Conv(w(32), (3, 3), (2, 2), use_bias=False,
                    dtype=self.dtype, name="stem_conv")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                (512, 2)] + [(512, 1)] * 5 + [(1024, 2), (1024, 1)]
        for i, (filters, stride) in enumerate(plan):
            x = _SeparableBlock(w(filters), (stride, stride),
                                dtype=self.dtype,
                                name=f"block{i + 1}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class VGG16(nn.Module):
    """VGG-16 (configuration D) with batch-norm (ref model-zoo family:
    image_classifier.py "vgg-16"). The giant fc6/fc7 dense layers are
    kept at 4096 to match the family's capacity."""

    num_classes: int = 1000
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        plan = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        for s, (filters, reps) in enumerate(plan):
            for r in range(reps):
                x = nn.Conv(filters, (3, 3), use_bias=False,
                            dtype=self.dtype,
                            name=f"conv{s + 1}_{r + 1}")(x)
                x = nn.relu(norm(name=f"bn{s + 1}_{r + 1}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for i in (6, 7):
            x = nn.Dense(4096, dtype=self.dtype, name=f"fc{i}")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate,
                           deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)
