"""Classic CNN backbones: Inception-v1 (GoogLeNet), MobileNet v1, VGG-16.

The reference's image-classification zoo spans these families as
pretrained load-and-predict models (ref: pyzoo/zoo/models/image/
imageclassification/image_classifier.py -- Inception-v1/MobileNet/VGG/
DenseNet variants listed in the model-zoo table) and ships Inception-v1
as its flagship distributed-training example (ref: zoo/src/main/scala/
com/intel/analytics/zoo/examples/inception/Train.scala /
Inception.scala). Here each is a trainable flax module, channels-last,
bf16-friendly, exposed through ``ImageClassifier``.

Design notes (TPU): all three are plain conv stacks XLA maps straight
onto the MXU; batch-norm everywhere (including the VGG variant, the
standard modern recipe) keeps activations bf16-stable; MobileNet's
depthwise convs use ``feature_group_count`` so XLA emits the fused
depthwise kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


def _norm(train: bool, dtype):
    # config-aware BN factory: exact nn.BatchNorm, or opt-in sampled
    # statistics via zoo.models.bn_stat_rows (see SampledBatchNorm)
    from analytics_zoo_tpu.keras.layers.normalization import batch_norm

    return batch_norm(train, dtype, momentum=0.9, epsilon=1e-3)


class InceptionBlock(nn.Module):
    """One GoogLeNet mixed block: 1x1 | 1x1-3x3 | 1x1-5x5 | pool-1x1
    branches concatenated on channels (ref: Inception.scala's
    inceptionLayerV1 branch structure)."""

    b1: int          # 1x1 branch filters
    b3_reduce: int   # 3x3 branch bottleneck
    b3: int
    b5_reduce: int   # 5x5 branch bottleneck
    b5: int
    pool_proj: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

        def unit(h, filters, kernel, name):
            h = conv(filters, kernel, name=f"{name}_conv")(h)
            return nn.relu(norm(name=f"{name}_bn")(h))

        br1 = unit(x, self.b1, (1, 1), "b1")
        br3 = unit(x, self.b3_reduce, (1, 1), "b3r")
        br3 = unit(br3, self.b3, (3, 3), "b3")
        br5 = unit(x, self.b5_reduce, (1, 1), "b5r")
        br5 = unit(br5, self.b5, (5, 5), "b5")
        brp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        brp = unit(brp, self.pool_proj, (1, 1), "bp")
        return jnp.concatenate([br1, br3, br5, brp], axis=-1)


# GoogLeNet table: (b1, b3_reduce, b3, b5_reduce, b5, pool_proj)
_INCEPTION_CFG = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class InceptionV1(nn.Module):
    """GoogLeNet with batch-norm (the reference's distributed-training
    flagship; ref: examples/inception/Inception.scala Inception_v1).
    The train-time auxiliary heads are omitted -- they existed to aid
    pre-BN optimization and modern BN training does not need them."""

    num_classes: int = 1000
    dropout_rate: float = 0.4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = conv(64, (7, 7), (2, 2), name="stem_conv1")(x)
        x = nn.relu(norm(name="stem_bn1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = conv(64, (1, 1), name="stem_conv2")(x)
        x = nn.relu(norm(name="stem_bn2")(x))
        x = conv(192, (3, 3), name="stem_conv3")(x)
        x = nn.relu(norm(name="stem_bn3")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for key in ("3a", "3b"):
            x = InceptionBlock(*_INCEPTION_CFG[key], dtype=self.dtype,
                               name=f"mixed{key}")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for key in ("4a", "4b", "4c", "4d", "4e"):
            x = InceptionBlock(*_INCEPTION_CFG[key], dtype=self.dtype,
                               name=f"mixed{key}")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for key in ("5a", "5b"):
            x = InceptionBlock(*_INCEPTION_CFG[key], dtype=self.dtype,
                               name=f"mixed{key}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class _SeparableBlock(nn.Module):
    """Depthwise 3x3 + pointwise 1x1, each BN-relu (MobileNet v1 unit)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        channels = x.shape[-1]
        x = nn.Conv(channels, (3, 3), self.strides, use_bias=False,
                    feature_group_count=channels, dtype=self.dtype,
                    name="dw_conv")(x)
        x = nn.relu(norm(name="dw_bn")(x))
        x = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="pw_conv")(x)
        return nn.relu(norm(name="pw_bn")(x))


class MobileNetV1(nn.Module):
    """MobileNet v1 with a width multiplier (ref model-zoo family:
    image_classifier.py "mobilenet" variants)."""

    num_classes: int = 1000
    width: float = 1.0
    dropout_rate: float = 0.001
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(f):
            return max(8, int(f * self.width))

        norm = _norm(train, self.dtype)
        x = nn.Conv(w(32), (3, 3), (2, 2), use_bias=False,
                    dtype=self.dtype, name="stem_conv")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                (512, 2)] + [(512, 1)] * 5 + [(1024, 2), (1024, 1)]
        for i, (filters, stride) in enumerate(plan):
            x = _SeparableBlock(w(filters), (stride, stride),
                                dtype=self.dtype,
                                name=f"block{i + 1}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class VGG16(nn.Module):
    """VGG-16 (configuration D) with batch-norm (ref model-zoo family:
    image_classifier.py "vgg-16"). The giant fc6/fc7 dense layers are
    kept at 4096 to match the family's capacity."""

    num_classes: int = 1000
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        plan = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        for s, (filters, reps) in enumerate(plan):
            for r in range(reps):
                x = nn.Conv(filters, (3, 3), use_bias=False,
                            dtype=self.dtype,
                            name=f"conv{s + 1}_{r + 1}")(x)
                x = nn.relu(norm(name=f"bn{s + 1}_{r + 1}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for i in (6, 7):
            x = nn.Dense(4096, dtype=self.dtype, name=f"fc{i}")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate,
                           deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class VGG19(VGG16):
    """VGG-19 (configuration E): the 16-layer plan with the last three
    stages deepened to four convs (ref model-zoo family:
    image_classifier.py "vgg-19")."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        plan = [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]
        for s, (filters, reps) in enumerate(plan):
            for r in range(reps):
                x = nn.Conv(filters, (3, 3), use_bias=False,
                            dtype=self.dtype,
                            name=f"conv{s + 1}_{r + 1}")(x)
                x = nn.relu(norm(name=f"bn{s + 1}_{r + 1}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for i in (6, 7):
            x = nn.Dense(4096, dtype=self.dtype, name=f"fc{i}")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate,
                           deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class AlexNet(nn.Module):
    """AlexNet with batch-norm in place of LRN (ref model-zoo family:
    image_classifier.py "alexnet"; BN is the modern stand-in for the
    original local response normalization)."""

    num_classes: int = 1000
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = conv(96, (11, 11), (4, 4), name="conv1")(x)
        x = nn.relu(norm(name="bn1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(256, (5, 5), name="conv2")(x)
        x = nn.relu(norm(name="bn2")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(384, (3, 3), name="conv3")(x)
        x = nn.relu(norm(name="bn3")(x))
        x = conv(384, (3, 3), name="conv4")(x)
        x = nn.relu(norm(name="bn4")(x))
        x = conv(256, (3, 3), name="conv5")(x)
        x = nn.relu(norm(name="bn5")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for i in (6, 7):
            x = nn.Dense(4096, dtype=self.dtype, name=f"fc{i}")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate,
                           deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class _FireModule(nn.Module):
    """SqueezeNet fire module: 1x1 squeeze, then parallel 1x1 + 3x3
    expands concatenated on channels."""

    squeeze: int
    expand: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        s = nn.Conv(self.squeeze, (1, 1), use_bias=False,
                    dtype=self.dtype, name="squeeze")(x)
        s = nn.relu(norm(name="squeeze_bn")(s))
        e1 = nn.relu(nn.Conv(self.expand, (1, 1), dtype=self.dtype,
                             name="expand1")(s))
        e3 = nn.relu(nn.Conv(self.expand, (3, 3), dtype=self.dtype,
                             name="expand3")(s))
        return jnp.concatenate([e1, e3], axis=-1)


class SqueezeNet(nn.Module):
    """SqueezeNet v1.1 (ref model-zoo family: image_classifier.py
    "squeezenet"): fire modules + a conv classifier head over global
    average pooling."""

    num_classes: int = 1000
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        x = nn.Conv(64, (3, 3), (2, 2), use_bias=False,
                    dtype=self.dtype, name="stem")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # v1.1 schedule: pool after fire3 and fire5 (early pooling is
        # v1.1's compute saving over v1.0)
        for i, (sq, ex) in enumerate([(16, 64), (16, 64)]):
            x = _FireModule(sq, ex, dtype=self.dtype,
                            name=f"fire{i + 2}")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        for i, (sq, ex) in enumerate([(32, 128), (32, 128)]):
            x = _FireModule(sq, ex, dtype=self.dtype,
                            name=f"fire{i + 4}")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        for i, (sq, ex) in enumerate([(48, 192), (48, 192), (64, 256),
                                      (64, 256)]):
            x = _FireModule(sq, ex, dtype=self.dtype,
                            name=f"fire{i + 6}")(x, train=train)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32,
                    name="head_conv")(x.astype(jnp.float32))
        return jnp.mean(nn.relu(x), axis=(1, 2))


class _DenseBlock(nn.Module):
    """DenseNet block: each layer concatenates its k new feature maps
    (bottleneck 1x1 -> 3x3) onto the running feature stack."""

    layers: int
    growth: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        for i in range(self.layers):
            h = nn.relu(norm(name=f"l{i}_bn1")(x))
            h = nn.Conv(4 * self.growth, (1, 1), use_bias=False,
                        dtype=self.dtype, name=f"l{i}_conv1")(h)
            h = nn.relu(norm(name=f"l{i}_bn2")(h))
            h = nn.Conv(self.growth, (3, 3), use_bias=False,
                        dtype=self.dtype, name=f"l{i}_conv2")(h)
            x = jnp.concatenate([x, h], axis=-1)
        return x


class DenseNet(nn.Module):
    """DenseNet-BC (ref model-zoo family: image_classifier.py
    "densenet-161"; default config = DenseNet-121, ``densenet161()``
    below builds the reference's 161 variant)."""

    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (6, 12, 24, 16)  # DenseNet-121
    growth: int = 32
    stem_features: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        x = nn.Conv(self.stem_features, (7, 7), (2, 2), use_bias=False,
                    dtype=self.dtype, name="stem_conv")(x)
        x = nn.relu(norm(name="stem_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, layers in enumerate(self.stage_sizes):
            x = _DenseBlock(layers, self.growth, dtype=self.dtype,
                            name=f"dense{s + 1}")(x, train=train)
            if s < len(self.stage_sizes) - 1:  # transition: halve C, HW
                x = nn.relu(norm(name=f"trans{s + 1}_bn")(x))
                x = nn.Conv(x.shape[-1] // 2, (1, 1), use_bias=False,
                            dtype=self.dtype,
                            name=f"trans{s + 1}_conv")(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm(name="final_bn")(x))
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


def densenet161(num_classes: int = 1000, dtype: Any = jnp.float32):
    """The reference's DenseNet-161 (growth 48, deeper stages)."""
    return DenseNet(num_classes=num_classes,
                    stage_sizes=(6, 12, 36, 24), growth=48,
                    stem_features=96, dtype=dtype)


class _InvertedResidual(nn.Module):
    """MobileNet v2 block: 1x1 expand -> depthwise 3x3 -> 1x1 project,
    residual when stride 1 and shapes match; relu6 activations."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    expand_ratio: int = 6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(train, self.dtype)
        inp = x.shape[-1]
        h = x
        if self.expand_ratio != 1:
            h = nn.Conv(inp * self.expand_ratio, (1, 1), use_bias=False,
                        dtype=self.dtype, name="expand")(h)
            h = jnp.clip(norm(name="expand_bn")(h), 0, 6)
        c = h.shape[-1]
        h = nn.Conv(c, (3, 3), self.strides, use_bias=False,
                    feature_group_count=c, dtype=self.dtype,
                    name="dw")(h)
        h = jnp.clip(norm(name="dw_bn")(h), 0, 6)
        h = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="project")(h)
        h = norm(name="project_bn")(h)
        if self.strides == (1, 1) and inp == self.filters:
            return x + h
        return h


class MobileNetV2(nn.Module):
    """MobileNet v2 (ref model-zoo family: image_classifier.py
    "mobilenet-v2")."""

    num_classes: int = 1000
    width: float = 1.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(f):
            return max(8, int(f * self.width))

        norm = _norm(train, self.dtype)
        x = nn.Conv(w(32), (3, 3), (2, 2), use_bias=False,
                    dtype=self.dtype, name="stem")(x)
        x = jnp.clip(norm(name="stem_bn")(x), 0, 6)
        # (expand_ratio, filters, repeats, first_stride)
        plan = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
                (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
                (6, 320, 1, 1)]
        idx = 0
        for t, f, reps, s0 in plan:
            for r in range(reps):
                x = _InvertedResidual(
                    w(f), (s0, s0) if r == 0 else (1, 1),
                    expand_ratio=t, dtype=self.dtype,
                    name=f"block{idx}")(x, train=train)
                idx += 1
        x = nn.Conv(max(1280, w(1280)), (1, 1), use_bias=False,
                    dtype=self.dtype, name="head_conv")(x)
        x = jnp.clip(norm(name="head_bn")(x), 0, 6)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)


class _ConvBN(nn.Module):
    filters: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        return nn.relu(_norm(train, self.dtype)(name="bn")(x))


class _MixedA(nn.Module):
    """Inception-v3 35x35 block: 1x1 | 5x5 | double-3x3 | pool-proj."""

    pool_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(_ConvBN, dtype=self.dtype)
        b1 = cb(64, (1, 1), name="b1")(x, train)
        b5 = cb(48, (1, 1), name="b5_1")(x, train)
        b5 = cb(64, (5, 5), name="b5_2")(b5, train)
        b3 = cb(64, (1, 1), name="b3_1")(x, train)
        b3 = cb(96, (3, 3), name="b3_2")(b3, train)
        b3 = cb(96, (3, 3), name="b3_3")(b3, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cb(self.pool_features, (1, 1), name="bp")(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class _MixedB(nn.Module):
    """Inception-v3 35->17 reduction."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(_ConvBN, dtype=self.dtype)
        b3 = cb(384, (3, 3), (2, 2), padding="VALID",
                name="b3")(x, train)
        bd = cb(64, (1, 1), name="bd_1")(x, train)
        bd = cb(96, (3, 3), name="bd_2")(bd, train)
        bd = cb(96, (3, 3), (2, 2), padding="VALID",
                name="bd_3")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class _MixedC(nn.Module):
    """Inception-v3 17x17 block with factorized 7x1/1x7 convs."""

    c7: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(_ConvBN, dtype=self.dtype)
        b1 = cb(192, (1, 1), name="b1")(x, train)
        b7 = cb(self.c7, (1, 1), name="b7_1")(x, train)
        b7 = cb(self.c7, (1, 7), name="b7_2")(b7, train)
        b7 = cb(192, (7, 1), name="b7_3")(b7, train)
        bd = cb(self.c7, (1, 1), name="bd_1")(x, train)
        bd = cb(self.c7, (7, 1), name="bd_2")(bd, train)
        bd = cb(self.c7, (1, 7), name="bd_3")(bd, train)
        bd = cb(self.c7, (7, 1), name="bd_4")(bd, train)
        bd = cb(192, (1, 7), name="bd_5")(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cb(192, (1, 1), name="bp")(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class _MixedD(nn.Module):
    """Inception-v3 17->8 reduction."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(_ConvBN, dtype=self.dtype)
        b3 = cb(192, (1, 1), name="b3_1")(x, train)
        b3 = cb(320, (3, 3), (2, 2), padding="VALID",
                name="b3_2")(b3, train)
        b7 = cb(192, (1, 1), name="b7_1")(x, train)
        b7 = cb(192, (1, 7), name="b7_2")(b7, train)
        b7 = cb(192, (7, 1), name="b7_3")(b7, train)
        b7 = cb(192, (3, 3), (2, 2), padding="VALID",
                name="b7_4")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class _MixedE(nn.Module):
    """Inception-v3 8x8 block with split 1x3/3x1 branches."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(_ConvBN, dtype=self.dtype)
        b1 = cb(320, (1, 1), name="b1")(x, train)
        b3 = cb(384, (1, 1), name="b3_1")(x, train)
        b3 = jnp.concatenate(
            [cb(384, (1, 3), name="b3_a")(b3, train),
             cb(384, (3, 1), name="b3_b")(b3, train)], axis=-1)
        bd = cb(448, (1, 1), name="bd_1")(x, train)
        bd = cb(384, (3, 3), name="bd_2")(bd, train)
        bd = jnp.concatenate(
            [cb(384, (1, 3), name="bd_a")(bd, train),
             cb(384, (3, 1), name="bd_b")(bd, train)], axis=-1)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cb(192, (1, 1), name="bp")(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Inception-v3 (ref model-zoo family: image_classifier.py
    "inception-v3"): factorized-conv mixed blocks; aux head omitted
    (BN training does not need it -- same stance as InceptionV1)."""

    num_classes: int = 1000
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(_ConvBN, dtype=self.dtype)
        x = cb(32, (3, 3), (2, 2), padding="VALID",
               name="stem1")(x, train)
        x = cb(32, (3, 3), padding="VALID", name="stem2")(x, train)
        x = cb(64, (3, 3), name="stem3")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cb(80, (1, 1), name="stem4")(x, train)
        x = cb(192, (3, 3), padding="VALID", name="stem5")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        for i, pf in enumerate((32, 64, 64)):
            x = _MixedA(pf, dtype=self.dtype,
                        name=f"mixedA{i}")(x, train=train)
        x = _MixedB(dtype=self.dtype, name="mixedB")(x, train=train)
        for i, c7 in enumerate((128, 160, 160, 192)):
            x = _MixedC(c7, dtype=self.dtype,
                        name=f"mixedC{i}")(x, train=train)
        x = _MixedD(dtype=self.dtype, name="mixedD")(x, train=train)
        for i in range(2):
            x = _MixedE(dtype=self.dtype,
                        name=f"mixedE{i}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x)
