"""Faster-RCNN-style two-stage detector, redesigned for static shapes.

The analog of the reference's Faster-RCNN load-and-predict family
(ref: zoo/src/main/scala/com/intel/analytics/zoo/models/image/
objectdetection/ -- ObjectDetector.loadModel ships pretrained
"frcnn-vgg16"/"frcnn-pvanet" graphs driven by Predictor.scala, with
proposal/ROI layers in the BigDL graph). A literal port would be
hostile to XLA: proposal generation and per-ROI pooling are
dynamic-shape ops. The TPU-native redesign keeps every stage static:

- the RPN scores one anchor set on a single feature map and takes a
  FIXED top-K of proposals with ``lax.top_k`` (no objectness-threshold
  filtering, no proposal NMS -- K is a compile-time constant);
- ROI-align is a gather-based bilinear crop vmapped over the K
  proposals (static [K, P, P, C] output);
- the second stage classifies all K proposals at once; per-class NMS
  happens host-side on the decoded [K, C+1] scores like SSD.

So the whole two-stage forward is ONE jittable program; only the final
suppression touches numpy.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.models.image.detection import (
    clip_boxes, decode_boxes, detect_per_class)


def rpn_anchors(image_size: int, stride: int,
                scales: Sequence[float] = (0.15, 0.3, 0.55),
                ratios: Sequence[float] = (0.5, 1.0, 2.0)) -> np.ndarray:
    """Dense single-level anchor grid [H*W*A, 4] (x1y1x2y2 pixels)."""
    fsize = -(-image_size // stride)
    out: List[Tuple[float, float, float, float]] = []
    for i, j in itertools.product(range(fsize), repeat=2):
        cx, cy = (j + 0.5) * stride, (i + 0.5) * stride
        for s in scales:
            for r in ratios:
                w = s * image_size * float(np.sqrt(r))
                h = s * image_size / float(np.sqrt(r))
                out.append((cx - w / 2, cy - h / 2,
                            cx + w / 2, cy + h / 2))
    return np.asarray(out, np.float32)


def roi_align(features: jnp.ndarray, boxes: jnp.ndarray, stride: int,
              pool: int = 7) -> jnp.ndarray:
    """Gather-based bilinear ROI-align.

    features: [H, W, C] one image's feature map; boxes: [K, 4] in image
    pixels. Returns [K, pool, pool, C]. Sampling grid is ``pool`` x
    ``pool`` box-center points (one sample per bin); gathers + lerp
    only -- no dynamic shapes, vmap over K.
    """
    fh, fw = features.shape[0], features.shape[1]

    def one(box):
        x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
        # bin centers in feature-map coordinates
        xs = (x1 + (x2 - x1) * (jnp.arange(pool) + 0.5) / pool) / stride
        ys = (y1 + (y2 - y1) * (jnp.arange(pool) + 0.5) / pool) / stride
        xs = jnp.clip(xs - 0.5, 0.0, fw - 1.0)
        ys = jnp.clip(ys - 0.5, 0.0, fh - 1.0)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, fw - 2)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, fh - 2)
        wx = (xs - x0)[None, :, None]
        wy = (ys - y0)[:, None, None]
        f00 = features[y0][:, x0]          # [P, P, C]
        f01 = features[y0][:, x0 + 1]
        f10 = features[y0 + 1][:, x0]
        f11 = features[y0 + 1][:, x0 + 1]
        top = f00 * (1 - wx) + f01 * wx
        bot = f10 * (1 - wx) + f11 * wx
        return top * (1 - wy) + bot * wy

    return jax.vmap(one)(boxes)


class _ConvBNRelu(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (3, 3),
                    strides=(self.stride, self.stride),
                    use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        return nn.relu(x)


class FasterRCNNModule(nn.Module):
    """Backbone + RPN + top-K proposals + ROI-align + box head.

    Input [B, S, S, 3] -> (proposals [B, K, 4] pixels,
    class_logits [B, K, C+1], box_deltas [B, K, 4]); column 0 of the
    class axis is background (reference output contract).
    """

    class_num: int
    image_size: int = 128
    width: int = 64
    top_k: int = 64
    pool: int = 7
    anchors: Any = None            # np [N, 4], baked by the wrapper

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        stride = 8
        h = _ConvBNRelu(self.width // 2)(x, train=train)
        h = _ConvBNRelu(self.width // 2, stride=2)(h, train=train)
        h = _ConvBNRelu(self.width, stride=2)(h, train=train)
        feat = _ConvBNRelu(self.width, stride=2)(h, train=train)

        n_anchor = 9  # 3 scales x 3 ratios (rpn_anchors defaults)
        rpn = nn.Conv(self.width, (3, 3), padding="SAME",
                      name="rpn_conv")(feat)
        rpn = nn.relu(rpn)
        obj = nn.Conv(n_anchor, (1, 1), name="rpn_obj")(rpn)
        dlt = nn.Conv(n_anchor * 4, (1, 1), name="rpn_delta")(rpn)
        obj = obj.reshape(b, -1)                    # [B, N]
        dlt = dlt.reshape(b, -1, 4)                 # [B, N, 4]

        anchors = jnp.asarray(self.anchors)         # [N, 4]
        _, idx = jax.lax.top_k(obj, self.top_k)     # [B, K] static
        sel_anchor = jnp.take(anchors, idx, axis=0)  # [B, K, 4]
        sel_delta = jnp.take_along_axis(
            dlt, idx[..., None], axis=1)            # [B, K, 4]

        # decode proposals on device (same math as detection.decode_boxes)
        aw = sel_anchor[..., 2] - sel_anchor[..., 0]
        ah = sel_anchor[..., 3] - sel_anchor[..., 1]
        acx = sel_anchor[..., 0] + 0.5 * aw
        acy = sel_anchor[..., 1] + 0.5 * ah
        cx = acx + sel_delta[..., 0] * 0.1 * aw
        cy = acy + sel_delta[..., 1] * 0.1 * ah
        w = aw * jnp.exp(jnp.clip(sel_delta[..., 2] * 0.2, -4, 4))
        hh = ah * jnp.exp(jnp.clip(sel_delta[..., 3] * 0.2, -4, 4))
        proposals = jnp.stack(
            [cx - w / 2, cy - hh / 2, cx + w / 2, cy + hh / 2], axis=-1)
        proposals = jnp.clip(proposals, 0.0, float(self.image_size))

        pooled = jax.vmap(
            lambda f, bx: roi_align(f, bx, stride, self.pool)
        )(feat, proposals)                          # [B, K, P, P, C]
        flat = pooled.reshape(b, self.top_k, -1)
        hdn = nn.Dense(256, name="head_fc1")(flat)
        hdn = nn.relu(hdn)
        cls = nn.Dense(self.class_num + 1, name="head_cls")(hdn)
        box = nn.Dense(4, name="head_box")(hdn)     # class-agnostic
        return proposals, cls, box


@register_model
class FasterRCNN(ZooModel):
    """Two-stage load-and-predict pipeline (ref: the objectdetection
    Faster-RCNN family driven by Predictor.scala). ``detect`` refines
    the K proposals with the head deltas and runs per-class NMS."""

    default_loss = None
    default_optimizer = "adam"

    def __init__(self, class_num: int, image_size: int = 128,
                 width: int = 64, top_k: int = 64, pool: int = 7,
                 label_map: Optional[Dict[Any, str]] = None):
        self._label_map = {int(k): v
                           for k, v in (label_map or {}).items()}
        # before super().__init__: ZooModel builds the module eagerly
        self.anchors = rpn_anchors(image_size, stride=8)
        super().__init__(class_num=class_num, image_size=image_size,
                         width=width, top_k=top_k, pool=pool,
                         label_map={str(k): v for k, v in
                                    (label_map or {}).items()})

    def _build_module(self):
        c = self._config
        return FasterRCNNModule(
            class_num=c["class_num"], image_size=c["image_size"],
            width=c["width"], top_k=c["top_k"], pool=c["pool"],
            anchors=self.anchors)

    def _example_input(self):
        s = self._config["image_size"]
        return np.zeros((1, s, s, 3), np.float32)

    def detect(self, images: np.ndarray, batch_size: int = 8,
               score_threshold: float = 0.3, iou_threshold: float = 0.45,
               top_k: int = 100
               ) -> List[List[Tuple[int, float, np.ndarray]]]:
        proposals, cls_logits, box_deltas = self.estimator.predict(
            np.asarray(images, np.float32), batch_size=batch_size)
        probs = np.asarray(jax.nn.softmax(jnp.asarray(cls_logits), -1))
        proposals = np.asarray(proposals)
        deltas = np.asarray(box_deltas)
        size = self._config["image_size"]
        results = []
        for b in range(probs.shape[0]):
            boxes = clip_boxes(
                decode_boxes(proposals[b], deltas[b]), size, size)
            results.append(detect_per_class(
                boxes, probs[b], score_threshold=score_threshold,
                iou_threshold=iou_threshold, top_k=top_k))
        return results

    def label_of(self, class_id: int) -> str:
        return self._label_map.get(class_id, str(class_id))
