"""Object-detection post-processing utilities.

The analog of ``BboxUtil``/NMS in the reference's object-detection predict
path (ref: zoo/.../models/image/objectdetection/common/BboxUtil.scala,
Nms.scala -- the reference ships pretrained SSD/Faster-RCNN for
load-and-predict; the shared geometry/suppression math lives here,
jit-friendly, with ``Visualizer``-style output decoding).

Boxes are [x1, y1, x2, y2] in pixel or normalized coordinates.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def bbox_iou(boxes_a: np.ndarray, boxes_b: np.ndarray) -> np.ndarray:
    """Pairwise IoU: [N, 4] x [M, 4] -> [N, M]
    (ref: BboxUtil.scala getIoURate/jaccardOverlap)."""
    a = np.asarray(boxes_a, np.float32)[:, None]
    b = np.asarray(boxes_b, np.float32)[None]
    lt = np.maximum(a[..., :2], b[..., :2])
    rb = np.minimum(a[..., 2:], b[..., 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = ((a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1]))
    area_b = ((b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1]))
    union = area_a + area_b - inter
    return inter / np.maximum(union, 1e-9)


def nms(boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.45,
        top_k: int = 200) -> np.ndarray:
    """Greedy non-maximum suppression; returns kept indices sorted by
    descending score (ref: objectdetection/common/Nms.scala)."""
    boxes = np.asarray(boxes, np.float32)
    scores = np.asarray(scores, np.float32)
    order = np.argsort(-scores)
    keep: List[int] = []
    while order.size and len(keep) < top_k:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        ious = bbox_iou(boxes[i:i + 1], boxes[order[1:]])[0]
        order = order[1:][ious <= iou_threshold]
    return np.asarray(keep, np.int64)


def decode_boxes(anchors: np.ndarray, deltas: np.ndarray,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> np.ndarray:
    """SSD-style box regression decode: anchors [N,4] (x1y1x2y2) +
    deltas [N,4] (dx,dy,dw,dh) -> boxes [N,4]
    (ref: BboxUtil.scala decodeBoxes)."""
    anchors = np.asarray(anchors, np.float32)
    deltas = np.asarray(deltas, np.float32)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    cx = acx + deltas[:, 0] * variances[0] * aw
    cy = acy + deltas[:, 1] * variances[1] * ah
    w = aw * np.exp(deltas[:, 2] * variances[2])
    h = ah * np.exp(deltas[:, 3] * variances[3])
    return np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                    axis=1)


def encode_boxes(anchors: np.ndarray, gt_boxes: np.ndarray,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> np.ndarray:
    """Inverse of :func:`decode_boxes`: per-anchor regression targets
    for matched ground-truth boxes (ref: BboxUtil.scala encodeBoxes).
    anchors/gt_boxes: [N, 4] x1y1x2y2 -> deltas [N, 4]."""
    anchors = np.asarray(anchors, np.float32)
    gt = np.asarray(gt_boxes, np.float32)
    aw = np.maximum(anchors[:, 2] - anchors[:, 0], 1e-6)
    ah = np.maximum(anchors[:, 3] - anchors[:, 1], 1e-6)
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    gw = np.maximum(gt[:, 2] - gt[:, 0], 1e-6)
    gh = np.maximum(gt[:, 3] - gt[:, 1], 1e-6)
    gcx = gt[:, 0] + 0.5 * gw
    gcy = gt[:, 1] + 0.5 * gh
    return np.stack([
        (gcx - acx) / aw / variances[0],
        (gcy - acy) / ah / variances[1],
        np.log(gw / aw) / variances[2],
        np.log(gh / ah) / variances[3],
    ], axis=1)


def match_anchors(anchors: np.ndarray, gt_boxes: np.ndarray,
                  gt_labels: np.ndarray, iou_threshold: float = 0.5,
                  variances=(0.1, 0.1, 0.2, 0.2)
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """SSD bipartite + per-prediction matching (ref: BboxUtil.scala
    matchBbox): every ground truth claims its best anchor; every other
    anchor joins its best-IoU ground truth when IoU >= threshold.

    gt_labels are FOREGROUND ids (>= 1); 0 marks background.
    Returns per-anchor (class_targets [N] int32, box_targets [N, 4]).
    Host-side numpy: runs in the input pipeline, so XLA only ever sees
    the static [N]/[N, 4] targets.
    """
    n = anchors.shape[0]
    cls_t = np.zeros((n,), np.int32)
    box_t = np.zeros((n, 4), np.float32)
    gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
    if gt_boxes.shape[0] == 0:
        return cls_t, box_t
    gt_labels = np.asarray(gt_labels, np.int32).reshape(-1)
    iou = bbox_iou(anchors, gt_boxes)            # [N, G]
    best_gt = iou.argmax(axis=1)                 # per anchor
    best_iou = iou[np.arange(n), best_gt]
    matched = best_iou >= iou_threshold
    # bipartite pass: each gt forces its single best anchor positive
    forced = iou.argmax(axis=0)                  # per gt
    matched[forced] = True
    best_gt[forced] = np.arange(gt_boxes.shape[0])
    cls_t[matched] = gt_labels[best_gt[matched]]
    box_t[matched] = encode_boxes(anchors[matched],
                                  gt_boxes[best_gt[matched]], variances)
    return cls_t, box_t


def clip_boxes(boxes: np.ndarray, height: float, width: float) -> np.ndarray:
    """(ref: BboxUtil.scala clipBoxes)."""
    boxes = np.asarray(boxes, np.float32).copy()
    boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, width)
    boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, height)
    return boxes


def detect_per_class(boxes: np.ndarray, class_scores: np.ndarray,
                     score_threshold: float = 0.3,
                     iou_threshold: float = 0.45, top_k: int = 100
                     ) -> List[Tuple[int, float, np.ndarray]]:
    """Full detection post-processing: per-class threshold + NMS, merged
    and sorted (ref: objectdetection DetectionOutput* postprocessing).
    class_scores: [N, C] including background at column 0.
    Returns [(class_id, score, box)] sorted by score."""
    out: List[Tuple[int, float, np.ndarray]] = []
    n_classes = class_scores.shape[1]
    for c in range(1, n_classes):
        sc = class_scores[:, c]
        sel = sc >= score_threshold
        if not sel.any():
            continue
        keep = nms(boxes[sel], sc[sel], iou_threshold, top_k)
        idx = np.nonzero(sel)[0][keep]
        out.extend((c, float(class_scores[i, c]), boxes[i]) for i in idx)
    out.sort(key=lambda t: -t[1])
    return out[:top_k]
