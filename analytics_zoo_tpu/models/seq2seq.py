"""Seq2seq encoder-decoder model.

The analog of ``Seq2seq`` (ref: zoo/.../models/seq2seq/Seq2seq.scala --
RNNEncoder/RNNDecoder/Bridge; pyzoo/zoo/models/seq2seq): stacked-LSTM
encoder, state bridge (direct pass or dense projection), stacked-LSTM
decoder with teacher forcing for training and greedy ``infer`` for
generation. Token-id sequences; id 0 is padding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model


class Seq2seqNet(nn.Module):
    vocab: int
    embed_dim: int
    hidden_sizes: Tuple[int, ...]
    bridge: str = "pass"  # "pass" | "dense"

    @nn.compact
    def __call__(self, x):
        """Teacher-forced forward: {"src": [B, Ls], "tgt_in": [B, Lt]}
        -> logits [B, Lt, vocab+1]."""
        if isinstance(x, dict):
            src, tgt_in = x["src"], x["tgt_in"]
        else:
            src, tgt_in = x
        embed = nn.Embed(self.vocab + 1, self.embed_dim, name="embed")
        h = embed(src.astype(jnp.int32))
        states = []
        for i, hsz in enumerate(self.hidden_sizes):
            carry, h = nn.RNN(nn.OptimizedLSTMCell(hsz),
                              return_carry=True, name=f"enc_{i}")(h)
            states.append(carry)
        if self.bridge == "dense":
            states = [
                (jnp.tanh(nn.Dense(hsz, name=f"bridge_c_{i}")(c)),
                 jnp.tanh(nn.Dense(hsz, name=f"bridge_h_{i}")(hh)))
                for i, (hsz, (c, hh)) in enumerate(
                    zip(self.hidden_sizes, states))]
        d = embed(tgt_in.astype(jnp.int32))
        for i, hsz in enumerate(self.hidden_sizes):
            d = nn.RNN(nn.OptimizedLSTMCell(hsz), name=f"dec_{i}")(
                d, initial_carry=states[i])
        return nn.Dense(self.vocab + 1, name="head")(d)


@register_model
class Seq2seq(ZooModel):
    """(ref: Seq2seq.scala). Train on {"src", "tgt_in"} -> labels
    ``tgt_out`` (the target shifted by one)."""

    default_optimizer = "adam"

    @staticmethod
    def default_loss(preds, labels):
        """Padding-masked CE over the time dimension."""
        labels = jnp.asarray(labels).astype(jnp.int32)
        logp = jax.nn.log_softmax(preds, -1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        mask = (labels > 0).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def __init__(self, vocab: int, embed_dim: int = 128,
                 hidden_sizes=(128,), bridge: str = "pass",
                 max_len: int = 32):
        super().__init__(vocab=vocab, embed_dim=embed_dim,
                         hidden_sizes=list(hidden_sizes), bridge=bridge,
                         max_len=max_len)

    def _build_module(self):
        c = self._config
        return Seq2seqNet(vocab=c["vocab"], embed_dim=c["embed_dim"],
                          hidden_sizes=tuple(c["hidden_sizes"]),
                          bridge=c["bridge"])

    def _example_input(self):
        return {"src": np.ones((1, 4), np.int32),
                "tgt_in": np.ones((1, 4), np.int32)}

    def infer(self, src, start_id: int, max_len: Optional[int] = None,
              host_loop: bool = False):
        """Greedy generation (ref: Seq2seq.scala infer).

        Default: the whole greedy loop runs on-device inside ONE
        jitted ``lax.fori_loop`` -- one dispatch per call instead of
        one per emitted token (the ISSUE-10 satellite fix: the old
        host loop paid ``max_len`` python->device round trips, which
        dominated wall time on remote-device runtimes). One compile
        per (batch, max_len) shape, cached on the model.

        ``host_loop=True`` keeps the original per-token host loop --
        the parity reference of ``tests/test_generation.py`` and the
        escape hatch for duck-typed modules jit can't trace.
        """
        max_len = max_len or self._config["max_len"]
        src = np.asarray(src, np.int32)
        est = self.estimator
        est._ensure_built({"src": src[:1], "tgt_in": src[:1, :1]})
        module = self.module

        if host_loop:
            @jax.jit
            def step(variables, src, tgt_in):
                return module.apply(variables,
                                    {"src": src, "tgt_in": tgt_in})

            b = src.shape[0]
            tgt_in = np.zeros((b, max_len), np.int32)
            tgt_in[:, 0] = start_id
            out = np.zeros((b, max_len), np.int32)
            for t in range(max_len):
                logits = np.asarray(step(est.variables, src, tgt_in))
                tok = logits[:, t].argmax(-1).astype(np.int32)
                out[:, t] = tok
                if t + 1 < max_len:
                    tgt_in[:, t + 1] = tok
            return out

        fns = self.__dict__.setdefault("_infer_fns", {})
        gen = fns.get(max_len)
        if gen is None:
            def gen_impl(variables, src_dev, start):
                b = src_dev.shape[0]
                # buffer one column wider than the window so the
                # unconditional write at t+1 never needs a bounds
                # branch; the forward always sees buf[:, :max_len]
                buf0 = jnp.zeros((b, max_len + 1),
                                 jnp.int32).at[:, 0].set(start)
                out0 = jnp.zeros((b, max_len), jnp.int32)

                def body(t, carry):
                    buf, out = carry
                    logits = module.apply(
                        variables,
                        {"src": src_dev,
                         "tgt_in": jax.lax.slice_in_dim(
                             buf, 0, max_len, axis=1)})
                    tok = jnp.argmax(logits[:, t], -1).astype(
                        jnp.int32)
                    return (buf.at[:, t + 1].set(tok),
                            out.at[:, t].set(tok))

                _, out = jax.lax.fori_loop(0, max_len, body,
                                           (buf0, out0))
                return out

            gen = fns[max_len] = jax.jit(gen_impl)
        return np.asarray(gen(est.variables, src,
                              jnp.int32(start_id)))
