"""Data layer: sharded datasets feeding the SPMD training engine.

The analog of the reference's three data stacks (SURVEY.md L2):
- ``TFDataset``   (pyzoo/zoo/tfpark/tf_dataset.py)  -> :class:`ZooDataset`
- ``XShards``     (pyzoo/zoo/orca/data/shard.py)    -> :class:`XShards`
- ``FeatureSet``  (zoo/.../feature/FeatureSet.scala) -> memory-tier caching
  on :class:`ZooDataset` (DRAM / DISK_AND_DRAM via memmap; the PMEM tier's
  role -- datasets bigger than RAM -- is served by the disk tier).

One abstraction instead of three: a ZooDataset yields *global* batches as
host numpy, and the engine places them onto the mesh (`shard_batch`).
Per-host sharding for multi-host runs happens at iteration time, mirroring
how TFDataset ships RDD partitions to executors.
"""

from analytics_zoo_tpu.data.shard import XShards  # noqa: F401
from analytics_zoo_tpu.data.dataset import ZooDataset  # noqa: F401
from analytics_zoo_tpu.data.sources import (  # noqa: F401
    read_csv,
    read_json,
    read_parquet,
    read_image_folder,
    read_tfrecord,
)
