"""XShards: a partitioned collection of data shards.

The analog of Orca's ``XShards``/``SparkXShards``
(ref: pyzoo/zoo/orca/data/shard.py:26-541 -- ``partition``,
``transform_shard``, ``collect``, ``num_partitions``, ``repartition``,
``zip``). Where the reference moves shards between Spark partitions and
Ray plasma, here shards are host-resident (numpy / pandas) and transforms
run on a thread pool -- device placement is the engine's job, and heavy
per-shard math belongs in jitted functions, not in the shard transform.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class XShards:
    """A list of shards; each shard is any python object (typically a dict
    of ndarrays or a pandas DataFrame)."""

    def __init__(self, shards: Sequence[Any]):
        if not shards:
            raise ValueError("XShards needs at least one shard")
        self._shards: List[Any] = list(shards)

    # ------------------------------------------------------ construction --
    @staticmethod
    def partition(data: Any, num_shards: Optional[int] = None) -> "XShards":
        """Split a dict-of-ndarrays / ndarray / DataFrame into shards
        (ref: shard.py:65 ``zoo.orca.data.XShards.partition``)."""
        import pandas as pd

        num_shards = num_shards or _default_num_shards()

        if isinstance(data, np.ndarray):
            return XShards(np.array_split(data, num_shards))
        if isinstance(data, pd.DataFrame):
            idx = np.array_split(np.arange(len(data)), num_shards)
            return XShards([data.iloc[i] for i in idx])
        if isinstance(data, dict):
            keys = list(data.keys())
            arrays = [np.asarray(data[k]) for k in keys]
            n = arrays[0].shape[0]
            if any(a.shape[0] != n for a in arrays):
                raise ValueError("all arrays must share the leading dim")
            idx = np.array_split(np.arange(n), num_shards)
            return XShards([{k: a[i] for k, a in zip(keys, arrays)}
                            for i in idx])
        if isinstance(data, (list, tuple)):
            arrays = [np.asarray(a) for a in data]
            n = arrays[0].shape[0]
            if any(a.shape[0] != n for a in arrays):
                raise ValueError("all arrays must share the leading dim")
            idx = np.array_split(np.arange(n), num_shards)
            return XShards([type(data)(a[i] for a in arrays) for i in idx])
        raise TypeError(f"cannot partition {type(data)}")

    # -------------------------------------------------------- transforms --
    def transform_shard(self, fn: Callable, *args) -> "XShards":
        """Apply ``fn(shard, *args)`` to every shard in parallel
        (ref: shard.py transform_shard)."""
        with ThreadPoolExecutor(max_workers=min(len(self._shards), 16)) as ex:
            return XShards(list(ex.map(lambda s: fn(s, *args),
                                       self._shards)))

    def zip(self, other: "XShards") -> "XShards":
        if other.num_partitions() != self.num_partitions():
            raise ValueError("zip requires equal partition counts")
        return XShards(list(zip(self._shards, other._shards)))

    def repartition(self, num_shards: int) -> "XShards":
        merged = self._merge(self.collect())
        return XShards.partition(merged, num_shards)

    # ------------------------------------------------------------ access --
    def collect(self) -> List[Any]:
        return list(self._shards)

    def num_partitions(self) -> int:
        return len(self._shards)

    def __len__(self) -> int:
        def shard_len(s) -> int:
            if isinstance(s, dict):
                return len(next(iter(s.values())))
            if isinstance(s, (list, tuple)) and len(s) and \
                    isinstance(s[0], np.ndarray):
                return len(s[0])
            if hasattr(s, "__len__"):
                return len(s)
            raise TypeError(f"shard of {type(s)} has no length")

        return sum(shard_len(s) for s in self._shards)

    def merged(self) -> Any:
        """Concatenate all shards back into one object."""
        return self._merge(self._shards)

    @staticmethod
    def _merge(shards: List[Any]) -> Any:
        import pandas as pd

        first = shards[0]
        if isinstance(first, np.ndarray):
            return np.concatenate(shards)
        if isinstance(first, pd.DataFrame):
            return pd.concat(shards, ignore_index=True)
        if isinstance(first, dict):
            return {k: np.concatenate([s[k] for s in shards])
                    for k in first.keys()}
        if isinstance(first, (list, tuple)):
            return type(first)(np.concatenate([s[i] for s in shards])
                               for i in range(len(first)))
        raise TypeError(f"cannot merge shards of {type(first)}")

    def to_dataset(self, **kwargs):
        """Materialize into a ZooDataset for training."""
        from analytics_zoo_tpu.data.dataset import ZooDataset

        return ZooDataset.from_xshards(self, **kwargs)


def _default_num_shards() -> int:
    import jax

    return max(jax.local_device_count(), 2)
