"""ZooDataset: the training-facing sharded dataset.

Analog of ``TFDataset`` (ref: pyzoo/zoo/tfpark/tf_dataset.py:115-1279) +
``FeatureSet`` memory tiers (ref: zoo/.../feature/FeatureSet.scala:644-683).

Contracts carried over from the reference:
- global batch size must divide evenly over the parallel workers
  (ref: tf_dataset.py:142-147 enforces ``batch_size % total_cores == 0``);
  here: over the mesh's data-axis size, checked in :meth:`batches`.
- datasets can be cached in DRAM or spilled to disk
  (``memory_type="DRAM" | "DISK"``; the reference's PMEM tier serves the
  same larger-than-RAM role, ref: FeatureSet.scala memoryType).
- deterministic epoch shuffling with a seed, sequential order optional
  (ref: FeatureSet ``sequentialOrder``/``shuffle`` flags).

Yields *host-local* numpy batches; ``device_iterator`` additionally places
them on the mesh (sharded along the data axis) with one-batch lookahead so
host->HBM transfer overlaps the train step.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)


def _tree_map(fn, tree):
    return jax.tree_util.tree_map(fn, tree)


def _leading_dim(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        raise ValueError("empty pytree")
    n = leaves[0].shape[0]
    for l in leaves:
        if l.shape[0] != n:
            raise ValueError("all arrays must share the leading dim")
    return n


def _take_chunked(tree, idx, memory_type: str, cache_dir: str,
                  chunk: int = 65536):
    """Index-select rows from a pytree; DISK tier streams through a new
    memmap in chunks so selection never materializes fully in RAM."""
    if memory_type != "DISK":
        return _tree_map(lambda a: np.asarray(a)[idx], tree)
    os.makedirs(cache_dir, exist_ok=True)
    counter = [0]

    def take(a):
        path = os.path.join(cache_dir, f"arr_{counter[0]}.npy")
        counter[0] += 1
        out = np.lib.format.open_memmap(
            path, mode="w+", dtype=a.dtype, shape=(len(idx),) + a.shape[1:])
        for s in range(0, len(idx), chunk):
            sel = idx[s:s + chunk]
            out[s:s + len(sel)] = a[sel]
        out.flush()
        return np.load(path, mmap_mode="r")

    return _tree_map(take, tree)


def _spill_to_disk(tree, cache_dir: str):
    """Replace each array with a read-only memmap backed by ``cache_dir``."""
    os.makedirs(cache_dir, exist_ok=True)
    counter = [0]

    def spill(x):
        x = np.asarray(x)
        path = os.path.join(cache_dir, f"arr_{counter[0]}.npy")
        counter[0] += 1
        np.save(path, x)
        return np.load(path, mmap_mode="r")

    return _tree_map(spill, tree)


class ZooDataset:
    """An in-memory (or disk-tiered) dataset of features + optional labels.

    ``features`` / ``labels`` are pytrees (array, dict, or tuple of arrays)
    sharing a leading sample dimension.
    """

    def __init__(self, features: Any, labels: Any = None,
                 memory_type: str = "DRAM",
                 cache_dir: Optional[str] = None):
        memory_type = memory_type.upper()
        if memory_type not in ("DRAM", "DISK"):
            raise ValueError(
                f"memory_type must be DRAM or DISK, got {memory_type!r}")
        features = _tree_map(np.asarray, features)
        labels = _tree_map(np.asarray, labels) if labels is not None else None
        self._n = _leading_dim(features)
        if labels is not None and _leading_dim(labels) != self._n:
            raise ValueError("features and labels disagree on sample count")
        if memory_type == "DISK":
            owned = cache_dir is None
            cache_dir = cache_dir or tempfile.mkdtemp(prefix="zoo_dataset_")
            features = _spill_to_disk(features, os.path.join(cache_dir, "x"))
            if labels is not None:
                labels = _spill_to_disk(labels, os.path.join(cache_dir, "y"))
            logger.info("dataset spilled to disk tier at %s", cache_dir)
            if owned:
                self._own_cache_dir(cache_dir)
        self.features = features
        self.labels = labels
        self.memory_type = memory_type

    def _own_cache_dir(self, cache_dir: str) -> None:
        """Delete a framework-created spill dir when the dataset is GC'd
        (user-supplied cache_dirs are never touched)."""
        import shutil
        import weakref

        weakref.finalize(self, shutil.rmtree, cache_dir,
                         ignore_errors=True)

    # ----------------------------------------------------- constructors --
    @staticmethod
    def from_ndarrays(features: Any, labels: Any = None,
                      **kwargs) -> "ZooDataset":
        """Mirror of ``TFDataset.from_ndarrays`` (ref: tf_dataset.py:322)."""
        return ZooDataset(features, labels, **kwargs)

    @staticmethod
    def from_xshards(shards, feature_cols=None, label_cols=None,
                     **kwargs) -> "ZooDataset":
        """Build from an XShards of dicts / DataFrames
        (ref: orca Estimator fit accepting SparkXShards)."""
        import pandas as pd

        merged = shards.merged()
        if isinstance(merged, pd.DataFrame):
            if feature_cols is None:
                raise ValueError("feature_cols required for DataFrame shards")
            feats = {c: merged[c].to_numpy() for c in feature_cols}
            labels = ({c: merged[c].to_numpy() for c in label_cols}
                      if label_cols else None)
            if labels is not None and len(labels) == 1:
                labels = next(iter(labels.values()))
            return ZooDataset(feats, labels, **kwargs)
        if isinstance(merged, dict):
            if feature_cols is None and "x" in merged:
                feats = merged["x"]
                labels = merged.get("y")
            else:
                feature_cols = feature_cols or list(merged.keys())
                feats = {c: merged[c] for c in feature_cols}
                labels = ({c: merged[c] for c in label_cols}
                          if label_cols else None)
                if labels is not None and len(labels) == 1:
                    labels = next(iter(labels.values()))
            return ZooDataset(feats, labels, **kwargs)
        return ZooDataset(merged, **kwargs)

    # ----------------------------------------------------------- queries --
    @property
    def num_samples(self) -> int:
        return self._n

    def __len__(self) -> int:
        return self._n

    def split(self, fraction: float, seed: int = 0
              ) -> Tuple["ZooDataset", "ZooDataset"]:
        """Random split into (first, second) with ``fraction`` in first.
        Children inherit the memory tier; DISK-tier data is copied in
        chunks so a larger-than-RAM dataset never fully materializes."""
        rng = np.random.RandomState(seed)
        perm = rng.permutation(self._n)
        cut = int(self._n * fraction)
        first, second = perm[:cut], perm[cut:]

        def make(idx):
            cache_dir = (tempfile.mkdtemp(prefix="zoo_split_")
                         if self.memory_type == "DISK" else "")
            # distinct subdirs: _take_chunked restarts its arr_<n> counter
            # per call, so sharing one dir would overwrite features with
            # labels
            feats = _take_chunked(self.features, idx, self.memory_type,
                                  os.path.join(cache_dir, "x"))
            labs = (_take_chunked(self.labels, idx, self.memory_type,
                                  os.path.join(cache_dir, "y"))
                    if self.labels is not None else None)
            # _take_chunked already produced disk-backed memmaps for the
            # DISK tier; construct as DRAM to avoid a second spill copy,
            # then restore the tier label.
            child = ZooDataset(feats, labs)
            child.memory_type = self.memory_type
            if cache_dir:
                child._own_cache_dir(cache_dir)
            return child

        return make(first), make(second)

    def map_features(self, fn: Callable) -> "ZooDataset":
        return ZooDataset(fn(self.features), self.labels)

    # --------------------------------------------------------- iteration --
    def steps_per_epoch(self, batch_size: int,
                        drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self._n // batch_size
        return -(-self._n // batch_size)

    def batches(self, batch_size: int, shuffle: bool = True, seed: int = 0,
                epoch: int = 0, drop_remainder: bool = True,
                mesh=None, with_mask: bool = False
                ) -> Iterator[Tuple[Any, ...]]:
        """Yield host-local numpy ``(features, labels)`` batches.

        ``batch_size`` is the GLOBAL batch size; it must divide by the
        mesh's data-axis size (ref contract: tf_dataset.py:142-147). On a
        multi-process run, each process yields its 1/num_processes slice of
        every global batch (samples strided by process index).

        With ``drop_remainder=False`` the final short batch is padded up to
        ``batch_size`` by wrapping (tiling) the epoch's samples, keeping
        every batch shape static for XLA and divisible for sharding
        (predict paths truncate outputs back to ``num_samples``). With
        ``with_mask=True`` each yield is ``(x, y, mask)`` where ``mask``
        is a local float32 [local_bs] vector with 0 marking padded rows --
        used by evaluate for exact tail-inclusive metrics.
        """
        n_data = 1
        if mesh is not None:
            from analytics_zoo_tpu.parallel.mesh import mesh_axis_size

            n_data = mesh_axis_size(mesh, "data")
        if batch_size % max(n_data, 1) != 0:
            # opt-out knob (zoo.data.check_batch_divisible) for callers
            # that shard manually; with the check off, XLA raises later
            # at placement instead of here with a readable message
            if get_config().get("zoo.data.check_batch_divisible", True):
                raise ValueError(
                    f"global batch_size {batch_size} must be divisible "
                    f"by the data-parallel degree {n_data} "
                    "(ref contract: tf_dataset.py:142-147)")
            logger.warning(
                "batch_size %d is not divisible by the data-parallel "
                "degree %d (zoo.data.check_batch_divisible is off)",
                batch_size, n_data)

        n_proc = jax.process_count()
        proc = jax.process_index()
        if batch_size % n_proc != 0:
            raise ValueError(
                f"global batch_size {batch_size} must divide over "
                f"{n_proc} processes")
        local_bs = batch_size // n_proc

        if shuffle:
            rng = np.random.RandomState((seed * 100003 + epoch) & 0x7FFFFFFF)
            order = rng.permutation(self._n)
        else:
            order = np.arange(self._n)

        n_batches = self.steps_per_epoch(batch_size, drop_remainder)
        for b in range(n_batches):
            global_idx = order[b * batch_size:(b + 1) * batch_size]
            n_valid = len(global_idx)
            if n_valid < batch_size:  # pad final short batch (tiled wrap)
                pad = np.resize(order, batch_size - n_valid)
                global_idx = np.concatenate([global_idx, pad])
            # contiguous per-process block: process p owns global rows
            # [p*local_bs, (p+1)*local_bs) -- matches the device order of
            # hybrid meshes (DCN outermost), so the assembled global array
            # preserves batch order (unlike strided slicing)
            local_positions = np.arange(proc * local_bs,
                                        (proc + 1) * local_bs)
            local_idx = global_idx[local_positions]
            x = _tree_map(lambda a: np.asarray(a[local_idx]), self.features)
            y = (_tree_map(lambda a: np.asarray(a[local_idx]), self.labels)
                 if self.labels is not None else None)
            if with_mask:
                mask = (local_positions < n_valid).astype(np.float32)
                yield x, y, mask
            else:
                yield x, y

    def device_iterator(self, batch_size: int, mesh=None, shuffle: bool = True,
                        seed: int = 0, epoch: int = 0,
                        drop_remainder: bool = True, with_mask: bool = False,
                        prefetch: Optional[int] = None
                        ) -> Iterator[Tuple[Any, ...]]:
        """``batches`` + mesh placement + background prefetch.

        A producer thread stages the next ``prefetch`` device batches
        (default: the ``zoo.data.prefetch_buffer`` config key) while
        the consumer runs the train step -- the analog of FeatureSet's
        cached-RDD prefetch, but across the host->HBM boundary.
        """
        if prefetch is None:
            prefetch = int(get_config().get("zoo.data.prefetch_buffer",
                                            2))
        from analytics_zoo_tpu.parallel.mesh import default_mesh
        from analytics_zoo_tpu.parallel.sharding import shard_batch

        mesh = mesh or default_mesh()
        q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        _SENTINEL = object()
        err: list = []
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up if the consumer went away, so an
            # abandoned iterator never leaks a blocked thread pinning
            # device batches in HBM
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in self.batches(batch_size, shuffle, seed, epoch,
                                         drop_remainder, mesh,
                                         with_mask=with_mask):
                    placed = tuple(
                        shard_batch(part, mesh) if part is not None else None
                        for part in item)
                    if not put(placed):
                        return
            except BaseException as e:  # surface in consumer
                err.append(e)
            finally:
                put(_SENTINEL)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
