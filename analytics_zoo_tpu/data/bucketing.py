"""Length-bucketing for variable-length sequences under XLA.

The reference leans on TF1 feed-dict shape flexibility for text data
(ref: pyzoo/zoo/tfpark/tf_dataset.py:115-175 ``hard_code_batch_size``
foreshadows the problem; SURVEY.md section 7 flags "dynamic-shape data
under XLA" as a hard part). XLA compiles per shape, so the TPU-native
strategy is: assign each sequence to a small set of length buckets, pad
within the bucket, and let jit cache ONE executable per bucket shape --
bounded compiles, minimal padding waste.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np


def bucket_boundaries_for(lengths: Sequence[int], n_buckets: int = 4,
                          multiple: int = 8) -> List[int]:
    """Quantile-based boundaries rounded up to ``multiple`` (XLA-tidy
    shapes), deduplicated, covering the max length."""
    lengths = np.asarray(lengths)
    qs = np.quantile(lengths, np.linspace(0, 1, n_buckets + 1)[1:])
    bounds = sorted({int(-(-q // multiple) * multiple) for q in qs})
    top = int(-(-lengths.max() // multiple) * multiple)
    if not bounds or bounds[-1] < top:
        bounds.append(top)
    return bounds


class SequenceBuckets:
    """Variable-length int sequences -> per-bucket padded arrays.

    Args:
      sequences: list of 1-D int arrays/lists (token ids).
      labels: optional per-sequence labels.
      boundaries: ascending max-length per bucket; sequences longer than
        the last boundary are TRUNCATED to it (keep-tail, matching
        SequenceShaper's default 'pre' mode). None derives quantile
        boundaries.
      pad_value: fill for the padded tail.
    """

    def __init__(self, sequences: Sequence[Any], labels: Optional[
            Sequence[Any]] = None,
            boundaries: Optional[Sequence[int]] = None,
            n_buckets: int = 4, pad_value: int = 0):
        seqs = [np.asarray(s, np.int32) for s in sequences]
        lens = [len(s) for s in seqs]
        if boundaries is None:
            boundaries = bucket_boundaries_for(lens, n_buckets)
        self.boundaries = list(boundaries)
        self.pad_value = pad_value
        per_bucket: List[List[int]] = [[] for _ in self.boundaries]
        for i, ln in enumerate(lens):
            for b, bound in enumerate(self.boundaries):
                if ln <= bound:
                    per_bucket[b].append(i)
                    break
            else:
                per_bucket[-1].append(i)  # over-long: truncate into top
        self._buckets: List[Tuple[int, np.ndarray,
                                  Optional[np.ndarray]]] = []
        labels_arr = (np.asarray(labels) if labels is not None else None)
        self._real_tokens = 0
        for bound, idxs in zip(self.boundaries, per_bucket):
            if not idxs:
                continue
            x = np.full((len(idxs), bound), pad_value, np.int32)
            for row, i in enumerate(idxs):
                s = seqs[i][-bound:]  # truncate keeps the tail
                x[row, :len(s)] = s
                self._real_tokens += len(s)
            y = labels_arr[idxs] if labels_arr is not None else None
            self._buckets.append((bound, x, y))

    def __len__(self) -> int:
        return len(self._buckets)

    def __iter__(self) -> Iterator[Tuple[int, np.ndarray, Any]]:
        return iter(self._buckets)

    @property
    def padding_waste(self) -> float:
        """Fraction of padded positions across all buckets -- the
        figure of merit bucketing minimizes. Computed from the true
        sequence lengths, so genuine tokens equal to ``pad_value``
        don't count as padding."""
        total = sum(x.size for _, x, _ in self._buckets)
        return 1.0 - self._real_tokens / max(total, 1)

    def datasets(self):
        """One ZooDataset per non-empty bucket."""
        from analytics_zoo_tpu.data.dataset import ZooDataset

        out = []
        for _, x, y in self._buckets:
            out.append(ZooDataset.from_ndarrays(x, y))
        return out


def fit_bucketed(estimator, buckets: SequenceBuckets, batch_size: int,
                 epochs: int = 1, **fit_kwargs) -> List[Any]:
    """Train one Estimator across every bucket: each epoch walks the
    buckets (largest first, so the biggest compile happens up front);
    jit caches one train step per bucket shape. Returns the concatenated
    per-bucket histories."""
    histories = []
    data = sorted(buckets, key=lambda t: -t[0])
    skipped = sum(len(x) for _, x, _ in data if len(x) < batch_size)
    if skipped:
        # no silent caps: these sequences never train at this batch size
        from analytics_zoo_tpu.common.log import get_logger

        get_logger(__name__).warning(
            "fit_bucketed: %d sequences sit in buckets smaller than "
            "batch_size=%d and are skipped every pass -- lower "
            "batch_size or widen the buckets to train on them",
            skipped, batch_size)
    for _ in range(epochs):
        for _, x, y in data:
            if len(x) < batch_size:
                continue  # short-remainder bucket: skip, not recompile
            # Estimator.fit's ``epochs`` is an absolute target over the
            # estimator's lifetime; one more epoch per bucket pass
            histories.extend(estimator.fit(
                (x, y) if y is not None else x, batch_size=batch_size,
                epochs=estimator.epoch + 1, **fit_kwargs))
    return histories
