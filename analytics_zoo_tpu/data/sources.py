"""Data sources: csv/json/parquet/image/tfrecord readers producing XShards.

The analog of Orca's distributed pandas readers
(ref: pyzoo/zoo/orca/data/pandas/preprocessing.py -- read_csv/read_json)
and ``NNImageReader`` (ref: zoo/.../nnframes/NNImageReader.scala), plus a
dependency-free TFRecord/tf.Example reader replacing
``TFDataset.from_tfrecord_file`` (ref: pyzoo/zoo/tfpark/tf_dataset.py:549).

Files matching a glob are partitioned across shards; each shard reads its
files on a worker thread.
"""

from __future__ import annotations

import glob as globlib
import os
import struct
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data.shard import XShards
from analytics_zoo_tpu.utils import fileio


def _expand(path) -> List[str]:
    if isinstance(path, (list, tuple)):
        files: List[str] = []
        for p in path:
            files.extend(_expand(p))
        return files
    if fileio.is_remote(path):
        # URI datasets (gs://bucket/dir, memory://...) resolve through
        # the filesystem layer; scheme is re-attached so downstream
        # readers (pandas handles fsspec URLs natively) keep working
        fs = fileio.get_filesystem(path)
        scheme = str(path).split("://", 1)[0]
        bare = str(path).split("://", 1)[1]
        if fs.isdir(bare):
            out = [u for u in fileio.listdir_uris(path, kind="file")
                   if not os.path.basename(u).startswith((".", "_"))]
        else:
            out = sorted(f"{scheme}://{p}" for p in fs.glob(bare))
        if not out:
            raise FileNotFoundError(f"no files match {path!r}")
        return out
    if os.path.isdir(path):
        return sorted(
            p for f in os.listdir(path)
            if not f.startswith((".", "_"))
            and os.path.isfile(p := os.path.join(path, f)))
    matches = sorted(globlib.glob(path))
    if not matches:
        raise FileNotFoundError(f"no files match {path!r}")
    return matches


def _read_files(path, reader, num_shards: Optional[int]) -> XShards:
    import pandas as pd

    files = _expand(path)
    num_shards = num_shards or min(len(files), 8)
    groups = np.array_split(np.asarray(files, dtype=object), num_shards)
    groups = [g for g in groups if len(g)]
    shards = XShards(list(groups)).transform_shard(
        lambda fs: pd.concat([reader(f) for f in fs], ignore_index=True))
    return shards


def read_csv(path, num_shards: Optional[int] = None, **kwargs) -> XShards:
    """Distributed CSV read -> XShards of DataFrames
    (ref: orca/data/pandas/preprocessing.py read_csv)."""
    import pandas as pd

    return _read_files(path, lambda f: pd.read_csv(f, **kwargs), num_shards)


def read_json(path, num_shards: Optional[int] = None, **kwargs) -> XShards:
    import pandas as pd

    return _read_files(path, lambda f: pd.read_json(f, **kwargs), num_shards)


def read_parquet(path, num_shards: Optional[int] = None, **kwargs) -> XShards:
    import pandas as pd

    return _read_files(path, lambda f: pd.read_parquet(f, **kwargs),
                       num_shards)


# ----------------------------------------------------------------- image ---


def read_image_folder(path: str, image_size: Optional[tuple] = None,
                      num_shards: Optional[int] = None,
                      with_label: bool = True) -> XShards:
    """Read a class-per-subdirectory image tree into XShards of
    ``{"x": uint8 [N,H,W,3], "y": int32 [N]}`` (requires ``image_size``
    for stacking) -- the analog of ``NNImageReader.readImages`` +
    ``ImageSet`` (ref: zoo/.../nnframes/NNImageReader.scala,
    zoo/.../feature/image/ImageSet.scala).
    """
    from PIL import Image

    if fileio.is_remote(path):
        classes = sorted(
            os.path.basename(d.rstrip("/")) for d in
            fileio.listdir_uris(path, kind="directory")
        ) if with_label else []
        entries: List[tuple] = []
        for ci, c in enumerate(classes):
            for f in fileio.listdir_uris(fileio.join(path, c),
                                         kind="file"):
                entries.append((f, ci))
    else:
        classes = sorted(
            d for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d))) if with_label else []
        entries = []
        for ci, c in enumerate(classes):
            for f in sorted(os.listdir(os.path.join(path, c))):
                entries.append((os.path.join(path, c, f), ci))
    if not classes:
        for f in _expand(path):
            entries.append((f, -1))
    if not entries:
        raise FileNotFoundError(f"no images under {path!r}")
    num_shards = num_shards or min(len(entries), 8)

    def load(group):
        xs, ys = [], []
        for fpath, label in group:
            with fileio.open_file(fpath, "rb") as fh:
                img = Image.open(fh).convert("RGB")
            if image_size is not None:
                img = img.resize((image_size[1], image_size[0]))
            xs.append(np.asarray(img, dtype=np.uint8))
            ys.append(label)
        return {"x": np.stack(xs), "y": np.asarray(ys, np.int32)}

    groups = [list(g) for g in
              np.array_split(np.asarray(entries, dtype=object), num_shards)
              if len(g)]
    return XShards(groups).transform_shard(load)


# -------------------------------------------------------------- tfrecord ---
# TFRecord framing: <len u64><masked-crc32c(len) u32><bytes><masked-crc u32>
# tf.Example payload: Example{features: Features{feature: map<str, Feature>}}
# Feature: oneof {bytes_list=1, float_list=2, int64_list=3}.
# Minimal protobuf wire decoding -- no TF dependency.


def _read_varint(buf: bytes, pos: int):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_feature(buf: bytes):
    for field, wire, val in _iter_fields(buf):
        if field == 1:  # BytesList
            return [v for f, w, v in _iter_fields(val) if f == 1]
        if field == 2:  # FloatList
            out: List[float] = []
            for f, w, v in _iter_fields(val):
                if f != 1:
                    continue
                if w == 2:  # packed
                    out.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    out.append(struct.unpack("<f", v)[0])
            return np.asarray(out, np.float32)
        if field == 3:  # Int64List
            out = []
            for f, w, v in _iter_fields(val):
                if f != 1:
                    continue
                if w == 2:
                    pos = 0
                    while pos < len(v):
                        x, pos = _read_varint(v, pos)
                        out.append(x)
                    continue
                out.append(v)
            # varints are unsigned on the wire; negative int64s arrive as
            # two's-complement 64-bit values
            out = [x - (1 << 64) if x >= (1 << 63) else x for x in out]
            return np.asarray(out, np.int64)
    return None


def parse_example(buf: bytes) -> Dict[str, Any]:
    """Decode one serialized tf.train.Example into {name: value}."""
    out: Dict[str, Any] = {}
    for field, _, val in _iter_fields(buf):
        if field != 1:  # Example.features
            continue
        for f2, _, entry in _iter_fields(val):
            if f2 != 1:  # Features.feature map entry
                continue
            name, feature = None, None
            for f3, _, v3 in _iter_fields(entry):
                if f3 == 1:
                    name = v3.decode("utf-8")
                elif f3 == 2:
                    feature = v3
            if name is not None and feature is not None:
                out[name] = _parse_feature(feature)
    return out


def iter_tfrecord(path: str, verify: bool = False):
    """Yield raw record payloads from one TFRecord file.

    The file is memory-mapped (copy-on-write pages, nothing
    materialized up front -- multi-GB shards stay O(1) resident) and
    frames are found in one native-C scanning pass when available
    (``verify=True`` additionally checks both masked CRCs per record),
    with a pure-Python fallback."""
    import mmap

    from analytics_zoo_tpu import native

    if fileio.is_remote(path):
        # object stores have no mmap; one ranged read of the shard
        buf = fileio.read_bytes(path)
        for offset, length in native.scan_tfrecords(buf, verify=verify):
            yield buf[offset:offset + length]
        return
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size == 0:
            return
        with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY) as mm:
            for offset, length in native.scan_tfrecords(mm,
                                                        verify=verify):
                yield bytes(mm[offset:offset + length])


def read_tfrecord(path, num_shards: Optional[int] = None,
                  parse: bool = True) -> XShards:
    """Read TFRecord files -> XShards of lists of parsed Examples (dicts)
    or raw payload bytes (ref: tf_dataset.py:549 from_tfrecord_file)."""
    files = _expand(path)
    num_shards = num_shards or min(len(files), 8)
    groups = [list(g) for g in
              np.array_split(np.asarray(files, dtype=object), num_shards)
              if len(g)]

    def load(fs):
        records: List[Any] = []
        for f in fs:
            for payload in iter_tfrecord(f):
                records.append(parse_example(payload) if parse else payload)
        return records

    return XShards(groups).transform_shard(load)
