"""Threshold-based anomaly detection for time series.

The analog of zouwu anomaly detection (ref: pyzoo/zoo/zouwu/model/
anomaly.py:51-130 -- ThresholdEstimator fits a threshold from forecast
residuals, ThresholdDetector flags samples whose actual/predicted
distance exceeds it, with scalar / per-sample / per-dimension / (min,max)
range threshold forms).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple, Union

import numpy as np


def euclidean_distance(y: np.ndarray, yhat: np.ndarray) -> np.ndarray:
    """Per-sample L2 distance; samples along axis 0."""
    d = (np.asarray(y, np.float64) -
         np.asarray(yhat, np.float64)).reshape(len(y), -1)
    return np.linalg.norm(d, axis=1)


class ThresholdEstimator:
    """Pick a distance threshold from residuals
    (ref: anomaly.py ThresholdEstimator.fit)."""

    def fit(self, y: np.ndarray, yhat: np.ndarray,
            mode: str = "default", ratio: float = 0.01) -> float:
        y, yhat = np.asarray(y), np.asarray(yhat)
        if y.shape != yhat.shape:
            raise ValueError("y and yhat must share a shape")
        dist = euclidean_distance(y, yhat)
        if mode == "default":  # empirical quantile
            return float(np.percentile(dist, (1 - ratio) * 100))
        if mode == "gaussian":  # fit N(mu, sigma), take the 1-ratio ppf
            mu, sigma = float(dist.mean()), float(dist.std())
            # inverse CDF via erfinv, no scipy dependency
            from math import sqrt

            t = sqrt(2) * _erfinv(2 * (1 - ratio) - 1)
            return t * sigma + mu
        raise ValueError(f"unsupported mode {mode!r}")


def _erfinv(x: float) -> float:
    """Winitzki's approximation; |error| < 5e-3 over (-1, 1), plenty for
    picking an anomaly quantile."""
    a = 0.147
    ln1mx2 = math.log(1 - x * x)
    term = 2 / (math.pi * a) + ln1mx2 / 2
    return math.copysign(
        math.sqrt(math.sqrt(term ** 2 - ln1mx2 / a) - term), x)


class ThresholdDetector:
    """(ref: anomaly.py ThresholdDetector.detect). Threshold forms:

    - scalar: one distance bound for every sample;
    - [num_samples] array: per-sample distance bound;
    - array shaped like y: per-dimension distance bound;
    - (min, max) tuple of arrays/scalars: y outside the range is
      anomalous, yhat is ignored.

    Returns the indices of anomalous samples (axis-0 positions).
    """

    def detect(self, y: np.ndarray, yhat: Optional[np.ndarray] = None,
               threshold: Union[float, np.ndarray, Tuple] = math.inf
               ) -> np.ndarray:
        y = np.asarray(y)
        if isinstance(threshold, tuple):
            lo, hi = (np.asarray(t, np.float64) for t in threshold)
            if np.any(lo > hi):
                raise ValueError("threshold min exceeds max")
            bad = (y < lo) | (y > hi)
            return np.unique(np.nonzero(bad)[0])
        if yhat is None:
            raise ValueError("yhat is required for distance thresholds")
        yhat = np.asarray(yhat)
        if y.shape != yhat.shape:
            raise ValueError("y and yhat must share a shape")
        threshold = np.asarray(threshold, np.float64)
        if threshold.ndim == 0:
            dist = euclidean_distance(y, yhat)
            return np.nonzero(dist > float(threshold))[0]
        if threshold.ndim == 1:
            if len(threshold) != len(y):
                raise ValueError("per-sample threshold length mismatch")
            dist = euclidean_distance(y, yhat)
            return np.nonzero(dist > threshold)[0]
        if threshold.shape != y.shape:
            raise ValueError("per-dimension threshold shape mismatch")
        bad = np.abs(y.astype(np.float64) - yhat) > threshold
        return np.unique(np.nonzero(bad)[0])
