"""Standalone forecasters: LSTM, MTNet, Seq2Seq, TCN, TCMF.

The analog of the zouwu forecaster family (ref: pyzoo/zoo/zouwu/model/
forecast/ -- lstm_forecaster.py, mtnet_forecaster.py:22-90,
tcmf_forecaster.py). All but TCMF wrap one :class:`TimeSequenceModel`
configuration behind a scikit-style fit/predict/evaluate surface. TCMF
is the multi-series model: a low-rank factorization Y ~= F @ X with a
TCN over the temporal factors, trained end-to-end by gradient descent
(the TPU-native collapse of DeepGLO's alternating scheme, ref:
automl/model/tcmf/DeepGLO.py:904 -- one jitted loss instead of
interleaved torch loops).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.automl import metrics as automl_metrics
from analytics_zoo_tpu.automl.models import TCN, TimeSequenceModel
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)


class Forecaster:
    """Base (ref: forecast/abstract.py): subclasses define
    ``_model_config()``; x is [B, past_seq_len, feature_dim]."""

    def __init__(self, future_seq_len: int, n_targets: int = 1,
                 feature_dim: Optional[int] = None):
        self.model = TimeSequenceModel(future_seq_len=future_seq_len,
                                       n_targets=n_targets)
        self.feature_dim = feature_dim

    def _model_config(self) -> Dict:
        raise NotImplementedError

    def fit(self, x: np.ndarray, y: np.ndarray,
            validation_data: Optional[Tuple] = None, epochs: int = 1,
            batch_size: int = 32, metric: str = "mse") -> float:
        x = np.asarray(x, np.float32)
        if self.feature_dim is not None and \
                x.shape[-1] != self.feature_dim:
            raise ValueError(
                f"input has {x.shape[-1]} features, forecaster was "
                f"declared with feature_dim={self.feature_dim}")
        config = dict(self._model_config(), epochs=epochs,
                      batch_size=batch_size, metric=metric)
        y = np.asarray(y).reshape(len(y), -1)
        return self.model.fit_eval(x, y,
                                   validation_data=validation_data,
                                   **config)

    def predict(self, x: np.ndarray) -> np.ndarray:
        return self.model.predict(np.asarray(x, np.float32))

    def predict_with_uncertainty(self, x: np.ndarray, n_iter: int = 10):
        return self.model.predict_with_uncertainty(
            np.asarray(x, np.float32), n_iter)

    def evaluate(self, x: np.ndarray, y: np.ndarray,
                 metrics: Sequence[str] = ("mse",)) -> Dict[str, float]:
        return self.model.evaluate(np.asarray(x, np.float32), y, metrics)

    def save(self, dir_path: str) -> None:
        self.model.save(dir_path)

    def restore(self, dir_path: str) -> None:
        self.model = TimeSequenceModel.restore(dir_path)


class LSTMForecaster(Forecaster):
    """(ref: forecast/lstm_forecaster.py:20-80)."""

    def __init__(self, target_dim: int = 1, feature_dim: int = None,
                 lstm_1_units: int = 16, dropout_1: float = 0.2,
                 lstm_2_units: int = 8, dropout_2: float = 0.2,
                 lr: float = 0.001):
        super().__init__(future_seq_len=target_dim, n_targets=1,
                         feature_dim=feature_dim)
        self._config = {
            "model": "LSTM", "lstm_1_units": lstm_1_units,
            "dropout_1": dropout_1, "lstm_2_units": lstm_2_units,
            "dropout_2": dropout_2, "lr": lr,
        }

    def _model_config(self):
        return dict(self._config)


class Seq2SeqForecaster(Forecaster):
    def __init__(self, horizon: int = 1, feature_dim: int = None,
                 latent_dim: int = 64, dropout: float = 0.2,
                 lr: float = 0.001):
        super().__init__(future_seq_len=horizon, n_targets=1,
                         feature_dim=feature_dim)
        self._config = {"model": "Seq2Seq", "latent_dim": latent_dim,
                        "dropout": dropout, "lr": lr}

    def _model_config(self):
        return dict(self._config)


class TCNForecaster(Forecaster):
    def __init__(self, horizon: int = 1, feature_dim: int = None,
                 levels: int = 3, hidden: int = 30, kernel_size: int = 3,
                 dropout: float = 0.1, lr: float = 0.001):
        super().__init__(future_seq_len=horizon, n_targets=1,
                         feature_dim=feature_dim)
        self._config = {"model": "TCN", "levels": levels,
                        "hidden": hidden, "kernel_size": kernel_size,
                        "dropout": dropout, "lr": lr}

    def _model_config(self):
        return dict(self._config)


class MTNetForecaster(Forecaster):
    """(ref: forecast/mtnet_forecaster.py:22-90). The input window must
    be ``(long_series_num + 1) * series_length`` steps long; use
    :meth:`preprocess_input` to roll a raw series accordingly."""

    def __init__(self, target_dim: int = 1, feature_dim: int = None,
                 long_series_num: int = 1, series_length: int = 1,
                 ar_window_size: int = 1, cnn_height: int = 1,
                 cnn_hid_size: int = 32, rnn_hid_size: int = 32,
                 cnn_dropout: float = 0.2, rnn_dropout: float = 0.2,
                 lr: float = 0.001):
        super().__init__(future_seq_len=1, n_targets=target_dim,
                         feature_dim=feature_dim)
        self.past_seq_len = (long_series_num + 1) * series_length
        self._config = {
            "model": "MTNet", "time_step": series_length,
            "long_num": long_series_num, "ar_size": ar_window_size,
            "cnn_height": cnn_height, "cnn_hidden": cnn_hid_size,
            "rnn_hidden": rnn_hid_size, "cnn_dropout": cnn_dropout,
            "rnn_dropout": rnn_dropout, "lr": lr,
        }

    def _model_config(self):
        return dict(self._config)


class TCMFForecaster:
    """Temporal-convolution matrix factorization for forecasting MANY
    correlated series at once (ref: forecast/tcmf_forecaster.py,
    automl/model/tcmf/DeepGLO.py:904).

    Y [n_series, T] ~= F [n_series, rank] @ X [rank, T]; a TCN over X's
    rows learns the temporal dynamics and rolls X beyond T at predict
    time. Both the factors and the TCN train jointly under one jitted
    Adam loop: reconstruction loss + one-step-ahead forecast loss on X.
    """

    def __init__(self, rank: int = 8, tcn_levels: int = 3,
                 tcn_hidden: int = 32, kernel_size: int = 3,
                 window: int = 16, lr: float = 0.01, seed: int = 0,
                 use_local: bool = False):
        self.rank = rank
        self.window = window
        self.lr = lr
        self.seed = seed
        self.use_local = use_local
        self.tcn = TCN(levels=tcn_levels, hidden=tcn_hidden,
                       kernel_size=kernel_size, dropout=0.0,
                       output_dim=rank)
        # DeepGLO's per-series "local" model: a second TCN over
        # [series value, global reconstruction] covariate windows that
        # predicts the FINAL value -- the global factorization captures
        # shared structure, the local model the per-series residual
        # (ref: automl/model/tcmf/local_model.py:705)
        self.local_tcn = TCN(levels=max(1, tcn_levels - 1),
                             hidden=max(8, tcn_hidden // 2),
                             kernel_size=kernel_size, dropout=0.0,
                             output_dim=1)
        self.params = None
        self.local_params = None
        self.y_mean = None
        self.y_std = None
        self._x_factors = None
        self._yn = None

    def fit(self, y: np.ndarray, epochs: int = 100,
            local_epochs: int = 100,
            distributed: bool = False) -> Dict[str, float]:
        """y: [n_series, T]. Returns final losses.

        ``distributed=True`` shards the series dimension (Y rows and F
        rows) over the context mesh's data axis -- the scale-out story
        DeepGLO got from distributed torch fit
        (ref: tcmf_model.py distributed fit): the factor matmul and the
        losses partition by series, X and the TCNs replicate, and XLA
        inserts the gradient reductions. n_series must divide the data
        axis.
        """
        import optax

        y = np.asarray(y, np.float32)
        if y.ndim != 2:
            raise ValueError("TCMF wants y shaped [n_series, T]")
        n, t = y.shape
        if t <= self.window + 1:
            raise ValueError("series shorter than the TCN window")
        self.y_mean = y.mean(axis=1, keepdims=True)
        self.y_std = np.where(y.std(axis=1, keepdims=True) < 1e-8, 1.0,
                              y.std(axis=1, keepdims=True))
        yn = jnp.asarray((y - self.y_mean) / self.y_std)

        series_sharding = None
        if distributed:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from analytics_zoo_tpu.parallel.mesh import (
                default_mesh, mesh_axis_size)

            mesh = default_mesh()
            dp = mesh_axis_size(mesh, "data")
            if n % dp != 0:
                raise ValueError(
                    f"n_series {n} must be divisible by the data-axis "
                    f"size ({dp})")
            series_sharding = NamedSharding(mesh, P("data", None))
            yn = jax.device_put(yn, series_sharding)

        rng = jax.random.PRNGKey(self.seed)
        k_f, k_x, k_t = jax.random.split(rng, 3)
        scale = 1.0 / np.sqrt(self.rank)
        params = {
            "F": jax.random.normal(k_f, (n, self.rank)) * scale,
            "X": jax.random.normal(k_x, (self.rank, t)) * scale,
            "tcn": self.tcn.init(
                k_t, jnp.zeros((1, self.window, self.rank)))["params"],
            # per-factor linear AR coefficients over the window: linear
            # recurrences extrapolate smooth/periodic factors exactly,
            # the TCN learns the nonlinear residual
            "ar": jnp.zeros((self.rank, self.window)),
        }
        if series_sharding is not None:
            # commit EVERY leaf to the mesh (F sharded by series, the
            # rest replicated): a mix of mesh-committed and uncommitted
            # inputs can wedge XLA's in-process CPU collectives
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(series_sharding.mesh, P())
            params = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, rep), params)
            params["F"] = jax.device_put(params["F"], series_sharding)
        tx = optax.adam(self.lr)
        opt_state = tx.init(params)
        window, tcn = self.window, self.tcn
        rollout = min(4, t - window)

        def loss_fn(p, ydata, psum_axis=None):
            recon = p["F"] @ p["X"]
            if psum_axis is None:
                recon_loss = jnp.mean((recon - ydata) ** 2)
            else:
                # shard_map body: local sum, one psum, global mean
                recon_loss = jax.lax.psum(
                    jnp.sum((recon - ydata) ** 2), psum_axis) / (n * t)
            xt = p["X"].T  # [T, rank]
            # temporal smoothness keeps the factors predictable -- the
            # TCN must learn dynamics, not memorize a jagged sequence
            smooth_loss = jnp.mean((xt[1:] - xt[:-1]) ** 2)
            # multi-step rollout forecast loss: from every window, roll
            # ``rollout`` steps feeding predictions back in -- predict()
            # uses the model exactly this way, so one-step teacher
            # forcing alone would let the TCN memorize the sequence and
            # diverge off the end of the training range
            starts = jnp.arange(t - window - rollout + 1)
            wins = jax.vmap(
                lambda s: jax.lax.dynamic_slice(
                    xt, (s, 0), (window, xt.shape[1])))(starts)
            targets = jax.vmap(
                lambda s: jax.lax.dynamic_slice(
                    xt, (s + window, 0), (rollout, xt.shape[1])))(starts)

            def roll_step(w, _):
                # w: [B, window, rank]; AR term + TCN residual
                ar = jnp.einsum("bwk,kw->bk", w, p["ar"])
                nxt = ar + tcn.apply({"params": p["tcn"]}, w)
                w = jnp.concatenate([w[:, 1:], nxt[:, None]], axis=1)
                return w, nxt

            _, preds = jax.lax.scan(roll_step, wins, None, length=rollout)
            fore_loss = jnp.mean(
                (jnp.moveaxis(preds, 0, 1) - targets) ** 2)
            loss = recon_loss + fore_loss + 0.1 * smooth_loss
            return loss, (recon_loss, fore_loss)

        if series_sharding is None:
            def full_loss(p):
                return loss_fn(p, yn)
        else:
            # explicit shard_map: F rows and Y rows shard by series;
            # X / tcn / ar replicate. The ONLY collectives are the
            # recon psum and the replicated-params gradient reductions
            # at the shard_map boundary -- none inside the rollout
            # scan, which wedges XLA's in-process CPU communicator
            # when auto-partitioned.
            from functools import partial

            from jax.sharding import PartitionSpec as P

            mesh = series_sharding.mesh
            param_specs = {
                k: (P("data", None) if k == "F"
                    else jax.tree_util.tree_map(lambda _: P(), v))
                for k, v in params.items()}
            from analytics_zoo_tpu.parallel.mesh import shard_map

            body = shard_map(
                partial(loss_fn, psum_axis="data"), mesh,
                in_specs=(param_specs, P("data", None)),
                out_specs=(P(), (P(), P())))

            def full_loss(p):
                return body(p, yn)

        @jax.jit
        def step(p, s):
            (loss, aux), grads = jax.value_and_grad(
                full_loss, has_aux=True)(p)
            updates, s = tx.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss, aux

        loss = recon = fore = None
        for i in range(epochs):
            params, opt_state, loss, (recon, fore) = step(params,
                                                          opt_state)
            if series_sharding is not None and i % 8 == 7:
                # bound the async dispatch queue: a deep pipeline of
                # collective-bearing programs can wedge the in-process
                # CPU communicator's rendezvous (observed at ~60 queued
                # steps on the 8-device test mesh)
                jax.block_until_ready(loss)
        self.params = jax.device_get(params)
        self._x_factors = self.params["X"]
        self._yn = np.asarray(jax.device_get(yn))
        logger.info("TCMF fit: loss=%.5f recon=%.5f forecast=%.5f",
                    float(loss), float(recon), float(fore))
        result = {"loss": float(loss), "recon": float(recon),
                  "forecast": float(fore)}
        if self.use_local:
            result["local"] = self._fit_local(yn, series_sharding,
                                              local_epochs)
        return result

    def _fit_local(self, yn, series_sharding, epochs: int) -> float:
        """Train the per-series local TCN on [value, global recon]
        covariate windows -> next value (DeepGLO's hybrid stage,
        ref: local_model.py:705). Series stay sharded when the global
        fit was distributed."""
        import optax

        n, t = yn.shape
        w = self.window
        recon = jnp.asarray(self.params["F"]) @ jnp.asarray(
            self.params["X"])
        if series_sharding is not None:
            recon = jax.lax.with_sharding_constraint(recon,
                                                     series_sharding)
        feats = jnp.stack([yn, recon], axis=-1)     # [n, t, 2]
        starts = jnp.arange(t - w)

        def windows_of(row):                         # [t, 2] -> [S, w, 2]
            return jax.vmap(lambda s: jax.lax.dynamic_slice(
                row, (s, 0), (w, 2)))(starts)

        wins = jax.vmap(windows_of)(feats)           # [n, S, w, 2]
        targets = jax.vmap(
            lambda row: jax.vmap(
                lambda s: jax.lax.dynamic_index_in_dim(
                    row, s + w, 0, keepdims=False))(starts))(yn)

        lp = self.local_tcn.init(
            jax.random.PRNGKey(self.seed + 1),
            jnp.zeros((1, w, 2)))["params"]
        local_tcn = self.local_tcn
        n_total = int(n) * int(t - w)

        def loss_fn(p, win_data, tgt_data, psum_axis=None):
            flat = win_data.reshape(-1, w, 2)
            preds = local_tcn.apply({"params": p}, flat)[:, 0]
            err = (preds - tgt_data.reshape(-1)) ** 2
            if psum_axis is None:
                return jnp.mean(err)
            # shard_map body (same structure as the global fit: the
            # only collectives sit at the boundary)
            return jax.lax.psum(jnp.sum(err), psum_axis) / n_total

        if series_sharding is None:
            def full_loss(p):
                return loss_fn(p, wins, targets)
        else:
            from functools import partial

            from jax.sharding import NamedSharding, PartitionSpec as P

            from analytics_zoo_tpu.parallel.mesh import shard_map

            mesh = series_sharding.mesh
            lp = jax.device_put(lp, NamedSharding(mesh, P()))
            body = shard_map(
                partial(loss_fn, psum_axis="data"), mesh,
                in_specs=(jax.tree_util.tree_map(lambda _: P(), lp),
                          P("data", None, None, None),
                          P("data", None)),
                out_specs=P())

            def full_loss(p):
                return body(p, wins, targets)

        tx = optax.adam(self.lr)
        opt_state = tx.init(lp)

        @jax.jit
        def step(p, s):
            l, grads = jax.value_and_grad(full_loss)(p)
            updates, s = tx.update(grads, s, p)
            return optax.apply_updates(p, updates), s, l

        l = None
        for i in range(epochs):
            lp, opt_state, l = step(lp, opt_state)
            if series_sharding is not None and i % 8 == 7:
                jax.block_until_ready(l)  # bound the dispatch queue
        self.local_params = jax.device_get(lp)
        logger.info("TCMF local fit: loss=%.5f", float(l))
        return float(l)

    def predict(self, horizon: int = 1) -> np.ndarray:
        """Roll X forward ``horizon`` steps, project through F; when
        the local model is fitted, it refines each step from
        [value, global] covariate windows (DeepGLO hybrid predict)."""
        if self.params is None:
            raise RuntimeError("fit first")
        xt = jnp.asarray(self.params["X"].T)  # [T, rank]
        tcn_params = {"params": self.params["tcn"]}
        ar_coef = jnp.asarray(self.params["ar"])
        for _ in range(horizon):
            win = xt[-self.window:][None]  # [1, window, rank]
            ar = jnp.einsum("bwk,kw->bk", win, ar_coef)
            nxt = (ar + self.tcn.apply(tcn_params, win))[0]
            xt = jnp.concatenate([xt, nxt[None]], axis=0)
        x_fut = np.asarray(xt[-horizon:]).T  # [rank, horizon]
        f = self.params["F"]
        y_fut = f @ x_fut                     # normalized global forecast
        if self.local_params is not None:
            w = self.window
            yn_ext = jnp.asarray(self._yn)            # [n, T]
            recon_ext = jnp.asarray(f @ self.params["X"])
            lp = {"params": self.local_params}
            outs = []
            for h in range(horizon):
                recon_h = jnp.asarray(y_fut[:, h])    # [n]
                feats = jnp.stack(
                    [yn_ext[:, -w:],
                     recon_ext[:, -w:]], axis=-1)     # [n, w, 2]
                pred = self.local_tcn.apply(lp, feats)[:, 0]
                outs.append(np.asarray(pred))
                yn_ext = jnp.concatenate(
                    [yn_ext, pred[:, None]], axis=1)
                recon_ext = jnp.concatenate(
                    [recon_ext, recon_h[:, None]], axis=1)
            y_fut = np.stack(outs, axis=1)
        return y_fut * self.y_std + self.y_mean

    def evaluate(self, y_true: np.ndarray,
                 metrics: Sequence[str] = ("mse",)) -> Dict[str, float]:
        """Score a [n_series, horizon] continuation."""
        y_true = np.asarray(y_true)
        pred = self.predict(y_true.shape[1])
        return automl_metrics.evaluate_all(metrics, y_true, pred)
