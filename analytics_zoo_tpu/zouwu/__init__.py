"""Zouwu: the time-series toolkit (AutoTS, forecasters, anomaly).

The analog of the reference's zouwu subsystem (ref: pyzoo/zoo/zouwu --
AutoTSTrainer/TSPipeline over automl, standalone LSTM/MTNet/TCMF
forecasters, threshold anomaly detection; SURVEY.md section 2.2).
"""

from analytics_zoo_tpu.zouwu.anomaly import (  # noqa: F401
    ThresholdDetector,
    ThresholdEstimator,
)
from analytics_zoo_tpu.zouwu.autots import (  # noqa: F401
    AutoTSTrainer,
    TSPipeline,
)
from analytics_zoo_tpu.zouwu.forecast import (  # noqa: F401
    Forecaster,
    LSTMForecaster,
    MTNetForecaster,
    Seq2SeqForecaster,
    TCMFForecaster,
    TCNForecaster,
)
