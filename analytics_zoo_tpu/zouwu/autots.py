"""AutoTS: automated time-series forecasting.

The analog of zouwu AutoTS (ref: pyzoo/zoo/zouwu/autots/forecast.py:
22-140 -- AutoTSTrainer wraps TimeSequencePredictor, TSPipeline wraps
the fitted TimeSequencePipeline).
"""

from __future__ import annotations

from typing import List, Optional

import pandas as pd

from analytics_zoo_tpu.automl.pipeline import (TimeSequencePipeline,
                                               load_ts_pipeline)
from analytics_zoo_tpu.automl.predictor import TimeSequencePredictor
from analytics_zoo_tpu.automl.recipes import Recipe, SmokeRecipe


class TSPipeline:
    """Fitted forecasting pipeline (ref: forecast.py TSPipeline)."""

    def __init__(self, internal: Optional[TimeSequencePipeline] = None):
        self.internal = internal

    def fit(self, input_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            epoch_num: int = 20) -> "TSPipeline":
        self.internal.fit(input_df, validation_df, epoch_num=epoch_num)
        return self

    def predict(self, input_df: pd.DataFrame) -> pd.DataFrame:
        return self.internal.predict(input_df)

    def predict_with_uncertainty(self, input_df: pd.DataFrame,
                                 n_iter: int = 10):
        return self.internal.predict_with_uncertainty(input_df, n_iter)

    def evaluate(self, input_df: pd.DataFrame,
                 metrics: List[str] = ("mse",)):
        return self.internal.evaluate(input_df, metrics)

    def describe(self):
        return self.internal.describe()

    def save(self, pipeline_dir: str) -> None:
        self.internal.save(pipeline_dir)

    @staticmethod
    def load(pipeline_dir: str) -> "TSPipeline":
        return TSPipeline(load_ts_pipeline(pipeline_dir))


class AutoTSTrainer:
    """(ref: forecast.py AutoTSTrainer)."""

    def __init__(self, horizon: int = 1, dt_col: str = "datetime",
                 target_col="value", extra_features_col=None,
                 logs_dir: Optional[str] = None,
                 executor: str = "sequential",
                 max_workers: Optional[int] = None):
        self.internal = TimeSequencePredictor(
            dt_col=dt_col, target_col=target_col, future_seq_len=horizon,
            extra_features_col=extra_features_col, logs_dir=logs_dir,
            executor=executor, max_workers=max_workers)

    def fit(self, train_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            metric: str = "mse", recipe: Recipe = None) -> TSPipeline:
        pipeline = self.internal.fit(train_df, validation_df,
                                     recipe=recipe or SmokeRecipe(),
                                     metric=metric)
        return TSPipeline(pipeline)
