"""Checker family 3: ``zoo.*`` config-key drift.

Ground truth is the ``_DEFAULTS`` dict in ``common/config.py`` (the
checker finds it structurally -- any scanned file with a module-level
``_DEFAULTS = {...}`` of string keys -- so fixture projects work).
Three rules close the drift triangle between use sites, declarations,
and docs:

``config-undeclared`` (error)
    A ``.get("zoo.x")`` / ``.set(...)`` / ``.unset(...)`` call on a
    literal key missing from ``_DEFAULTS``: either a typo'd key
    silently reading its fallback, or a real knob nobody declared.

``config-unused`` (warning)
    A declared key with no use site anywhere in the scanned tree.
    Use sites include **indirect prefix access** -- the helper-wrapper
    idiom ``cfg.get("zoo.mesh.axis." + kind)`` /
    ``f"zoo.mesh.axis.{kind}"`` marks every declared key under that
    prefix as used (a naive grep flags exactly these as dead).

``config-undocumented`` (warning)
    A declared key never mentioned in ``docs/*.md``. Every knob in
    the glossary or it does not exist. Skipped when the project has
    no docs tree (fixtures).

Docstring string constants are excluded from use-site detection: a
key *described* in prose is not a key *read*.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, SourceFile, register)

_KEY_RE = re.compile(r"^zoo(\.[a-z0-9_]+)+$")
_CONFIG_METHODS = {"get", "set", "unset"}


def _defaults_decl(src: SourceFile
                   ) -> Optional[Dict[str, int]]:
    """{key: lineno} when this module assigns a dict of zoo.* string
    keys to ``_DEFAULTS`` at top level."""
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # _DEFAULTS: Dict[...] = {}
            targets = [node.target]
        if not (any(isinstance(t, ast.Name) and t.id == "_DEFAULTS"
                    for t in targets)
                and isinstance(getattr(node, "value", None), ast.Dict)):
            continue
        out: Dict[str, int] = {}
        for k in node.value.keys:
            if (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _KEY_RE.match(k.value)):
                out[k.value] = k.lineno
        if out:
            return out
    return None


def _literal_prefix(node: ast.AST) -> Optional[str]:
    """Leading literal of a dynamically-built key: ``"zoo.a." + x``,
    ``f"zoo.a.{x}"``, ``"zoo.a.%s" % x``, ``"zoo.a.{}".format(x)``."""
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Mod)):
        return _literal_prefix(node.left)
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return first.value
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"):
        return _literal_prefix(node.func.value)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Uses:
    def __init__(self):
        self.literals: Dict[str, List[Tuple[str, int]]] = {}
        self.prefixes: Dict[str, List[Tuple[str, int]]] = {}
        # literal keys passed to a config get/set/unset call
        self.config_calls: Dict[str, List[Tuple[str, int]]] = {}


def collect_uses(project: Project,
                 skip: Optional[SourceFile] = None) -> _Uses:
    uses = _Uses()
    for src in project.files:
        if src is skip:
            continue
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _KEY_RE.match(node.value)
                    and not src.is_docstring(node)):
                uses.literals.setdefault(node.value, []).append(
                    (src.rel, node.lineno))
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _CONFIG_METHODS
                        and node.args):
                    arg = node.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                            and arg.value.startswith("zoo.")):
                        uses.config_calls.setdefault(
                            arg.value, []).append(
                                (src.rel, arg.lineno))
                    else:
                        prefix = _literal_prefix(arg)
                        if prefix and prefix.startswith("zoo."):
                            uses.prefixes.setdefault(
                                prefix, []).append(
                                    (src.rel, arg.lineno))
    return uses


@register
class ConfigKeyChecker(Checker):
    name = "config"
    rules = {
        "config-undeclared": "config API call on a zoo.* key missing "
                             "from common.config _DEFAULTS",
        "config-unused": "declared _DEFAULTS key with no use site "
                         "(direct or prefix-wrapper) in the scanned "
                         "tree",
        "config-undocumented": "declared _DEFAULTS key never "
                               "mentioned in docs/*.md",
    }

    def check_project(self, project: Project) -> Iterable[Finding]:
        decl_src: Optional[SourceFile] = None
        declared: Dict[str, int] = {}
        for src in project.files:
            found = _defaults_decl(src)
            if found:
                decl_src, declared = src, found
                break
        if decl_src is None:
            return  # nothing to reconcile against
        uses = collect_uses(project, skip=decl_src)

        for key, sites in sorted(uses.config_calls.items()):
            if key in declared:
                continue
            rel, line = sites[0]
            yield Finding(
                "config-undeclared", "error", rel, line,
                f"config key '{key}' is read/written but not declared "
                "in common.config _DEFAULTS (typo, or add the "
                "default)")

        used_keys: Set[str] = set(uses.literals) | set(
            uses.config_calls)
        prefix_list = sorted(uses.prefixes)
        docs = project.docs_text()
        for key, line in sorted(declared.items()):
            direct = key in used_keys
            via_prefix = any(key.startswith(p) for p in prefix_list)
            if not direct and not via_prefix:
                yield Finding(
                    "config-unused", "warning", decl_src.rel, line,
                    f"config key '{key}' is declared in _DEFAULTS but "
                    "never read anywhere in the scanned tree (wire it "
                    "up, or delete/document it)")
            if docs and key not in docs:
                yield Finding(
                    "config-undocumented", "warning", decl_src.rel,
                    line,
                    f"config key '{key}' is not mentioned in any "
                    "docs/*.md; add it to the config glossary")
