"""Checker family 3: ``zoo.*`` config-key drift.

Ground truth is the ``_DEFAULTS`` dict in ``common/config.py`` (the
checker finds it structurally -- any scanned file with a module-level
``_DEFAULTS = {...}`` of string keys -- so fixture projects work).
Three rules close the drift triangle between use sites, declarations,
and docs:

``config-undeclared`` (error)
    A ``.get("zoo.x")`` / ``.set(...)`` / ``.unset(...)`` call on a
    literal key missing from ``_DEFAULTS``: either a typo'd key
    silently reading its fallback, or a real knob nobody declared.

``config-unused`` (warning)
    A declared key with no use site anywhere in the scanned tree.
    Use sites include **indirect prefix access** -- the helper-wrapper
    idiom ``cfg.get("zoo.mesh.axis." + kind)`` /
    ``f"zoo.mesh.axis.{kind}"`` marks every declared key under that
    prefix as used (a naive grep flags exactly these as dead).

``config-undocumented`` (warning)
    A declared key never mentioned in ``docs/*.md``. Every knob in
    the glossary or it does not exist. Skipped when the project has
    no docs tree (fixtures).

``config-type`` (error)
    Cross-boundary type/range drift against the ``_SPECS`` metadata
    dict next to ``_DEFAULTS`` (per-key ``("int", lo, hi)`` /
    ``("float", lo, hi)`` / ``("bool",)`` / ``("str",)`` /
    ``("enum", ...)`` shapes): a ``get``/``set`` call site whose
    literal default/value contradicts the declared type, falls
    outside the declared range, or whose wrapping ``int()``/
    ``float()``/``str()`` cast contradicts the declared type; plus
    self-checks -- a spec for an undeclared key, or a ``_DEFAULTS``
    value violating its own spec.

Docstring string constants are excluded from use-site detection: a
key *described* in prose is not a key *read*.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, SourceFile, register)

_KEY_RE = re.compile(r"^zoo(\.[a-z0-9_]+)+$")
_CONFIG_METHODS = {"get", "set", "unset"}


def _dict_decl(src: SourceFile, name: str) -> Optional[ast.Dict]:
    """The top-level ``<name> = {...}`` dict node of this module."""
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # name: Dict[...] = {}
            targets = [node.target]
        if (any(isinstance(t, ast.Name) and t.id == name
                for t in targets)
                and isinstance(getattr(node, "value", None), ast.Dict)):
            return node.value
    return None


def _defaults_decl(src: SourceFile
                   ) -> Optional[Dict[str, int]]:
    """{key: lineno} when this module assigns a dict of zoo.* string
    keys to ``_DEFAULTS`` at top level."""
    value = _dict_decl(src, "_DEFAULTS")
    if value is None:
        return None
    out: Dict[str, int] = {}
    for k in value.keys:
        if (isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and _KEY_RE.match(k.value)):
            out[k.value] = k.lineno
    return out or None


def _literal(node: ast.AST):
    """Python constant of a literal expression (incl. -5), else a
    _NO_LITERAL sentinel."""
    if isinstance(node, ast.Constant):
        return node.value
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, (int, float))):
        return -node.operand.value
    return _NO_LITERAL


_NO_LITERAL = object()


def _specs_decl(src: SourceFile) -> Optional[Dict[str, tuple]]:
    """{key: (lineno, spec tuple)} from a top-level ``_SPECS`` dict of
    ``key: ("type", ...)`` literal entries; malformed entries are
    skipped (conservative)."""
    value = _dict_decl(src, "_SPECS")
    if value is None:
        return None
    out: Dict[str, tuple] = {}
    for k, v in zip(value.keys, value.values):
        if not (isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and isinstance(v, (ast.Tuple, ast.List)) and v.elts):
            continue
        elems = [_literal(e) for e in v.elts]
        if any(e is _NO_LITERAL for e in elems) or not isinstance(
                elems[0], str):
            continue
        out[k.value] = (k.lineno, tuple(elems))
    return out or None


def _spec_violation(spec: tuple, value) -> Optional[str]:
    """Why ``value`` (a python literal) violates ``spec``, or None --
    delegates to the ONE shared implementation in common.config so
    the lint rule and launch-time validation cannot drift apart."""
    from analytics_zoo_tpu.common.config import spec_violation

    return spec_violation(spec, value)


# cast name -> spec kinds it contradicts (a float() around an int key
# is widening and fine; an int() around a float key truncates; any
# numeric cast around a str/enum key means the type metadata is wrong
# on one side of the boundary)
_CAST_CONFLICTS = {
    "int": ("str", "enum", "float"),
    "float": ("str", "enum"),
    "str": ("int", "float", "bool"),
}


def _literal_prefix(node: ast.AST) -> Optional[str]:
    """Leading literal of a dynamically-built key: ``"zoo.a." + x``,
    ``f"zoo.a.{x}"``, ``"zoo.a.%s" % x``, ``"zoo.a.{}".format(x)``."""
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Mod)):
        return _literal_prefix(node.left)
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return first.value
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"):
        return _literal_prefix(node.func.value)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Uses:
    def __init__(self):
        self.literals: Dict[str, List[Tuple[str, int]]] = {}
        self.prefixes: Dict[str, List[Tuple[str, int]]] = {}
        # literal keys passed to a config get/set/unset call
        self.config_calls: Dict[str, List[Tuple[str, int]]] = {}


def collect_uses(project: Project,
                 skip: Optional[SourceFile] = None) -> _Uses:
    uses = _Uses()
    for src in project.files:
        if src is skip:
            continue
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _KEY_RE.match(node.value)
                    and not src.is_docstring(node)):
                uses.literals.setdefault(node.value, []).append(
                    (src.rel, node.lineno))
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _CONFIG_METHODS
                        and node.args):
                    arg = node.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                            and arg.value.startswith("zoo.")):
                        uses.config_calls.setdefault(
                            arg.value, []).append(
                                (src.rel, arg.lineno))
                    else:
                        prefix = _literal_prefix(arg)
                        if prefix and prefix.startswith("zoo."):
                            uses.prefixes.setdefault(
                                prefix, []).append(
                                    (src.rel, arg.lineno))
    return uses


@register
class ConfigKeyChecker(Checker):
    name = "config"
    rules = {
        "config-undeclared": "config API call on a zoo.* key missing "
                             "from common.config _DEFAULTS",
        "config-unused": "declared _DEFAULTS key with no use site "
                         "(direct or prefix-wrapper) in the scanned "
                         "tree",
        "config-undocumented": "declared _DEFAULTS key never "
                               "mentioned in docs/*.md",
        "config-type": "get/set call site whose cast or literal "
                       "default contradicts the key's _SPECS "
                       "type/range metadata (or a spec/_DEFAULTS "
                       "self-inconsistency)",
    }

    def check_project(self, project: Project) -> Iterable[Finding]:
        decl_src: Optional[SourceFile] = None
        declared: Dict[str, int] = {}
        for src in project.files:
            found = _defaults_decl(src)
            if found:
                decl_src, declared = src, found
                break
        if decl_src is None:
            return  # nothing to reconcile against
        uses = collect_uses(project, skip=decl_src)
        yield from self._check_types(project, decl_src, declared)

        for key, sites in sorted(uses.config_calls.items()):
            if key in declared:
                continue
            rel, line = sites[0]
            yield Finding(
                "config-undeclared", "error", rel, line,
                f"config key '{key}' is read/written but not declared "
                "in common.config _DEFAULTS (typo, or add the "
                "default)")

        used_keys: Set[str] = set(uses.literals) | set(
            uses.config_calls)
        prefix_list = sorted(uses.prefixes)
        docs = project.docs_text()
        for key, line in sorted(declared.items()):
            direct = key in used_keys
            via_prefix = any(key.startswith(p) for p in prefix_list)
            if not direct and not via_prefix:
                yield Finding(
                    "config-unused", "warning", decl_src.rel, line,
                    f"config key '{key}' is declared in _DEFAULTS but "
                    "never read anywhere in the scanned tree (wire it "
                    "up, or delete/document it)")
            if docs and key not in docs:
                yield Finding(
                    "config-undocumented", "warning", decl_src.rel,
                    line,
                    f"config key '{key}' is not mentioned in any "
                    "docs/*.md; add it to the config glossary")

    # ------------------------------------------------- config-type ----
    def _check_types(self, project: Project, decl_src: SourceFile,
                     declared: Dict[str, int]) -> Iterable[Finding]:
        specs = _specs_decl(decl_src)
        if specs is None:
            # metadata may live next to a separate _DEFAULTS fixture
            for src in project.files:
                specs = _specs_decl(src)
                if specs is not None:
                    break
        if specs is None:
            return

        # self-checks: spec'd key must be declared; the _DEFAULTS
        # literal must satisfy its own spec
        defaults_dict = _dict_decl(decl_src, "_DEFAULTS")
        default_values: Dict[str, object] = {}
        if defaults_dict is not None:
            for k, v in zip(defaults_dict.keys, defaults_dict.values):
                if isinstance(k, ast.Constant) and isinstance(
                        k.value, str):
                    default_values[k.value] = _literal(v)
        for key, (line, spec) in sorted(specs.items()):
            if key not in declared:
                yield Finding(
                    "config-type", "error", decl_src.rel, line,
                    f"_SPECS declares metadata for '{key}' but "
                    "_DEFAULTS does not declare the key")
                continue
            default = default_values.get(key, _NO_LITERAL)
            if default is not _NO_LITERAL:
                why = _spec_violation(spec, default)
                if why:
                    yield Finding(
                        "config-type", "error", decl_src.rel, line,
                        f"_DEFAULTS value for '{key}' violates its "
                        f"own _SPECS entry: {why}")

        # use sites: literal-key get/set defaults + wrapping casts
        for src in project.files:
            if src is decl_src:
                continue
            parents: Dict[int, ast.AST] = {}
            for node in ast.walk(src.tree):
                for child in ast.iter_child_nodes(node):
                    parents[id(child)] = node
            for node in ast.walk(src.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("get", "set")
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                key = node.args[0].value
                if key not in specs:
                    continue
                _line, spec = specs[key]
                if len(node.args) > 1:
                    value = _literal(node.args[1])
                    if value is not _NO_LITERAL:
                        # get(key, None) = "absent is fine" sentinel,
                        # not a typed default -- never a finding
                        if not (node.func.attr == "get"
                                and value is None):
                            why = _spec_violation(spec, value)
                            if why:
                                word = ("default"
                                        if node.func.attr == "get"
                                        else "value")
                                yield Finding(
                                    "config-type", "error", src.rel,
                                    node.lineno,
                                    f"config {node.func.attr}() "
                                    f"{word} for '{key}' contradicts "
                                    f"its _SPECS entry: {why}")
                parent = parents.get(id(node))
                if (isinstance(parent, ast.Call)
                        and isinstance(parent.func, ast.Name)
                        and len(parent.args) == 1
                        and parent.args[0] is node):
                    conflicts = _CAST_CONFLICTS.get(parent.func.id)
                    if conflicts and spec[0] in conflicts:
                        yield Finding(
                            "config-type", "error", src.rel,
                            parent.lineno,
                            f"{parent.func.id}() cast around config "
                            f"key '{key}' contradicts its declared "
                            f"'{spec[0]}' type (fix the cast or the "
                            "_SPECS entry)")
