"""Baseline file support: grandfathered findings with rationales.

The baseline (``zoolint_baseline.json`` at the repo root) is the
checked-in set of findings a past reviewer accepted -- each entry
carries a ``rationale`` string saying *why* it is allowed to stay
(an inline ``# zoolint: disable=`` is preferred for new code; the
baseline exists so turning a new rule on does not require touching
every historical site in the same PR). The CLI exits non-zero only on
findings **not** in the baseline, and ``--update-baseline`` rewrites
the file preserving rationales for entries that survive.

Identity is :meth:`Finding.key` -- ``(rule, path, message)``, no line
numbers -- so the baseline tolerates edits elsewhere in a file but
goes stale the moment the flagged symbol itself changes (which is the
point: changed code must re-justify its exemption).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from analytics_zoo_tpu.analysis.core import Finding

BaselineKey = Tuple[str, str, str]


def load_baseline(path: str) -> Dict[BaselineKey, Dict]:
    """{(rule, path, message): entry}; empty when the file is absent."""
    if not path or not os.path.isfile(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[BaselineKey, Dict] = {}
    for entry in data.get("findings", []):
        key = (entry["rule"], entry["path"], entry["message"])
        out[key] = entry
    return out


def new_findings(findings: Sequence[Finding],
                 baseline: Dict[BaselineKey, Dict]) -> List[Finding]:
    return [f for f in findings if f.key() not in baseline]


def stale_entries(findings: Sequence[Finding],
                  baseline: Dict[BaselineKey, Dict]) -> List[Dict]:
    """Baseline entries whose finding no longer fires (fixed code or a
    renamed symbol) -- reported so the baseline shrinks over time
    instead of accreting dead exemptions."""
    live = {f.key() for f in findings}
    return [e for k, e in sorted(baseline.items()) if k not in live]


def write_baseline(findings: Sequence[Finding], path: str,
                   previous: Dict[BaselineKey, Dict]) -> int:
    """Write every current finding as a baseline entry, carrying over
    rationales from ``previous`` where the key survives. Returns the
    entry count."""
    entries = []
    for f in sorted(findings, key=lambda f: f.key()):
        prev = previous.get(f.key(), {})
        entries.append({
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
            "severity": f.severity,
            "rationale": prev.get("rationale", ""),
        })
    with open(path, "w") as out:
        json.dump({"findings": entries}, out, indent=2, sort_keys=True)
        out.write("\n")
    return len(entries)
