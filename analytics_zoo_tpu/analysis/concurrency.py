"""Checker family 2: concurrency lints for the threaded layers.

The serving data plane (worker/batcher/queues/frontends) and the obs
stack are the only deliberately multi-threaded parts of the package,
so these rules are scoped to files under ``serving/`` and ``obs/`` by
default (``restrict_dirs=None`` lifts the scope -- unit-test
fixtures). Three rules:

``lock-guard`` (warning)
    Lock-guard inference: within one class, an attribute assigned
    both inside ``with self.<lock>:`` and outside it (in non-init
    methods) is either missing a guard at the unguarded site or
    carrying a redundant one at the guarded site -- both are worth a
    human look. ``__init__``/``__new__`` are exempt (construction
    happens-before publication), as are the lock attributes
    themselves.

``lock-order`` (error)
    Two locks of one class acquired nested in opposite orders across
    methods: the classic ABBA deadlock, invisible until the unlucky
    interleaving ships.

``thread-join`` (warning)
    A non-daemon ``threading.Thread`` whose owner never calls
    ``.join`` on it: process exit then blocks on the forgotten
    thread. Either pass ``daemon=True`` (and accept hard-kill
    semantics) or join it in the stop path.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, SourceFile, register)

_INIT_METHODS = {"__init__", "__new__", "__init_subclass__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_name_of_with_item(item: ast.withitem) -> Optional[str]:
    """Attr name for ``with self.<name>:`` items that look like locks
    (name contains 'lock' or 'mutex'), incl. ``self._lock.acquire``-
    style guards via ``with self._lock:`` only."""
    attr = _self_attr(item.context_expr)
    if attr and ("lock" in attr.lower() or "mutex" in attr.lower()):
        return attr
    return None


def _is_thread_ctor(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr == "Thread":
        root = func.value
        return isinstance(root, ast.Name) and root.id == "threading"
    return isinstance(func, ast.Name) and func.id == "Thread"


def _daemon_true(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon":
            return (isinstance(kw.value, ast.Constant)
                    and kw.value.value is True)
    return False


class _MethodScan(ast.NodeVisitor):
    """One method: every self-attr assignment tagged with the lock
    stack active at that point, plus nested lock-acquisition pairs.
    Nested function defs are traversed (closures mutate state too);
    nested class defs are not."""

    def __init__(self):
        self.lock_stack: List[str] = []
        # attr -> set of "guarded by" frozensets observed
        self.writes: List[Tuple[str, Tuple[str, ...], int]] = []
        self.pairs: List[Tuple[str, str, int]] = []
        self.locks_seen: Set[str] = set()

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            name = _lock_name_of_with_item(item)
            if name:
                self.locks_seen.add(name)
                for held in self.lock_stack:
                    if held != name:
                        self.pairs.append((held, name, node.lineno))
                acquired.append(name)
        self.lock_stack.extend(acquired)
        self.generic_visit(node)
        for _ in acquired:
            self.lock_stack.pop()

    visit_AsyncWith = visit_With

    def _record_targets(self, targets, lineno: int) -> None:
        for t in targets:
            for node in ast.walk(t):
                attr = _self_attr(node)
                if attr:
                    self.writes.append(
                        (attr, tuple(self.lock_stack), lineno))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record_targets(node.targets, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_targets([node.target], node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_targets([node.target], node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        self._record_targets(node.targets, node.lineno)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return  # a nested class is its own synchronization domain


@register
class ConcurrencyChecker(Checker):
    name = "concurrency"
    rules = {
        "lock-guard": "attribute assigned both inside and outside "
                      "'with self.<lock>:' in the same class",
        "lock-order": "two locks acquired nested in opposite orders "
                      "across methods (ABBA deadlock)",
        "thread-join": "non-daemon threading.Thread never joined by "
                       "its owner",
    }

    def __init__(self, restrict_dirs: Optional[Tuple[str, ...]] = (
            "serving", "obs")):
        self.restrict_dirs = restrict_dirs

    def _in_scope(self, src: SourceFile) -> bool:
        if self.restrict_dirs is None:
            return True
        parts = src.rel.split("/")
        return any(d in parts for d in self.restrict_dirs)

    # ----------------------------------------------------- per class --
    def _check_class(self, src: SourceFile,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        # attr -> {"guarded": {(method, line)}, "bare": {(method, line)}}
        guarded: Dict[str, List[Tuple[str, int]]] = {}
        bare: Dict[str, List[Tuple[str, int]]] = {}
        # (lockA, lockB) -> [(method, line)] for A held while taking B
        order: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        locks: Set[str] = set()
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scan = _MethodScan()
            for stmt in item.body:
                scan.visit(stmt)
            locks |= scan.locks_seen
            for a, b, line in scan.pairs:
                order.setdefault((a, b), []).append((item.name, line))
            if item.name in _INIT_METHODS:
                continue  # construction happens-before publication
            for attr, held, line in scan.writes:
                if held:
                    guarded.setdefault(attr, []).append(
                        (item.name, line))
                else:
                    bare.setdefault(attr, []).append((item.name, line))
        for attr in sorted(set(guarded) & set(bare)):
            if attr in locks:
                continue
            g_methods = sorted({m for m, _ in guarded[attr]})
            b_methods = sorted({m for m, _ in bare[attr]})
            line = min(l for _, l in bare[attr])
            yield Finding(
                "lock-guard", "warning", src.rel, line,
                f"{cls.name}.{attr} is assigned under a lock in "
                f"{', '.join(g_methods)} but without one in "
                f"{', '.join(b_methods)}; guard the bare writes or "
                "document why they are safe")
        for (a, b), sites in sorted(order.items()):
            if (b, a) in order and a < b:  # report each pair once
                m1 = sorted({m for m, _ in sites})
                m2 = sorted({m for m, _ in order[(b, a)]})
                line = min(l for _, l in sites)
                yield Finding(
                    "lock-order", "error", src.rel, line,
                    f"{cls.name} acquires self.{a} then self.{b} in "
                    f"{', '.join(m1)} but self.{b} then self.{a} in "
                    f"{', '.join(m2)}; pick one order (ABBA "
                    "deadlock)")

    # --------------------------------------------------- thread-join --
    def _check_threads(self, src: SourceFile) -> Iterable[Finding]:
        # parent links so a Thread(...) call can find its Assign
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(src.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        joined: Set[str] = set()  # attr or local names .join()-ed
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr == "join"):
                base = _self_attr(node.value)
                if base is None and isinstance(node.value, ast.Name):
                    base = node.value.id
                if base:
                    joined.add(base)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and _is_thread_ctor(node)):
                continue
            if _daemon_true(node):
                continue
            parent = parents.get(id(node))
            target_name: Optional[str] = None
            if isinstance(parent, ast.Assign) and parent.targets:
                t = parent.targets[0]
                target_name = _self_attr(t) or (
                    t.id if isinstance(t, ast.Name) else None)
            if target_name and target_name in joined:
                continue
            where = (f"bound to '{target_name}'" if target_name
                     else "unbound (started inline?)")
            yield Finding(
                "thread-join", "warning", src.rel, node.lineno,
                f"non-daemon threading.Thread {where} is never "
                "joined; pass daemon=True or join it in the stop "
                "path so process exit cannot hang")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        if not self._in_scope(src):
            return
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(src, node)
        yield from self._check_threads(src)
