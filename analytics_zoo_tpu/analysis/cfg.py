"""Per-function control-flow graphs: the substrate of engine #4.

The first three zoolint engines (AST rules, dataflow, call graph) are
all path-*insensitive*: they see that a release call exists, not
whether every path from the acquire reaches it.  This module builds a
CFG per function -- branches, loops, try/except/finally, with-blocks,
early return/raise/break/continue, and *exception edges* -- so
``lifecycle_rules`` can walk paths and prove pairing properties the
runtime ledger can only enforce dynamically.

Model (chosen for lint-scale precision, documented in
docs/zoolint.md):

- One :class:`Node` per simple statement, plus synthetic nodes:
  ``entry``, ``exit`` (normal completion), ``raise-exit`` (an
  exception left the function), ``branch``/``loop`` headers,
  ``except`` handler entries, ``finally``/``with-exit`` unwind
  anchors.
- Edges are ``(successor, label)`` with labels ``next``, ``true``,
  ``false``, ``back`` (loop back edge), ``return``, ``break``,
  ``raise`` (explicit), ``exc`` (unwind continuation), ``case``, and
  ``mayraise`` -- the *implicit* exception edge added for statements
  the ``may_raise`` predicate accepts (default: contains a call).
  On a ``mayraise``/``raise`` edge the statement's effects have NOT
  happened -- walkers must propagate the pre-state.
- ``finally`` bodies (and ``with`` unwinds) are **duplicated** per
  crossing kind -- one copy on the normal path, one per abrupt jump
  (return/break/continue) that crosses them, and one shared copy for
  the exception unwind.  Sharing a single copy would merge paths that
  continue to different places and fabricate infeasible routes; at
  lint scale the duplication is cheap and exact.  A node-count cap
  (``max_nodes``) makes pathological nesting degrade to "no CFG"
  (conservative: callers skip the function) rather than blow up.
- ``iter_paths`` enumerates complete entry-to-exit paths taking each
  *edge* at most once -- every loop contributes its zero-iteration
  and one-iteration paths, which is exactly the precision the
  lifecycle rules need (a leak that needs two iterations to manifest
  also manifests in one).
"""

from __future__ import annotations

import ast
from typing import Callable, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = ["Node", "CFG", "build_cfg", "default_may_raise",
           "iter_paths"]

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Lambda)


def _calls_in(node: ast.AST) -> bool:
    """True when ``node`` contains a Call that executes *here* --
    nested def/class/lambda bodies run later (or never) and are
    pruned."""
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.Call):
            return True
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _NESTED_SCOPES):
                continue
            stack.append(child)
    return False


def default_may_raise(stmt: ast.stmt) -> bool:
    """Default implicit-exception predicate: a statement that calls
    anything may raise.  Asserts always may (AssertionError).  Walkers
    with domain knowledge (lifecycle: a bare registered release call
    is exempt, or exception paths would flag the cleanup itself) pass
    their own predicate to :func:`build_cfg`."""
    if isinstance(stmt, ast.Assert):
        return True
    return _calls_in(stmt)


class Node:
    """One CFG node. ``stmt`` is the owning AST statement (None for
    entry/exit), ``kind`` one of: entry, exit, raise-exit, stmt,
    raise, branch, loop, except, with, with-exit, finally."""

    __slots__ = ("stmt", "kind", "idx", "succ")

    def __init__(self, stmt: Optional[ast.AST], kind: str, idx: int):
        self.stmt = stmt
        self.kind = kind
        self.idx = idx
        self.succ: List[Tuple["Node", str]] = []

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<cfg {self.kind}#{self.idx} L{self.line}>"


class CFG:
    """The built graph for one function."""

    def __init__(self, func: ast.AST):
        self.func = func
        self.name = getattr(func, "name", "<lambda>")
        self.nodes: List[Node] = []
        self.entry = self._new_node(None, "entry")
        self.exit = self._new_node(None, "exit")
        self.raise_exit = self._new_node(None, "raise-exit")

    def _new_node(self, stmt: Optional[ast.AST], kind: str) -> Node:
        node = Node(stmt, kind, len(self.nodes))
        self.nodes.append(node)
        return node


class _Overflow(Exception):
    pass


class _LoopFrame:
    __slots__ = ("header", "breaks")

    def __init__(self, header: Node):
        self.header = header
        self.breaks: List[Tuple[Node, str]] = []


class _TryFrame:
    __slots__ = ("handlers", "catch_all")

    def __init__(self, handlers: List[Node], catch_all: bool):
        self.handlers = handlers
        self.catch_all = catch_all


class _FinallyFrame:
    """A ``finally`` body (or a ``with`` __exit__) every route out of
    the guarded region must run.  ``_unwind`` caches the one shared
    exception-unwind copy."""

    __slots__ = ("body", "anchor", "is_with", "_unwind")

    def __init__(self, body: Optional[Sequence[ast.stmt]],
                 anchor: ast.stmt, is_with: bool = False):
        self.body = body
        self.anchor = anchor
        self.is_with = is_with
        self._unwind: Optional[Node] = None


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    """Bare ``except:`` or Exception/BaseException (incl. inside a
    tuple) stops outward exception propagation."""
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception",
                                                "BaseException"):
            return True
    return False


# frontier: list of (node, label) dangling edges awaiting their target
_Frontier = List[Tuple[Node, str]]


class _Builder:
    def __init__(self, cfg: CFG,
                 may_raise: Callable[[ast.stmt], bool],
                 max_nodes: int):
        self.cfg = cfg
        self.may_raise = may_raise
        self.max_nodes = max_nodes

    def new(self, stmt: Optional[ast.AST], kind: str = "stmt") -> Node:
        if len(self.cfg.nodes) >= self.max_nodes:
            raise _Overflow
        return self.cfg._new_node(stmt, kind)

    @staticmethod
    def connect(frontier: _Frontier, target: Node) -> None:
        for node, label in frontier:
            node.succ.append((target, label))

    # ------------------------------------------------------- driver --
    def build(self) -> None:
        frontier = self.stmts(self.cfg.func.body,
                              [(self.cfg.entry, "next")], [])
        self.connect(frontier, self.cfg.exit)

    def stmts(self, body: Sequence[ast.stmt], frontier: _Frontier,
              stack: list) -> _Frontier:
        for s in body:
            frontier = self.stmt(s, frontier, stack)
        return frontier

    # -------------------------------------------- exception routing --
    def _exc_targets(self, stack: list) -> List[Node]:
        """Where an exception raised under ``stack`` goes first:
        every reachable handler entry, then (unless a catch-all
        stops it) the nearest finally unwind or raise-exit."""
        targets: List[Node] = []
        for i in range(len(stack) - 1, -1, -1):
            fr = stack[i]
            if isinstance(fr, _FinallyFrame):
                targets.append(self._unwind_entry(fr, stack[:i]))
                return targets
            if isinstance(fr, _TryFrame):
                targets.extend(fr.handlers)
                if fr.catch_all:
                    return targets
        targets.append(self.cfg.raise_exit)
        return targets

    def _unwind_entry(self, fr: _FinallyFrame, outer: list) -> Node:
        """The shared exception-path copy of a finally/with unwind:
        run the body, then keep propagating outward."""
        if fr._unwind is not None:
            return fr._unwind
        if fr.is_with:
            head = self.new(fr.anchor, "with-exit")
            fr._unwind = head
            tail: _Frontier = [(head, "next")]
        else:
            head = self.new(fr.anchor, "finally")
            fr._unwind = head
            tail = self.stmts(fr.body, [(head, "next")], list(outer))
        targets = self._exc_targets(outer)
        for node, _label in tail:
            for target in targets:
                node.succ.append((target, "exc"))
        return head

    def _add_exc_edges(self, node: Node, stack: list,
                       label: str) -> None:
        for target in self._exc_targets(stack):
            node.succ.append((target, label))

    def _route_through_finallys(self, frontier: _Frontier, stack: list,
                                stop_index: int) -> _Frontier:
        """Build fresh finally copies for every _FinallyFrame in
        ``stack[stop_index+1:]``, innermost first -- the path an
        abrupt jump (return/break/continue) takes."""
        for i in range(len(stack) - 1, stop_index, -1):
            fr = stack[i]
            if isinstance(fr, _FinallyFrame):
                frontier = self._finally_copy(fr, frontier, stack[:i])
        return frontier

    def _finally_copy(self, fr: _FinallyFrame, frontier: _Frontier,
                      outer: list) -> _Frontier:
        if fr.is_with:
            node = self.new(fr.anchor, "with-exit")
            self.connect(frontier, node)
            return [(node, "next")]
        head = self.new(fr.anchor, "finally")
        self.connect(frontier, head)
        return self.stmts(fr.body, [(head, "next")], list(outer))

    # ---------------------------------------------------- dispatch --
    def stmt(self, s: ast.stmt, frontier: _Frontier,
             stack: list) -> _Frontier:
        if isinstance(s, ast.If):
            return self._if(s, frontier, stack)
        if isinstance(s, ast.While):
            return self._while(s, frontier, stack)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return self._for(s, frontier, stack)
        if isinstance(s, ast.Try) or (hasattr(ast, "TryStar")
                                      and isinstance(s, ast.TryStar)):
            return self._try(s, frontier, stack)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self._with(s, frontier, stack)
        if isinstance(s, ast.Return):
            return self._return(s, frontier, stack)
        if isinstance(s, ast.Raise):
            return self._raise(s, frontier, stack)
        if isinstance(s, ast.Break):
            return self._break(s, frontier, stack)
        if isinstance(s, ast.Continue):
            return self._continue(s, frontier, stack)
        if hasattr(ast, "Match") and isinstance(s, ast.Match):
            return self._match(s, frontier, stack)
        node = self.new(s, "stmt")
        self.connect(frontier, node)
        if not isinstance(s, _NESTED_SCOPES) and self.may_raise(s):
            self._add_exc_edges(node, stack, "mayraise")
        return [(node, "next")]

    def _if(self, s: ast.If, frontier: _Frontier,
            stack: list) -> _Frontier:
        node = self.new(s, "branch")
        self.connect(frontier, node)
        if _calls_in(s.test):
            self._add_exc_edges(node, stack, "mayraise")
        out = self.stmts(s.body, [(node, "true")], stack)
        if s.orelse:
            out = out + self.stmts(s.orelse, [(node, "false")], stack)
        else:
            out = out + [(node, "false")]
        return out

    def _while(self, s: ast.While, frontier: _Frontier,
               stack: list) -> _Frontier:
        header = self.new(s, "loop")
        self.connect(frontier, header)
        if _calls_in(s.test):
            self._add_exc_edges(header, stack, "mayraise")
        lf = _LoopFrame(header)
        body = self.stmts(s.body, [(header, "true")], stack + [lf])
        for node, _label in body:
            node.succ.append((header, "back"))
        out: _Frontier = []
        # ``while True:`` has no normal exit edge -- only breaks leave
        always = (isinstance(s.test, ast.Constant) and bool(s.test.value))
        if not always:
            if s.orelse:
                out += self.stmts(s.orelse, [(header, "false")], stack)
            else:
                out += [(header, "false")]
        return out + lf.breaks

    def _for(self, s, frontier: _Frontier, stack: list) -> _Frontier:
        header = self.new(s, "loop")
        self.connect(frontier, header)
        if _calls_in(s.iter):
            self._add_exc_edges(header, stack, "mayraise")
        lf = _LoopFrame(header)
        body = self.stmts(s.body, [(header, "true")], stack + [lf])
        for node, _label in body:
            node.succ.append((header, "back"))
        out: _Frontier = []
        if s.orelse:
            out += self.stmts(s.orelse, [(header, "false")], stack)
        else:
            out += [(header, "false")]
        return out + lf.breaks

    def _try(self, s, frontier: _Frontier, stack: list) -> _Frontier:
        fin: Optional[_FinallyFrame] = None
        stack_f = stack
        if s.finalbody:
            fin = _FinallyFrame(s.finalbody, s)
            stack_f = stack + [fin]
        entries: List[Node] = []
        catch_all = False
        for h in s.handlers:
            entries.append(self.new(h, "except"))
            catch_all = catch_all or _is_catch_all(h)
        if s.handlers:
            tf = _TryFrame(entries, catch_all)
            out = self.stmts(s.body, frontier, stack_f + [tf])
        else:
            out = self.stmts(s.body, frontier, stack_f)
        if s.orelse:  # runs only on clean try body; its exceptions
            out = self.stmts(s.orelse, out, stack_f)  # skip handlers
        for h, entry in zip(s.handlers, entries):
            out = out + self.stmts(h.body, [(entry, "next")], stack_f)
        if fin is not None:
            out = self._finally_copy(fin, out, stack)
        return out

    def _with(self, s, frontier: _Frontier, stack: list) -> _Frontier:
        node = self.new(s, "with")
        self.connect(frontier, node)
        if any(_calls_in(it.context_expr) for it in s.items):
            # the context-manager expression can raise BEFORE the
            # scope exists -- that edge bypasses __exit__
            self._add_exc_edges(node, stack, "mayraise")
        fr = _FinallyFrame(None, s, is_with=True)
        body = self.stmts(s.body, [(node, "next")], stack + [fr])
        exit_node = self.new(s, "with-exit")
        self.connect(body, exit_node)
        return [(exit_node, "next")]

    def _return(self, s: ast.Return, frontier: _Frontier,
                stack: list) -> _Frontier:
        node = self.new(s, "stmt")
        self.connect(frontier, node)
        if self.may_raise(s):
            self._add_exc_edges(node, stack, "mayraise")
        out = self._route_through_finallys([(node, "return")],
                                           stack, -1)
        self.connect(out, self.cfg.exit)
        return []

    def _raise(self, s: ast.Raise, frontier: _Frontier,
               stack: list) -> _Frontier:
        node = self.new(s, "raise")
        self.connect(frontier, node)
        for target in self._exc_targets(stack):
            node.succ.append((target, "raise"))
        return []

    def _loop_index(self, stack: list) -> int:
        for i in range(len(stack) - 1, -1, -1):
            if isinstance(stack[i], _LoopFrame):
                return i
        return -1

    def _break(self, s, frontier: _Frontier, stack: list) -> _Frontier:
        idx = self._loop_index(stack)
        if idx < 0:  # syntactically invalid; degrade to a plain stmt
            node = self.new(s, "stmt")
            self.connect(frontier, node)
            return [(node, "next")]
        node = self.new(s, "stmt")
        self.connect(frontier, node)
        out = self._route_through_finallys([(node, "break")],
                                           stack, idx)
        stack[idx].breaks.extend(out)
        return []

    def _continue(self, s, frontier: _Frontier,
                  stack: list) -> _Frontier:
        idx = self._loop_index(stack)
        if idx < 0:
            node = self.new(s, "stmt")
            self.connect(frontier, node)
            return [(node, "next")]
        node = self.new(s, "stmt")
        self.connect(frontier, node)
        out = self._route_through_finallys([(node, "next")],
                                           stack, idx)
        self.connect(out, stack[idx].header)
        # label fix: edges into the header from a continue are back
        # edges; connect() wrote them with their carried labels, which
        # is fine for walkers (the header is the loop node either way)
        return []

    def _match(self, s, frontier: _Frontier, stack: list) -> _Frontier:
        node = self.new(s, "branch")
        self.connect(frontier, node)
        out: _Frontier = [(node, "false")]  # no case matched
        for case in s.cases:
            out += self.stmts(case.body, [(node, "case")], stack)
        return out


def build_cfg(func: ast.AST,
              may_raise: Optional[Callable[[ast.stmt], bool]] = None,
              max_nodes: int = 4000) -> Optional[CFG]:
    """Build the CFG for one FunctionDef/AsyncFunctionDef.  Returns
    None when the function exceeds ``max_nodes`` (pathological
    nesting): callers must treat that as "no knowledge", never as
    "clean" -- conservative, like every engine here."""
    if may_raise is None:
        may_raise = default_may_raise
    cfg = CFG(func)
    builder = _Builder(cfg, may_raise, max_nodes)
    try:
        builder.build()
    except _Overflow:
        return None
    except RecursionError:  # pragma: no cover - absurd nesting
        return None
    return cfg


def iter_paths(cfg: CFG, max_paths: int = 4096
               ) -> Iterator[Tuple[Tuple[str, Node], ...]]:
    """Enumerate complete paths from entry to exit/raise-exit as
    tuples of (edge label, node).  Each *edge* is taken at most once
    per path, so every loop yields its zero- and one-iteration
    variants without unrolling.  Stops quietly after ``max_paths``
    (callers needing to know use a counter and compare)."""
    emitted = 0
    path: List[Tuple[str, Node]] = []
    used: Set[Tuple[int, int]] = set()

    def walk(node: Node) -> Iterator[Tuple[Tuple[str, Node], ...]]:
        nonlocal emitted
        if emitted >= max_paths:
            return
        if node.kind in ("exit", "raise-exit"):
            emitted += 1
            yield tuple(path)
            return
        for pos, (nxt, label) in enumerate(node.succ):
            key = (node.idx, pos)
            if key in used:
                continue
            used.add(key)
            path.append((label, nxt))
            yield from walk(nxt)
            path.pop()
            used.discard(key)
            if emitted >= max_paths:
                return

    yield from walk(cfg.entry)
