"""Checker family 1: jit/pmap/shard_map trace + concretization hazards.

The static twin of obs.events.RecompileDetector: the runtime detector
notices a jitted fn compiling N distinct shapes inside a window; these
rules catch the code patterns that *cause* retraces or trace-time
errors before they ship.

A function counts as **jitted** when it is

- decorated ``@jax.jit`` / ``@jit`` / ``@jax.pmap`` /
  ``@partial(jax.jit, ...)`` (any of jit/pmap/shard_map spellings), or
- passed by name to ``jax.jit(fn, ...)`` / ``jax.shard_map(fn, ...)``
  anywhere in the same module (the repo's dominant idiom:
  ``self._step = jax.jit(step)``), or
- a lambda given directly to one of those wrappers.

Inside a jitted function, its parameters (minus ``static_argnums`` /
``static_argnames``) are tracers. Rules:

``jit-numpy-call`` (error)
    ``np.*(...)`` with a tracer-derived argument: numpy concretizes
    the tracer (ConcretizationTypeError at best, a silently host-
    computed constant at worst). Use ``jnp``/``lax`` inside traces.

``jit-concretize`` (error)
    ``.item()`` / ``float()`` / ``int()`` / ``bool()`` on a tracer-
    derived value: forces a host sync + concrete value mid-trace.

``jit-tracer-branch`` (error)
    Python ``if``/``while`` on a tracer-derived condition: either a
    trace error or -- when the value sneaks in via a static argument
    -- one full retrace *per distinct value*, the exact storm the
    runtime detector pages on. Shape/dtype/``is None`` conditions are
    static and exempt.

``jit-static-argnums`` (warning)
    ``static_argnums``/``static_argnames`` given a list/set/dict
    display (unhashable; jit's cache key wants an int or tuple of
    ints) or non-int/str elements.

Tracer-ness is decided by :func:`_is_tracer_expr` -- a conservative
symbolic walk that treats ``x.shape`` / ``x.ndim`` / ``x.dtype`` /
``x.size`` / ``len(x)`` / ``isinstance(x, ...)`` / ``x is None`` as
static (they are, at trace time), so shape-bucketing branches and
None-gated optional operands do not fire.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, SourceFile, register)

_JIT_NAMES = {"jit", "pmap", "shard_map"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_FUNCS = {"len", "isinstance", "type", "hasattr", "range",
                 "enumerate", "zip"}


def _jit_kind(func: ast.expr) -> Optional[str]:
    """'jit'/'pmap'/'shard_map' when ``func`` names a jit-family
    wrapper (bare or as ``jax.<name>`` / ``api.<name>``)."""
    if isinstance(func, ast.Name) and func.id in _JIT_NAMES:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in _JIT_NAMES:
        return func.attr
    return None


def _is_partial(func: ast.expr) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "partial"
    if isinstance(func, ast.Attribute):
        return func.attr == "partial"
    return False


def _static_params(call: Optional[ast.Call],
                   fn: ast.AST) -> Set[str]:
    """Param names made static by static_argnums/static_argnames on
    the wrapping jit call (best-effort: literal ints/strs only)."""
    if call is None:
        return set()
    args = getattr(fn, "args", None)
    pos: List[str] = []
    if args is not None:
        pos = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(
                        c.value, int) and 0 <= c.value < len(pos):
                    out.add(pos[c.value])
        elif kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(
                        c.value, str):
                    out.add(c.value)
    return out


def _is_tracer_expr(node: ast.AST, params: Set[str]) -> bool:
    """Conservative 'may hold a tracer at trace time' walk."""
    if isinstance(node, ast.Name):
        return node.id in params
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False  # x.shape / x.dtype are concrete under trace
        return _is_tracer_expr(node.value, params)
    if isinstance(node, ast.Subscript):
        return _is_tracer_expr(node.value, params)
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Name) and func.id in _STATIC_FUNCS:
            return False  # len(x), isinstance(x, ...) are static
        children = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(func, ast.Attribute):
            children.append(func.value)  # x.astype(...) tracks x
        return any(_is_tracer_expr(c, params) for c in children)
    if isinstance(node, ast.Compare):
        # `x is None` / `x is not None` resolve statically at trace
        # time (a tracer is never None); other comparators propagate
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return (_is_tracer_expr(node.left, params)
                or any(_is_tracer_expr(c, params)
                       for c in node.comparators))
    if isinstance(node, ast.BoolOp):
        return any(_is_tracer_expr(v, params) for v in node.values)
    if isinstance(node, ast.BinOp):
        return (_is_tracer_expr(node.left, params)
                or _is_tracer_expr(node.right, params))
    if isinstance(node, ast.UnaryOp):
        return _is_tracer_expr(node.operand, params)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_is_tracer_expr(e, params) for e in node.elts)
    if isinstance(node, ast.IfExp):
        return (_is_tracer_expr(node.body, params)
                or _is_tracer_expr(node.orelse, params))
    return False


def _np_root(func: ast.expr) -> Optional[str]:
    """'np'/'numpy'/'onp' when ``func`` is an attribute chain rooted
    at a host-numpy module alias."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name) and node.id in ("np", "numpy", "onp"):
        return node.id
    return None


class _JittedFn:
    def __init__(self, fn: ast.AST, kind: str,
                 call: Optional[ast.Call]):
        self.fn = fn
        self.kind = kind
        self.name = getattr(fn, "name", "<lambda>")
        args = getattr(fn, "args", None)
        names: Set[str] = set()
        if args is not None:
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                names.add(a.arg)
        self.params = names - _static_params(call, fn)


def jitted_functions(src: SourceFile) -> List["_JittedFn"]:
    """Every function the PR-4 detection counts as jit/pmap/shard_map
    traced in this file (decorated, wrapped by name, or an inline
    lambda). Shared with the interprocedural layer
    (:mod:`analytics_zoo_tpu.analysis.callgraph`), which uses these as
    the jit roots of its context propagation."""
    return TraceHazardChecker()._jitted_functions(src)


@register
class TraceHazardChecker(Checker):
    name = "trace"
    rules = {
        "jit-numpy-call": "host numpy call on a traced value inside a "
                          "jitted function (use jnp/lax)",
        "jit-concretize": ".item()/float()/int()/bool() on a traced "
                          "value inside a jitted function",
        "jit-tracer-branch": "Python if/while on a traced value inside "
                             "a jitted function (retrace or trace "
                             "error; use lax.cond/jnp.where)",
        "jit-static-argnums": "static_argnums/static_argnames should "
                              "be an int/str or tuple literal "
                              "(lists/sets/dicts are unhashable cache "
                              "keys)",
    }

    # ------------------------------------------------------ discovery --
    def _jitted_functions(self, src: SourceFile) -> List[_JittedFn]:
        tree = src.tree
        # pass 1: names (and lambdas) handed to jit-family wrappers
        wrapped: Dict[str, ast.Call] = {}
        lambdas: List[Tuple[ast.Lambda, str, ast.Call]] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _jit_kind(node.func)
            if kind is None or not node.args:
                continue
            target = node.args[0]
            if isinstance(target, ast.Name):
                wrapped[target.id] = node
            elif isinstance(target, ast.Lambda):
                lambdas.append((target, kind, node))
        # pass 2: decorated defs + defs matching a wrapped name
        out: List[_JittedFn] = []
        claimed: Set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            deco_call: Optional[ast.Call] = None
            kind: Optional[str] = None
            for deco in node.decorator_list:
                k = _jit_kind(deco)
                if k is None and isinstance(deco, ast.Call):
                    k = _jit_kind(deco.func)
                    if k is not None:
                        deco_call = deco
                    elif _is_partial(deco.func) and deco.args:
                        k = _jit_kind(deco.args[0])
                        if k is not None:
                            deco_call = deco
                if k is not None:
                    kind = k
                    break
            if kind is None and node.name in wrapped:
                kind = _jit_kind(wrapped[node.name].func) or "jit"
                deco_call = wrapped[node.name]
            if kind is not None and id(node) not in claimed:
                claimed.add(id(node))
                out.append(_JittedFn(node, kind, deco_call))
        for lam, kind, call in lambdas:
            out.append(_JittedFn(lam, kind, call))
        return out

    # ------------------------------------------------------- per rule --
    def _check_body(self, src: SourceFile,
                    jf: _JittedFn) -> Iterable[Finding]:
        params = jf.params
        body = (jf.fn.body if isinstance(jf.fn.body, list)
                else [jf.fn.body])
        for stmt in body:
            for node in ast.walk(stmt):
                # nested defs re-bind their own params; a shadowing
                # inner fn is rare enough that the conservative shared
                # param set is acceptable
                if isinstance(node, ast.Call):
                    root = _np_root(node.func)
                    if root is not None and any(
                            _is_tracer_expr(a, params)
                            for a in list(node.args)
                            + [kw.value for kw in node.keywords]):
                        yield Finding(
                            "jit-numpy-call", "error", src.rel,
                            node.lineno,
                            f"{jf.kind}-traced function "
                            f"'{jf.name}' calls host numpy "
                            f"({root}.{self._attr_chain(node.func)}) "
                            "on a traced value; use jnp/lax so the op "
                            "stays in the XLA program")
                        continue
                    if (isinstance(node.func, ast.Attribute)
                            and node.func.attr == "item"
                            and not node.args
                            and _is_tracer_expr(node.func.value,
                                                params)):
                        yield Finding(
                            "jit-concretize", "error", src.rel,
                            node.lineno,
                            f"{jf.kind}-traced function "
                            f"'{jf.name}' calls .item() on a traced "
                            "value (host sync + concretization inside "
                            "the trace)")
                        continue
                    if (isinstance(node.func, ast.Name)
                            and node.func.id in ("float", "int", "bool")
                            and len(node.args) == 1
                            and _is_tracer_expr(node.args[0], params)):
                        yield Finding(
                            "jit-concretize", "error", src.rel,
                            node.lineno,
                            f"{jf.kind}-traced function "
                            f"'{jf.name}' applies "
                            f"{node.func.id}() to a traced value "
                            "(ConcretizationTypeError under jit)")
                elif isinstance(node, (ast.If, ast.While)):
                    if _is_tracer_expr(node.test, params):
                        kw = ("if" if isinstance(node, ast.If)
                              else "while")
                        yield Finding(
                            "jit-tracer-branch", "error", src.rel,
                            node.lineno,
                            f"{jf.kind}-traced function "
                            f"'{jf.name}' branches with Python "
                            f"'{kw}' on a traced value; use lax.cond/"
                            "lax.while_loop or jnp.where (a static "
                            "operand here means one retrace per "
                            "distinct value -- the recompile-storm "
                            "pattern)")

    @staticmethod
    def _attr_chain(func: ast.expr) -> str:
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        return ".".join(reversed(parts)) or "?"

    def _check_static_argnums(self, src: SourceFile
                              ) -> Iterable[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit = _jit_kind(node.func) is not None or (
                _is_partial(node.func) and node.args
                and _jit_kind(node.args[0]) is not None)
            if not is_jit:
                continue
            for kw in node.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                bad = None
                if isinstance(kw.value, (ast.List, ast.Set,
                                         ast.Dict)):
                    bad = type(kw.value).__name__.lower()
                elif isinstance(kw.value, ast.Tuple):
                    ok = (int if kw.arg == "static_argnums" else str)
                    if any(not (isinstance(e, ast.Constant)
                                and isinstance(e.value, ok))
                           for e in kw.value.elts):
                        bad = "tuple with non-literal elements"
                if bad:
                    yield Finding(
                        "jit-static-argnums", "warning", src.rel,
                        kw.value.lineno,
                        f"{kw.arg} given a {bad}; jit's cache key "
                        "needs a hashable int/str or tuple of "
                        "literals")

    # --------------------------------------------------------- driver --
    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        # memoized on the SourceFile: the deep layer re-runs this scan
        # over every file just to dedup its transitive findings, and
        # one parse's findings never change within a run
        cached = getattr(src, "_trace_findings", None)
        if cached is None:
            cached = list(self._check_uncached(src))
            src._trace_findings = cached
        return cached

    def _check_uncached(self, src: SourceFile) -> Iterable[Finding]:
        for jf in self._jitted_functions(src):
            yield from self._check_body(src, jf)
        yield from self._check_static_argnums(src)
