"""Checker family 7: serving wire-protocol contracts (shardcheck).

The serving wire carries two out-of-band vocabularies as plain
strings: reserved blob keys (``__uri__``, ``__trace__``,
``__deadline__``, ...) and structured error-reply prefixes
(``deadline_exceeded:``, ``circuit_open:``). Both have exactly ONE
declaring module -- ``serving/protocol.py`` -- found structurally as
the module assigning ``WIRE_KEYS`` (a tuple of dunder strings) and
``ERROR_PREFIXES`` (the prefix -> HTTP-status dict), so fixture
projects work. A hand-typed copy anywhere else in ``serving/`` is
either a typo that fails only under load (a mistyped ``__deadlin__``
never expires anything) or vocabulary drift waiting to typo.

Rules (scoped to ``serving/``; docstrings and event-type arguments --
their own vocabulary, checked by the ``vocabulary`` family -- are
exempt):

``wire-key-literal`` (error)
    A dunder string literal outside the declaring module: a
    hand-typed copy of a reserved key (import the constant) or an
    unknown reserved-looking key (typo). Python's own dunders
    (``__main__`` etc.) are whitelisted.

``error-prefix-literal`` (error)
    A string literal outside the declaring module equal to a declared
    prefix or building a ``<prefix>: ...`` message inline -- the
    constant exists precisely so grep and the frontend agree.

``error-prefix-unknown`` (error)
    ``<expr>.startswith("<snake_case>")`` on a prefix-shaped literal
    (or a name resolving to one -- the dataflow layer follows one
    level of indirection) that no declaring module declares but that
    *near-matches* a declared prefix (close edit distance): a typo'd
    frontend mapping for a prefix no worker emits. The near-match
    gate keeps ordinary scheme sniffing
    (``backend.startswith("redis")``) out of scope.

``error-prefix-unmapped`` (warning)
    A declared ``*_PREFIX`` constant missing from ``ERROR_PREFIXES``
    (the frontend cannot map it to an HTTP status -- the failure
    class ships half-wired) or never referenced outside the declaring
    module (nobody emits it).

``protocol-vocab-module`` (error)
    Wire-key or error-prefix constants declared outside the declaring
    module: a second vocabulary home fragments the namespace exactly
    the way cross-module metric registration fragments families.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, SourceFile, register)
from analytics_zoo_tpu.analysis.dataflow import module_chain

_DUNDER_RE = re.compile(r"^__[a-z][a-z0-9_]*__$")
_PREFIX_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# python-idiom dunders that are not wire keys
_PY_DUNDERS = frozenset((
    "__main__", "__name__", "__init__", "__file__", "__doc__",
    "__all__", "__dict__", "__class__", "__module__", "__qualname__",
    "__version__", "__spec__", "__path__", "__slots__", "__len__",
    "__call__", "__enter__", "__exit__", "__getattr__", "__setattr__",
    "__delattr__", "__getitem__", "__setitem__", "__iter__",
    "__next__", "__repr__", "__str__", "__hash__", "__eq__",
    "__builtins__", "__loader__", "__package__", "__new__", "__del__",
))


def _top_level_assigns(src: SourceFile):
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        value = getattr(node, "value", None)
        for t in targets:
            if isinstance(t, ast.Name) and value is not None:
                yield t.id, value, node.lineno


def _dunder_tuple(value: ast.AST,
                  chain=None) -> Optional[List[str]]:
    """Tuple/list of dunder strings -- literal, or (with a module
    ``chain``) names resolving to dunder-string constants, the
    declaring module's own ``WIRE_KEYS = (URI_KEY, ...)`` idiom."""
    if not isinstance(value, (ast.Tuple, ast.List)):
        return None
    out = []
    for e in value.elts:
        v = None
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            v = e.value
        elif chain is not None and isinstance(e, ast.Name):
            resolved = chain.resolve_strings(e)
            if resolved and len(resolved) == 1:
                (candidate,) = resolved
                if isinstance(candidate, str):
                    v = candidate
        if v is None or not _DUNDER_RE.match(v):
            return None
        out.append(v)
    return out or None


def _near_prefix(candidate: str, declared) -> bool:
    """True when ``candidate`` plausibly MEANS one of the declared
    prefixes (close edit distance) -- the unknown-prefix rule targets
    typo'd mappings, not every snake-case startswith in serving/
    (scheme sniffing like ``backend.startswith("redis")`` must never
    fire)."""
    import difflib

    for known in declared:
        if difflib.SequenceMatcher(None, candidate,
                                   known).ratio() >= 0.75:
            return True
    return False


def _is_emit_arg0(node: ast.Constant, parents: Dict[int, ast.AST]
                  ) -> bool:
    parent = parents.get(id(node))
    if not isinstance(parent, ast.Call) or not parent.args:
        return False
    if parent.args[0] is not node:
        return False
    func = parent.func
    fname = (func.id if isinstance(func, ast.Name)
             else func.attr if isinstance(func, ast.Attribute) else "")
    return fname in ("emit", "emit_event")


@register
class ProtocolChecker(Checker):
    name = "protocol"
    rules = {
        "wire-key-literal": "hand-typed dunder wire-key literal in "
                            "serving/ outside the declaring module "
                            "(typo, or import the constant)",
        "error-prefix-literal": "structured error prefix built inline "
                                "instead of from the declaring "
                                "module's constant",
        "error-prefix-unknown": "startswith() on a string near-"
                                "matching a declared error prefix "
                                "that no module declares (typo'd "
                                "mapping for a prefix nobody emits)",
        "error-prefix-unmapped": "declared error prefix missing from "
                                 "ERROR_PREFIXES (no HTTP mapping) or "
                                 "never referenced outside its "
                                 "declaring module (never emitted)",
        "protocol-vocab-module": "wire-key/error-prefix constants "
                                 "declared outside the one declaring "
                                 "module",
    }

    def __init__(self, restrict_dirs: Optional[Tuple[str, ...]]
                 = ("serving",)):
        self._restrict = restrict_dirs

    def _in_scope(self, src: SourceFile) -> bool:
        if self._restrict is None:
            return True
        parts = src.rel.split("/")
        return any(d in parts[:-1] for d in self._restrict)

    # ------------------------------------------------------ discovery --
    @staticmethod
    def _find_homes(files) -> Tuple[Optional[SourceFile],
                                    Optional[SourceFile]]:
        wire_home = prefix_home = None
        for src in files:
            chain = module_chain(src.tree)
            for name, value, _line in _top_level_assigns(src):
                if (name in ("WIRE_KEYS", "_META_KEYS")
                        and _dunder_tuple(value, chain)
                        and wire_home is None):
                    wire_home = src
                if (name == "ERROR_PREFIXES"
                        and isinstance(value, ast.Dict)
                        and prefix_home is None):
                    prefix_home = src
        return wire_home, prefix_home

    @staticmethod
    def _declared_keys(src: SourceFile) -> Set[str]:
        keys: Set[str] = set()
        chain = module_chain(src.tree)
        for name, value, _line in _top_level_assigns(src):
            tup = _dunder_tuple(value, chain)
            if name in ("WIRE_KEYS", "_META_KEYS") and tup:
                keys.update(tup)
            elif (name.endswith("_KEY")
                  and isinstance(value, ast.Constant)
                  and isinstance(value.value, str)
                  and _DUNDER_RE.match(value.value)):
                keys.add(value.value)
        return keys

    @staticmethod
    def _declared_prefixes(src: SourceFile
                           ) -> Tuple[Dict[str, str], Set[str]]:
        """({prefix value: constant name}, mapped prefix values) from
        the declaring module's ``*_PREFIX`` constants and the
        ``ERROR_PREFIXES`` dict (keys resolved through module-level
        constants)."""
        chain = module_chain(src.tree)
        consts: Dict[str, str] = {}
        mapped: Set[str] = set()
        for name, value, _line in _top_level_assigns(src):
            if (name.endswith("_PREFIX")
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                    and _PREFIX_RE.match(value.value)):
                consts[value.value] = name
            elif name == "ERROR_PREFIXES" and isinstance(value,
                                                         ast.Dict):
                for k in value.keys:
                    if k is None:
                        continue
                    resolved = chain.resolve_strings(k)
                    if resolved:
                        mapped.update(v for v in resolved
                                      if isinstance(v, str))
        return consts, mapped

    # ---------------------------------------------------------- check --
    def check_project(self, project: Project) -> Iterable[Finding]:
        scoped = [s for s in project.files if self._in_scope(s)]
        if not scoped:
            return
        wire_home, prefix_home = self._find_homes(scoped)
        wire_keys = (self._declared_keys(wire_home)
                     if wire_home else set())
        prefix_consts, mapped = (
            self._declared_prefixes(prefix_home)
            if prefix_home else ({}, set()))

        # -- declaration-side contract checks ------------------------ --
        if prefix_home is not None:
            for value, cname in sorted(prefix_consts.items()):
                if value not in mapped:
                    yield Finding(
                        "error-prefix-unmapped", "warning",
                        prefix_home.rel, 0,
                        f"error prefix {cname} ('{value}') is not a "
                        "key of ERROR_PREFIXES: the frontend cannot "
                        "map it to an HTTP status")

        # -- use-site scans ------------------------------------------ --
        prefix_refs: Set[str] = set()  # constant names referenced
        for src in scoped:
            is_wire_home = src is wire_home
            is_prefix_home = src is prefix_home
            parents: Dict[int, ast.AST] = {}
            for node in ast.walk(src.tree):
                for child in ast.iter_child_nodes(node):
                    parents[id(child)] = node
            chain = module_chain(src.tree)
            for node in ast.walk(src.tree):
                if (isinstance(node, (ast.Name, ast.Attribute))
                        and not is_prefix_home):
                    ref = (node.id if isinstance(node, ast.Name)
                           else node.attr)
                    if ref in prefix_consts.values():
                        prefix_refs.add(ref)
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str):
                    if src.is_docstring(node) or _is_emit_arg0(
                            node, parents):
                        continue
                    yield from self._check_literal(
                        src, node, is_wire_home, is_prefix_home,
                        wire_home, wire_keys, prefix_consts)
                elif isinstance(node, ast.Call):
                    yield from self._check_startswith(
                        src, node, chain, is_prefix_home,
                        prefix_consts)
            if not (is_wire_home and is_prefix_home):
                yield from self._check_vocab_module(
                    src, chain, is_wire_home, is_prefix_home,
                    wire_home, prefix_home)

        for value, cname in sorted(prefix_consts.items()):
            if prefix_home is not None and cname not in prefix_refs:
                yield Finding(
                    "error-prefix-unmapped", "warning",
                    prefix_home.rel, 0,
                    f"error prefix {cname} ('{value}') is declared "
                    "but never referenced outside its declaring "
                    "module: nobody emits or maps it")

    def _check_literal(self, src: SourceFile, node: ast.Constant,
                       is_wire_home: bool, is_prefix_home: bool,
                       wire_home, wire_keys: Set[str],
                       prefix_consts: Dict[str, str]
                       ) -> Iterable[Finding]:
        value = node.value
        if (_DUNDER_RE.match(value) and value not in _PY_DUNDERS
                and not is_wire_home and wire_keys):
            if value in wire_keys:
                yield Finding(
                    "wire-key-literal", "error", src.rel, node.lineno,
                    f"hand-typed copy of reserved wire key '{value}'; "
                    f"import the constant from {wire_home.rel}")
            else:
                near = ", ".join(sorted(wire_keys))
                yield Finding(
                    "wire-key-literal", "error", src.rel, node.lineno,
                    f"'{value}' looks like a reserved wire key but "
                    f"none is declared with that name (typo? known: "
                    f"{near})")
            return
        if is_prefix_home or not prefix_consts:
            return
        for pvalue, cname in prefix_consts.items():
            if value == pvalue or value.startswith(pvalue + ":") \
                    or value.startswith(pvalue + " "):
                yield Finding(
                    "error-prefix-literal", "error", src.rel,
                    node.lineno,
                    f"error prefix '{pvalue}' built inline; use the "
                    f"{cname} constant so the frontend mapping and "
                    "grep stay in sync")
                return

    def _check_startswith(self, src: SourceFile, node: ast.Call,
                          chain, is_prefix_home: bool,
                          prefix_consts: Dict[str, str]
                          ) -> Iterable[Finding]:
        if is_prefix_home or not prefix_consts:
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr == "startswith" and node.args):
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant):
            # a literal DECLARED prefix is _check_literal's finding;
            # a literal prefix-shaped typo is still unknown-prefix
            values = (frozenset([arg.value])
                      if isinstance(arg.value, str) else None)
        else:
            values = chain.resolve_strings(arg)
        if not values:
            return
        for v in sorted(v for v in values if isinstance(v, str)):
            base = v[:-1] if v.endswith(":") else v
            if (_PREFIX_RE.match(base) and base not in prefix_consts
                    and _near_prefix(base, prefix_consts)):
                yield Finding(
                    "error-prefix-unknown", "error", src.rel,
                    node.lineno,
                    f"startswith() maps error prefix '{base}' but no "
                    "declaring module declares it (known: "
                    f"{', '.join(sorted(prefix_consts))}) -- a typo "
                    "here silently downgrades structured errors")

    def _check_vocab_module(self, src: SourceFile, chain,
                            is_wire_home: bool,
                            is_prefix_home: bool, wire_home,
                            prefix_home) -> Iterable[Finding]:
        for name, value, line in _top_level_assigns(src):
            dunder_const = (isinstance(value, ast.Constant)
                            and isinstance(value.value, str)
                            and _DUNDER_RE.match(value.value)
                            and value.value not in _PY_DUNDERS)
            if (not is_wire_home and wire_home is not None
                    and (name in ("WIRE_KEYS", "_META_KEYS")
                         and _dunder_tuple(value, chain)
                         or name.endswith("_KEY") and dunder_const)):
                yield Finding(
                    "protocol-vocab-module", "error", src.rel, line,
                    f"wire-key constant '{name}' declared outside "
                    f"the declaring module ({wire_home.rel}); one "
                    "vocabulary home only")
            elif (not is_prefix_home and prefix_home is not None
                  and (name == "ERROR_PREFIXES"
                       and isinstance(value, ast.Dict)
                       or name.endswith("_PREFIX")
                       and isinstance(value, ast.Constant)
                       and isinstance(value.value, str)
                       and _PREFIX_RE.match(value.value))):
                yield Finding(
                    "protocol-vocab-module", "error", src.rel, line,
                    f"error-prefix constant '{name}' declared outside "
                    f"the declaring module ({prefix_home.rel}); one "
                    "vocabulary home only")
