"""zoolint: repo-native static analysis for TPU-serving hygiene.

The runtime observability stack (obs.flight's recompile-storm
detector, the serving worker's crash events) catches shape churn and
thread death *after* they ship; this package is the static twin --
an AST-level checker framework that catches the same bug classes at
review time:

- ``trace_hazards``  jit/pmap/shard_map retrace + concretization
                     hazards (the lint form of obs.events'
                     RecompileDetector)
- ``concurrency``    lock-guard inference, lock-ordering, and
                     thread-join lints for the threaded serving/obs
                     layers
- ``config_keys``    ``zoo.*`` config-key drift between use sites,
                     ``common.config._DEFAULTS``, and the docs
                     glossary (resolves helper-wrapper/prefix access
                     that naive grep misses), plus ``config-type``
                     cast/default checks against the ``_SPECS``
                     type/range metadata
- ``vocabulary``     metric-name and event-type conventions (one
                     registry with obs.metrics / obs.events)
- ``hygiene``        silent ``except Exception: pass`` blocks
- ``mesh_rules``     mesh/collective correctness: axis-name
                     resolution against the ``zoo.mesh.axis.*``
                     vocabulary, shard_map in_specs arity,
                     unsharded-axis reductions, nested collectives
                     (dataflow-powered: one level of variable
                     indirection resolves)
- ``protocol``       serving wire-protocol contracts: reserved wire
                     keys and structured error prefixes have ONE
                     declaring module (serving/protocol.py); inline
                     copies, typos, and unmapped prefixes are
                     findings
- ``dataflow``       the shared reaching-definitions +
                     constant-propagation layer the above build on
- ``callgraph``      deepcheck's project-wide call graph: resolved
                     self.method / module-fn / intra-package-import /
                     one-alias-level edges, with jit / collective /
                     serving-hot-path context propagation and
                     per-parameter tracer/device taint
- ``deep_rules``     the interprocedural families on top of it:
                     transitive trace hazards (jit-numpy-call &c. one
                     call deep, jit-host-callback-undeclared),
                     hot-path host syncs (hotpath-block-on-device),
                     and dtype drift (dtype-upcast-f32,
                     dtype-mixed-collective)
- ``cfg``            engine #4's substrate: per-function control-flow
                     graphs with exception edges, duplicated
                     finally/with unwinds, and bounded path
                     enumeration
- ``lifecycle_rules``path-sensitive resource-lifecycle + exactly-
                     once-reply checks over the CFG (leak-on-path,
                     double-release, release-unacquired,
                     cleanup-not-in-finally, reply-missing-on-path,
                     reply-duplicated-on-path) -- the static twin of
                     the serving ledger, with one interprocedural
                     level of acquire/release through helpers

Entry points: ``scripts/zoolint.py`` (CLI, baseline-aware, ``--json``
/ ``--format sarif`` / ``--profile``)
and ``tests/test_zoolint.py`` (tier-1 gate). Findings suppress inline
with ``# zoolint: disable=<rule>`` on the offending or preceding line;
grandfathered findings live in ``zoolint_baseline.json`` with a
rationale each. See docs/zoolint.md for the rule catalog.
"""

from analytics_zoo_tpu.analysis.core import (  # noqa: F401
    Checker,
    Finding,
    Project,
    SourceFile,
    all_checkers,
    all_rules,
    register,
    run_zoolint,
)
from analytics_zoo_tpu.analysis.baseline import (  # noqa: F401
    load_baseline,
    new_findings,
    write_baseline,
)
from analytics_zoo_tpu.analysis.cfg import (  # noqa: F401
    CFG,
    build_cfg,
    iter_paths,
)
from analytics_zoo_tpu.analysis.lifecycle_rules import (  # noqa: F401
    LifecycleChecker,
    ResourceSpec,
)

__all__ = [
    "CFG",
    "Checker",
    "Finding",
    "LifecycleChecker",
    "Project",
    "ResourceSpec",
    "SourceFile",
    "all_checkers",
    "all_rules",
    "build_cfg",
    "iter_paths",
    "load_baseline",
    "new_findings",
    "register",
    "run_zoolint",
    "write_baseline",
]
