"""deepcheck layer 2: interprocedural rule families over the call graph.

Three families, all riding :mod:`analytics_zoo_tpu.analysis.callgraph`'s
context propagation. Everything here is conservative by construction:
an unresolved call, an unknown value, an untainted parameter is never a
finding.

**Transitive trace hazards.** The PR-4 rules (``jit-numpy-call`` /
``jit-concretize`` / ``jit-tracer-branch``) re-run inside every
function that *inherits* jit/collective context through the graph, with
the tracer-ness walk seeded by the propagated per-parameter taint -- a
helper extracted out of a jitted step keeps its guardrails. Findings
PR 4 already reports (directly jitted functions) are deduplicated, so
each hazard fires exactly once. ``jit-host-callback-undeclared`` flags
``pure_callback`` / ``io_callback`` / ``host_callback.call`` /
``py_func``-style trace escapes reached from jit context: each one is a
host round-trip per dispatch, fine only when somebody wrote down why
(suppress inline with the reason).

**Hot-path host syncs.** ``hotpath-block-on-device`` fires on
``.block_until_ready()`` / ``jax.device_get`` anywhere in propagated
serving-hot-path context, and on ``.item()`` / ``float()`` / ``int()``
/ ``np.asarray`` / ``np.array`` whose operand is *proven*
device-derived. The decode->dispatch stages exist to overlap host work
with device compute (docs/serving.md); one synchronous materialization
there stalls the whole pipeline for a device round-trip -- the recurring
TPU-serving-throughput lesson. The finalize seam is exempt (that stage
exists to absorb the sync), as is anything in jit context (a host sync
inside a trace is a *trace* hazard, reported by the jit family).

**Version-fragile collective API.** The repo runs on two jax lines
(the 0.4.x rigs and >=0.5 drivers); ``jax.shard_map`` and
``lax.axis_size`` exist only on the newer one, so a direct use is a
crash half the fleet never sees until dispatch. ``shard-map-direct``
flags any ``jax.shard_map`` use outside the one compat wrapper
(``parallel/mesh.py``). ``collective-version-api`` flags
``lax.axis_size`` in **propagated collective context** -- the
interprocedural part: the pipeline/ring-attention local bodies are
plain module functions whose collective-ness is only provable by
resolving ``shard_map(partial(body, ...), ...)`` through the call
graph. Dogfooding this pair on the pre-deepcheck tree found 10 real
crashes-in-waiting (7 direct ``jax.shard_map`` uses, 3
``lax.axis_size`` bodies) -- see docs/zoolint.md.

**Dtype drift.** ``dtype-upcast-f32`` flags an argument with a
provable float32/float64 dtype flowing into a parameter whose
default/annotation declares bf16/f16 at a resolved call edge -- the
static twin of the r4 ResNet-50 profile where f32 batch-norm constants
upcast bf16 activations into convert+reduce fusions worth 31% of step
time (BENCH_NOTES.md). ``dtype-mixed-collective`` flags a collective
whose operand expression mixes two provable float dtypes: the operand
is silently computed (and shipped cross-chip) at the wider one.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.callgraph import (
    CTX_COLLECTIVE, CTX_HOTPATH, CTX_JIT, FnNode, build_call_graph,
    is_device_expr, own_nodes)
from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, register)
from analytics_zoo_tpu.analysis.mesh_rules import _COLLECTIVES
from analytics_zoo_tpu.analysis.trace_hazards import (
    TraceHazardChecker, _is_tracer_expr, _np_root)

# py_func-style trace escapes: each is a host callback per dispatch
_HOST_CALLBACKS = {"pure_callback", "io_callback", "py_func"}
_HOST_CALLBACK_MODULES = {"host_callback", "hcb"}

# host-numpy functions that only read array METADATA -- safe on a
# tracer (shape/dtype are concrete at trace time), so they are never
# a jit-numpy-call finding
_NP_METADATA = {"ndim", "shape", "size", "result_type", "dtype",
                "isscalar", "iterable"}

_F32_TOKENS = {"float32", "float64"}
_BF16_TOKENS = {"bfloat16", "float16"}
_DTYPE_TOKENS = _F32_TOKENS | _BF16_TOKENS
_ARRAY_CTORS = {"array", "asarray", "zeros", "ones", "full", "empty",
                "arange", "linspace", "eye", "full_like", "zeros_like",
                "ones_like"}
_FLOAT_MODULES = {"np", "numpy", "onp", "jnp"}


def _call_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _chain_root(func: ast.expr) -> Optional[str]:
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


# --------------------------------------------------------------------- #
# literal dtype inference (one level of Name indirection via Scope)      #
# --------------------------------------------------------------------- #
def dtype_token(expr: ast.AST, fn: Optional[FnNode] = None,
                _depth: int = 0) -> Optional[str]:
    """The provable dtype of an expression, as a canonical token
    ("float32", "bfloat16", ...), or None when unknown. Plain python
    float literals are weakly typed under jax and never claim."""
    if _depth > 2:
        return None
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, str) and expr.value in _DTYPE_TOKENS:
            return expr.value
        return None
    if isinstance(expr, ast.Attribute):
        # np.float32 / jnp.bfloat16 as a dtype object
        if (expr.attr in _DTYPE_TOKENS
                and _chain_root(expr) in _FLOAT_MODULES):
            return expr.attr
        return None
    if isinstance(expr, ast.Name):
        if fn is None:
            return None
        for scope in (fn.scope(),):
            if expr.id in scope.tainted:
                return None
            assigns = scope.assigns.get(expr.id, [])
            if len(assigns) == 1:
                return dtype_token(assigns[0], fn, _depth + 1)
        return None
    if isinstance(expr, ast.Call):
        name = _call_name(expr.func)
        root = _chain_root(expr.func)
        if name in _DTYPE_TOKENS and root in _FLOAT_MODULES:
            return name  # np.float32(1.0) / jnp.bfloat16(x)
        if name == "astype" and isinstance(expr.func, ast.Attribute):
            if expr.args:
                return dtype_token(expr.args[0], fn, _depth + 1)
            return None
        if name in _ARRAY_CTORS and root in _FLOAT_MODULES:
            for kw in expr.keywords:
                if kw.arg == "dtype":
                    return dtype_token(kw.value, fn, _depth + 1)
            if len(expr.args) >= 2:
                return dtype_token(expr.args[1], fn, _depth + 1)
            return None
    return None


def _is_dtype_selector(expr: ast.AST) -> bool:
    """A bare dtype OBJECT (``jnp.bfloat16``, ``"float32"``) rather
    than a value carrying that dtype: a selector parameter/argument.
    An explicit ``dtype=np.float32`` is the caller *choosing* f32 --
    the opposite of the silent-upcast pattern the rule hunts."""
    if isinstance(expr, ast.Attribute):
        return (expr.attr in _DTYPE_TOKENS
                and _chain_root(expr) in _FLOAT_MODULES)
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, str) and expr.value in \
            _DTYPE_TOKENS
    return False


def _param_decl_dtypes(fn: FnNode) -> Dict[str, str]:
    """Declared dtypes of parameters: a VALUE default or annotation
    with a provable dtype token (``eps=jnp.bfloat16(1e-3)``,
    ``x: jnp.bfloat16``). A bare dtype-object default
    (``dtype=jnp.bfloat16``) declares a selector parameter, not a
    bf16 value, and is excluded."""
    args = getattr(fn.node, "args", None)
    if args is None:
        return {}
    out: Dict[str, str] = {}
    pos = list(args.posonlyargs) + list(args.args)
    defaults = list(args.defaults)
    for a, d in zip(pos[len(pos) - len(defaults):], defaults):
        tok = dtype_token(d, fn)
        if tok is not None and not _is_dtype_selector(d):
            out[a.arg] = tok
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            tok = dtype_token(d, fn)
            if tok is not None and not _is_dtype_selector(d):
                out[a.arg] = tok
    for a in pos + list(args.kwonlyargs):
        if a.annotation is not None:
            tok = dtype_token(a.annotation, fn)
            if tok is not None:
                out.setdefault(a.arg, tok)
    return out


def _augmented_tracer_names(fn: FnNode, params: Set[str]) -> Set[str]:
    """Tainted params plus locals provably derived from them: a name
    whose every simple assignment is a tracer expression w.r.t. the
    growing set (``l = jnp.sum(x)`` with ``x`` traced taints ``l``).
    Tainted-any-other-way names (unpacking, loop targets) stay out --
    conservative, like everything here."""
    scope = fn.scope()
    names = set(params)
    changed = True
    while changed:
        changed = False
        for name, exprs in scope.assigns.items():
            if name in names or name in scope.tainted:
                continue
            if exprs and all(_is_tracer_expr(e, names) for e in exprs):
                names.add(name)
                changed = True
    return names


def _short(qname: str) -> str:
    """'pkg/mod.py::Class.fn' -> 'Class.fn' (messages stay symbolic
    and path-independent; the finding's own path column has the file)."""
    return qname.split("::", 1)[-1]


@register
class DeepChecker(Checker):
    """deepcheck: the interprocedural families (docs/zoolint.md)."""

    name = "deep"
    rules = {
        "jit-numpy-call": "host numpy call on a traced value inside a "
                          "jitted function (use jnp/lax)",
        "jit-concretize": ".item()/float()/int()/bool() on a traced "
                          "value inside a jitted function",
        "jit-tracer-branch": "Python if/while on a traced value inside "
                             "a jitted function (retrace or trace "
                             "error; use lax.cond/jnp.where)",
        "jit-host-callback-undeclared": "pure_callback/io_callback/"
                                        "host_callback/py_func escape "
                                        "reached from jit context -- a "
                                        "host round-trip per dispatch; "
                                        "suppress inline with the "
                                        "reason if intentional",
        "hotpath-block-on-device": "host sync (.item()/float()/"
                                   "np.asarray/device_get/"
                                   ".block_until_ready) on a device "
                                   "value reached from a serving "
                                   "pipeline stage outside the "
                                   "finalize seam (stalls the decode/"
                                   "dispatch overlap)",
        "shard-map-direct": "direct jax.shard_map use outside the "
                            "parallel/mesh.py compat wrapper (absent "
                            "on jax 0.4.x: crashes at dispatch; use "
                            "parallel.mesh.shard_map)",
        "collective-version-api": "lax.axis_size in propagated "
                                  "collective context (jax>=0.5-only; "
                                  "use parallel.collectives.axis_size "
                                  "-- psum(1, axis) on 0.4.x)",
        "dtype-upcast-f32": "f32/f64 value flowing into a parameter "
                            "declared/defaulted bf16 or f16 (the "
                            "convert-fusion upcast pattern behind the "
                            "r4 BN profile)",
        "dtype-mixed-collective": "collective operand mixes two "
                                  "provable float dtypes (computed "
                                  "and shipped at the wider one)",
    }

    # ------------------------------------------------------- driver --
    def check_project(self, project: Project) -> Iterable[Finding]:
        graph = build_call_graph(project)
        # (rel, rule, line) PR 4 already reports: dedup so a directly
        # jitted function's hazards fire exactly once, from one family
        base = TraceHazardChecker()
        seen: Set[Tuple[str, str, int]] = set()
        for src in project.files:
            for f in base.check_file(src):
                seen.add((f.path, f.rule, f.line))
        for fn in graph.nodes:
            yield from self._check_trace(fn, seen)
            yield from self._check_host_callbacks(fn)
            yield from self._check_hotpath(fn)
            yield from self._check_dtype_edges(fn)
            yield from self._check_version_api(fn)
        for fn in graph.nodes:
            yield from self._check_mixed_collectives(fn)
        for src in project.files:
            yield from self._check_shard_map_direct(src)

    # ------------------------------------- transitive trace hazards --
    def _check_trace(self, fn: FnNode,
                     seen: Set[Tuple[str, str, int]]
                     ) -> Iterable[Finding]:
        if fn.jit_direct:
            return  # PR 4's per-file scan owns directly jitted bodies
        if not ({CTX_JIT, CTX_COLLECTIVE} & fn.contexts):
            return
        params = fn.effective_tracer_params()
        if not params:
            return
        params = _augmented_tracer_names(fn, params)
        root, caller = fn.via.get(
            CTX_JIT, fn.via.get(CTX_COLLECTIVE, (fn.qname, fn.qname)))
        reach = (f"'{fn.name}' (reached from jit-traced "
                 f"'{_short(root)}' via '{_short(caller)}')")
        for node in own_nodes(fn):
                if isinstance(node, ast.Call):
                    key = (fn.src.rel, "jit-numpy-call", node.lineno)
                    np_mod = _np_root(node.func)
                    if _call_name(node.func) in _NP_METADATA:
                        np_mod = None  # shape/dtype probes are static
                    if (np_mod is not None and key not in seen
                            and any(_is_tracer_expr(a, params)
                                    for a in list(node.args)
                                    + [kw.value
                                       for kw in node.keywords])):
                        seen.add(key)
                        yield Finding(
                            "jit-numpy-call", "error", fn.src.rel,
                            node.lineno,
                            f"helper {reach} calls host numpy "
                            f"({np_mod}.{_call_name(node.func)}) on a "
                            "transitively traced value; use jnp/lax")
                        continue
                    key = (fn.src.rel, "jit-concretize", node.lineno)
                    if (isinstance(node.func, ast.Attribute)
                            and node.func.attr == "item"
                            and not node.args and key not in seen
                            and _is_tracer_expr(node.func.value,
                                                params)):
                        seen.add(key)
                        yield Finding(
                            "jit-concretize", "error", fn.src.rel,
                            node.lineno,
                            f"helper {reach} calls .item() on a "
                            "transitively traced value (host sync "
                            "inside the trace)")
                        continue
                    if (isinstance(node.func, ast.Name)
                            and node.func.id in ("float", "int",
                                                 "bool")
                            and len(node.args) == 1
                            and key not in seen
                            and _is_tracer_expr(node.args[0], params)):
                        seen.add(key)
                        yield Finding(
                            "jit-concretize", "error", fn.src.rel,
                            node.lineno,
                            f"helper {reach} applies "
                            f"{node.func.id}() to a transitively "
                            "traced value (ConcretizationTypeError "
                            "under jit)")
                elif isinstance(node, (ast.If, ast.While)):
                    key = (fn.src.rel, "jit-tracer-branch",
                           node.lineno)
                    if (key not in seen
                            and _is_tracer_expr(node.test, params)):
                        seen.add(key)
                        kw = "if" if isinstance(node, ast.If) else \
                            "while"
                        yield Finding(
                            "jit-tracer-branch", "error", fn.src.rel,
                            node.lineno,
                            f"helper {reach} branches with Python "
                            f"'{kw}' on a transitively traced value; "
                            "use lax.cond/lax.while_loop or "
                            "jnp.where")

    def _check_host_callbacks(self, fn: FnNode) -> Iterable[Finding]:
        if not ({CTX_JIT, CTX_COLLECTIVE} & fn.contexts):
            return
        root = _short(fn.root_of(CTX_JIT if CTX_JIT in fn.contexts
                                 else CTX_COLLECTIVE))
        for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node.func)
                is_cb = name in _HOST_CALLBACKS or (
                    name == "call"
                    and isinstance(node.func, ast.Attribute)
                    and _chain_root(node.func)
                    in _HOST_CALLBACK_MODULES)
                if is_cb:
                    yield Finding(
                        "jit-host-callback-undeclared", "warning",
                        fn.src.rel, node.lineno,
                        f"'{fn.name}' (jit context from "
                        f"'{root}') escapes the trace through "
                        f"{name}; each dispatch pays a host "
                        "round-trip -- suppress inline with the "
                        "reason if intentional")

    # ------------------------------------------- hot-path host syncs --
    def _check_hotpath(self, fn: FnNode) -> Iterable[Finding]:
        if CTX_HOTPATH not in fn.contexts:
            return
        if {CTX_JIT, CTX_COLLECTIVE} & fn.contexts or fn.jit_direct:
            return  # inside a trace a sync is a trace hazard instead
        root, caller = fn.via.get(CTX_HOTPATH, (fn.qname, fn.qname))
        reach = (f"'{fn.name}' (hot path from '{_short(root)}'"
                 + ("" if caller == fn.qname
                    else f" via '{_short(caller)}'") + ")")
        for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sync_message(node, fn)
                if msg is not None:
                    yield Finding(
                        "hotpath-block-on-device", "warning",
                        fn.src.rel, node.lineno,
                        f"serving stage helper {reach} {msg}; the "
                        "decode/dispatch stages must stay "
                        "non-blocking -- move the materialization to "
                        "the finalize seam (or suppress with the "
                        "reason)")

    @staticmethod
    def _sync_message(node: ast.Call, fn: FnNode) -> Optional[str]:
        func = node.func
        name = _call_name(func)
        if name == "block_until_ready":
            return "blocks on .block_until_ready()"
        if name == "device_get":
            return "synchronously fetches with jax.device_get"
        if (name == "item" and isinstance(func, ast.Attribute)
                and not node.args
                and is_device_expr(func.value, fn)):
            return ".item()s a device value (one host round-trip)"
        if (name in ("asarray", "array")
                and _chain_root(func) in ("np", "numpy", "onp")
                and node.args and is_device_expr(node.args[0], fn)):
            return (f"materializes a device value with np.{name} "
                    "(synchronous d2h copy)")
        if (isinstance(func, ast.Name) and func.id in ("float", "int")
                and len(node.args) == 1
                and is_device_expr(node.args[0], fn)):
            return (f"concretizes a device value with {func.id}() "
                    "(one host round-trip)")
        return None

    # -------------------------------- version-fragile collective API --
    def _check_version_api(self, fn: FnNode) -> Iterable[Finding]:
        if CTX_COLLECTIVE not in fn.contexts:
            return  # axis_size outside a mapped body is its own error
        caller = fn.via.get(CTX_COLLECTIVE, (fn.qname, fn.qname))[1]
        for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr == "axis_size"
                        and _chain_root(func) in ("lax", "jax")):
                    yield Finding(
                        "collective-version-api", "error", fn.src.rel,
                        node.lineno,
                        f"'{fn.name}' (collective body, traced via "
                        f"'{_short(caller)}') calls lax.axis_size -- "
                        "jax>=0.5-only, crashes the 0.4.x rigs at "
                        "dispatch; use parallel.collectives.axis_size "
                        "(psum(1, axis) there)")

    def _check_shard_map_direct(self, src) -> Iterable[Finding]:
        if src.rel.endswith("parallel/mesh.py"):
            return  # the one compat wrapper, by contract
        seen_lines: Set[int] = set()
        for node in ast.walk(src.tree):
            hit = None
            if (isinstance(node, ast.ImportFrom) and node.module
                    and node.module.startswith("jax")
                    and any(a.name == "shard_map"
                            for a in node.names)):
                hit = f"imports shard_map from {node.module}"
            elif (isinstance(node, ast.Attribute)
                    and node.attr == "shard_map"
                    and _chain_root(node) == "jax"):
                hit = "uses jax.shard_map directly"
            if hit is not None and node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                yield Finding(
                    "shard-map-direct", "error", src.rel, node.lineno,
                    f"{hit}: absent on jax 0.4.x (and renamed across "
                    "lines) -- route through parallel.mesh.shard_map, "
                    "the one version-compat wrapper")

    # ------------------------------------------------- dtype drift --
    def _check_dtype_edges(self, fn: FnNode) -> Iterable[Finding]:
        for edge in fn.edges_out:
            decl = _param_decl_dtypes(edge.callee)
            if not decl:
                continue
            for pname, aexpr in edge.bindings:
                want = decl.get(pname)
                if want not in _BF16_TOKENS:
                    continue
                if _is_dtype_selector(aexpr):
                    continue  # explicit dtype= choice, not a leak
                got = dtype_token(aexpr, fn)
                if got in _F32_TOKENS:
                    yield Finding(
                        "dtype-upcast-f32", "warning", fn.src.rel,
                        aexpr.lineno,
                        f"'{fn.name}' passes a {got} value to "
                        f"'{edge.callee.name}' parameter "
                        f"'{pname}' declared {want}; the math runs "
                        f"(and buffers convert) at {got} -- the BN "
                        "convert-fusion upcast pattern")

    def _check_mixed_collectives(self, fn: FnNode) -> Iterable[Finding]:
        for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = _call_name(node.func)
                if cname not in _COLLECTIVES or not node.args:
                    continue
                toks: Set[str] = set()
                for sub in ast.walk(node.args[0]):
                    tok = dtype_token(sub, fn)
                    if tok is not None:
                        toks.add(tok)
                floats = toks & _DTYPE_TOKENS
                if len(floats) >= 2:
                    yield Finding(
                        "dtype-mixed-collective", "warning",
                        fn.src.rel, node.lineno,
                        f"collective '{cname}' in '{fn.name}' mixes "
                        f"operand dtypes {sorted(floats)}; the "
                        "reduction computes (and the wire carries) "
                        "the widest one -- cast to one dtype first")
