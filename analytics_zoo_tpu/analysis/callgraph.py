"""deepcheck layer 1: a project-wide call graph with context propagation.

zoolint's PR-4 rule families and the PR-6 dataflow layer are strictly
*intraprocedural*: a ``.item()`` inside a jitted function fires, the
same ``.item()`` one helper-call deep is invisible. Every XLA-shaped
property this repo cares about crosses function boundaries -- whether a
helper reached from a jitted function concretizes a tracer, whether the
decode->dispatch->finalize serving hot path blocks on a host sync,
whether an f32 constant flows into a bf16 kernel -- so this module
builds the missing piece: a call graph over the one-parse
:class:`~analytics_zoo_tpu.analysis.core.Project`, with **contexts**
propagated along its edges.

Resolution (all same-parse, no imports executed). A call site resolves
when its callee is

- a function/method defined in an enclosing lexical scope or at module
  level of the same file (``helper(x)``);
- ``self.method(...)`` / ``cls.method(...)`` on the enclosing class
  (single definition; ambiguous names never resolve);
- ``mod.fn(...)`` where ``mod`` is an intra-package import of a scanned
  module (``from analytics_zoo_tpu.serving import worker`` /
  ``import ... as w`` / relative forms), or a symbol imported from one
  (``from .queues import _encode``);
- one level of **alias indirection** through the
  :mod:`~analytics_zoo_tpu.analysis.dataflow` scope machinery:
  ``f = helper`` / ``f = jax.jit(helper)`` / ``self._step =
  jax.jit(step)`` followed by ``f(...)`` / ``self._step(...)``
  (jit/pmap/shard_map/partial wrappers are unwrapped).

Anything else -- dict dispatch, ``*args`` forwarding, attribute calls on
arbitrary objects, names assigned more than once -- is **conservatively
unknown and never produces a finding**.

Contexts propagated caller -> callee along resolved edges:

``jit`` / ``collective``
    Roots are the PR-4 jitted-function detection
    (:func:`~analytics_zoo_tpu.analysis.trace_hazards.jitted_functions`;
    ``shard_map`` roots also carry ``collective``). Alongside the
    context, per-parameter *tracer taint* flows: a callee parameter is
    traced iff some resolved jit-context call site passes it a
    tracer-derived argument.

``hotpath``
    The serving hot path. Roots are the worker pipeline stages
    (methods of ``ServingWorker`` in the decode/dispatch seams) and
    ``InferenceModel.predict_async``; a module may declare extra roots
    with ``ZOOLINT_HOT_PATH = ("fn", "Class.method", ...)``. The
    finalize seam (``_finalize_*`` / ``finalize_loop``) is a *barrier*:
    hotpath context never enters it -- materializing results there is
    the engine's one sanctioned host sync. Per-parameter *device taint*
    flows along hotpath edges (arguments proven device-derived:
    ``predict_async`` results, jit-wrapped call results, ``jnp`` ops,
    ``device_put``).

Nested defs inherit their enclosing function's contexts (the enclosing
body can call them through trampolines the resolver cannot see), and
their tracer walk sees enclosing traced parameters as free variables.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from analytics_zoo_tpu.analysis.core import Project, SourceFile
from analytics_zoo_tpu.analysis.dataflow import Scope
from analytics_zoo_tpu.analysis.trace_hazards import (
    _STATIC_ATTRS, _is_tracer_expr, _static_params, jitted_functions)

CTX_JIT = "jit"
CTX_COLLECTIVE = "collective"
CTX_HOTPATH = "hotpath"

# structural hot-path roots: the serving worker's decode/dispatch
# stages (the threads that must never stall on device results) and the
# inference engine's async dispatch entry
_HOT_STAGE_METHODS = {
    "ServingWorker": {"process_one_batch", "_decode_stage",
                      "_dispatch_group", "_predict_group",
                      "_run_pipelined"},
    "InferenceModel": {"predict_async"},
}
# the finalize seam: materializing device results here is the design
# (the pipelined engine's third stage exists to absorb that sync)
_FINALIZE_SEAM = {"_finalize_one", "_finalize_record",
                  "_finalize_inner", "finalize_loop"}
_HOT_DECL = "ZOOLINT_HOT_PATH"

_JIT_WRAPPERS = {"jit", "pmap", "shard_map", "partial"}
_SCOPE_FNS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _attr_root(expr: ast.expr) -> Optional[str]:
    node = expr
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _unwrap_wrapper(expr: ast.expr, depth: int = 0,
                    stripped: Optional[List[str]] = None) -> ast.expr:
    """Strip ``jax.jit(fn, ...)`` / ``partial(fn, ...)`` layers so an
    alias of a wrapped function still resolves to the def; appends
    each stripped wrapper's name to ``stripped`` (a ``partial`` layer
    shifts positional binding, which callers must know)."""
    if depth > 2:
        return expr
    if isinstance(expr, ast.Call) and expr.args:
        name = None
        if isinstance(expr.func, ast.Name):
            name = expr.func.id
        elif isinstance(expr.func, ast.Attribute):
            name = expr.func.attr
        if name in _JIT_WRAPPERS:
            if stripped is not None:
                stripped.append(name)
            return _unwrap_wrapper(expr.args[0], depth + 1, stripped)
    return expr


class FnNode:
    """One function/method definition in the graph."""

    def __init__(self, src: SourceFile, node: ast.AST, qname: str,
                 cls_name: Optional[str], parent: Optional["FnNode"]):
        self.src = src
        self.node = node
        self.qname = qname                  # "<rel>::Class.method"
        self.name = getattr(node, "name", "<lambda>")
        self.cls_name = cls_name
        self.parent = parent                # enclosing FnNode, if any
        self.children: List["FnNode"] = []
        args = getattr(node, "args", None)
        self.pos_params: List[str] = []
        self.all_params: Set[str] = set()
        if args is not None:
            self.pos_params = [a.arg for a in
                               (list(args.posonlyargs) + list(args.args))]
            self.all_params = set(self.pos_params) | {
                a.arg for a in args.kwonlyargs}
        # propagation state
        self.contexts: Set[str] = set()
        self.jit_direct = False
        self.jit_kind: Optional[str] = None
        self.tracer_params: Set[str] = set()
        self.device_params: Set[str] = set()
        # one representative (root qname, caller qname) per context, so
        # finding messages can name HOW the context arrived
        self.via: Dict[str, Tuple[str, str]] = {}
        self.edges_out: List["CallEdge"] = []
        self.edges_in: List["CallEdge"] = []
        self._scope: Optional[Scope] = None

    @property
    def is_method(self) -> bool:
        return self.cls_name is not None

    def owning_class(self) -> Optional[str]:
        """The class whose ``self`` is in scope: this method's class,
        or -- for a def nested inside a method (the jitted-step idiom:
        ``def step(...)`` closing over ``self``) -- the enclosing
        method's class."""
        node: Optional["FnNode"] = self
        while node is not None:
            if node.cls_name is not None:
                return node.cls_name
            node = node.parent
        return None

    def scope(self) -> Scope:
        if self._scope is None:
            self._scope = Scope(self.node)
        return self._scope

    def effective_tracer_params(self) -> Set[str]:
        """Own traced params plus enclosing functions' traced params
        visible as closure free variables (minus shadowed names)."""
        out = set(self.tracer_params)
        node, shadow = self.parent, set(self.all_params)
        while node is not None:
            out |= node.tracer_params - shadow
            shadow |= node.all_params
            node = node.parent
        return out

    def root_of(self, ctx: str) -> str:
        return self.via.get(ctx, (self.qname, self.qname))[0]


def own_nodes(fn: FnNode) -> Iterable[ast.AST]:
    """Every AST node in ``fn``'s OWN body, pruning nested-def
    subtrees (each nested def is its own FnNode and scans itself --
    ``ast.walk`` + a skip of the def node alone would still descend
    into its body and double-report every finding there)."""
    nested = {id(c.node) for c in fn.children}

    def walk(node: ast.AST) -> Iterable[ast.AST]:
        for child in ast.iter_child_nodes(node):
            if id(child) in nested:
                continue
            yield child
            yield from walk(child)

    body = fn.node.body
    for stmt in (body if isinstance(body, list) else [body]):
        if id(stmt) in nested:
            continue  # a nested def IS a top-level body statement
        yield stmt
        yield from walk(stmt)


class CallEdge:
    def __init__(self, caller: FnNode, callee: FnNode, call: ast.Call,
                 bindings: List[Tuple[str, ast.expr]]):
        self.caller = caller
        self.callee = callee
        self.call = call
        self.bindings = bindings  # (callee param name, arg expression)


class CallGraph:
    """The built graph: nodes, edges, per-file unresolved counts."""

    def __init__(self, project: Project):
        self.project = project
        self.nodes: List[FnNode] = []
        self.by_node_id: Dict[int, FnNode] = {}
        # (rel, fn name) -> [module-level FnNodes]
        self._module_fns: Dict[Tuple[str, str], List[FnNode]] = {}
        # (rel, class, method) -> [FnNodes]
        self._methods: Dict[Tuple[str, str, str], List[FnNode]] = {}
        # rel -> {alias: ("module", rel2) | ("symbol", rel2, name)}
        self._imports: Dict[str, Dict[str, Tuple]] = {}
        # (rel, class) -> {attr: [value exprs]} from self.<attr> = ...
        self._self_attrs: Dict[Tuple[str, str],
                               Dict[str, List[ast.expr]]] = {}
        self._module_scopes: Dict[str, Scope] = {}
        self.unresolved: Dict[str, int] = {}
        self._build()
        self._mark_roots()
        self._mark_wrapper_call_roots()
        self._propagate()

    # ------------------------------------------------------ indexing --
    def _module_rel(self, dotted: str) -> Optional[str]:
        """rel path of a dotted module among the scanned files."""
        base = dotted.replace(".", "/")
        for cand in (base + ".py", base + "/__init__.py"):
            if self.project.file(cand) is not None:
                return cand
        # paths are repo-root-relative; a lint of a subtree may carry a
        # prefix (e.g. "analytics_zoo_tpu/...") -- try suffix match
        for f in self.project.files:
            if f.rel.endswith("/" + base + ".py"):
                return f.rel
        return None

    def _collect_imports(self, src: SourceFile) -> None:
        imp: Dict[str, Tuple] = {}
        pkg_parts = src.rel.split("/")[:-1]
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    rel2 = self._module_rel(alias.name)
                    if rel2 is not None:
                        imp[alias.asname
                            or alias.name.split(".")[0]] = (
                            ("module", rel2) if alias.asname
                            else ("module_root", alias.name, rel2))
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + (node.module.split(".")
                                           if node.module else []))
                else:
                    mod = node.module or ""
                rel2 = self._module_rel(mod) if mod else None
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    # "from pkg import worker" (submodule) vs
                    # "from pkg.mod import fn" (symbol)
                    sub = self._module_rel(
                        (mod + "." if mod else "") + alias.name)
                    if sub is not None:
                        imp[bound] = ("module", sub)
                    elif rel2 is not None:
                        imp[bound] = ("symbol", rel2, alias.name)
        self._imports[src.rel] = imp

    def _collect_defs(self, src: SourceFile) -> None:
        graph = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[Tuple[str, object]] = []  # (kind, x)

            def _fn_parent(self) -> Optional[FnNode]:
                for kind, x in reversed(self.stack):
                    if kind == "fn":
                        return x
                return None

            def visit_ClassDef(self, node):
                self.stack.append(("cls", node.name))
                self.generic_visit(node)
                self.stack.pop()

            def _def(self, node):
                parent = self._fn_parent()
                cls = None
                if (self.stack and self.stack[-1][0] == "cls"):
                    cls = self.stack[-1][1]
                qname = "::".join((src.rel, ".".join(
                    [x if k == "cls" else x.name
                     for k, x in self.stack] + [node.name])))
                fn = FnNode(src, node, qname, cls, parent)
                graph.nodes.append(fn)
                graph.by_node_id[id(node)] = fn
                if parent is not None:
                    parent.children.append(fn)
                if cls is not None:
                    graph._methods.setdefault(
                        (src.rel, cls, node.name), []).append(fn)
                elif parent is None:
                    graph._module_fns.setdefault(
                        (src.rel, node.name), []).append(fn)
                else:  # nested def: findable from enclosing scopes too
                    graph._module_fns.setdefault(
                        (src.rel, node.name), []).append(fn)
                self.stack.append(("fn", fn))
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _def
            visit_AsyncFunctionDef = _def

        V().visit(src.tree)

        # self.<attr> = <expr> assignments per class (alias one level)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs: Dict[str, List[ast.expr]] = {}
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Attribute)
                        and isinstance(sub.targets[0].value, ast.Name)
                        and sub.targets[0].value.id == "self"):
                    attrs.setdefault(sub.targets[0].attr,
                                     []).append(sub.value)
            self._self_attrs[(src.rel, node.name)] = attrs

    # ---------------------------------------------------- resolution --
    def _module_scope(self, rel: str) -> Scope:
        if rel not in self._module_scopes:
            src = self.project.file(rel)
            self._module_scopes[rel] = Scope(src.tree)
        return self._module_scopes[rel]

    def _lookup_local(self, caller: FnNode,
                      name: str) -> Optional[FnNode]:
        """A def LEXICALLY visible from ``caller`` by bare name:
        module level, or nested inside the caller's enclosing-function
        chain (a def nested in an unrelated function is not in scope
        and must not make an edge). Unique or nothing."""
        ancestors = {None}
        node: Optional[FnNode] = caller
        while node is not None:
            ancestors.add(node)
            node = node.parent
        hits = [n for n in self._module_fns.get(
            (caller.src.rel, name), [])
            if n.cls_name is None and n.parent in ancestors]
        if len(hits) == 1:
            return hits[0]
        return None

    def _resolve_ref(self, caller: FnNode, expr: ast.expr,
                     depth: int = 0,
                     stripped: Optional[List[str]] = None
                     ) -> Optional[FnNode]:
        if depth > 1:  # one level of alias indirection, by contract
            return None
        expr = _unwrap_wrapper(expr, stripped=stripped)
        if isinstance(expr, ast.Name):
            hit = self._lookup_local(caller, expr.id)
            if hit is not None:
                return hit
            imp = self._imports.get(caller.src.rel, {}).get(expr.id)
            if imp is not None and imp[0] == "symbol":
                return self._foreign_fn(imp[1], imp[2])
            # alias: unique simple assignment in the caller's own
            # scope, else the module scope (dataflow's Scope machinery)
            for scope in (caller.scope(),
                          self._module_scope(caller.src.rel)):
                if expr.id in scope.tainted:
                    return None
                assigns = scope.assigns.get(expr.id, [])
                if len(assigns) == 1:
                    return self._resolve_ref(caller, assigns[0],
                                             depth + 1, stripped)
                if assigns:
                    return None
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            own_cls = caller.owning_class()
            if (isinstance(base, ast.Name)
                    and base.id in ("self", "cls")
                    and own_cls is not None):
                hits = self._methods.get(
                    (caller.src.rel, own_cls, expr.attr), [])
                if len(hits) == 1:
                    return hits[0]
                if hits:
                    return None
                # self-attribute alias: self._step = jax.jit(step)
                attrs = self._self_attrs.get(
                    (caller.src.rel, own_cls), {})
                exprs = attrs.get(expr.attr, [])
                if len(exprs) == 1:
                    return self._resolve_ref(caller, exprs[0],
                                             depth + 1, stripped)
                return None
            if isinstance(base, ast.Name):
                imp = self._imports.get(caller.src.rel,
                                        {}).get(base.id)
                if imp is not None and imp[0] == "module":
                    return self._foreign_fn(imp[1], expr.attr)
            # "import analytics_zoo_tpu.serving.worker" root form:
            # worker.fn via full dotted attribute chain
            root = _attr_root(expr.value)
            if root is not None:
                imp = self._imports.get(caller.src.rel,
                                        {}).get(root)
                if imp is not None and imp[0] == "module_root":
                    dotted = self._dotted(expr.value)
                    if dotted is not None:
                        rel2 = self._module_rel(dotted)
                        if rel2 is not None:
                            return self._foreign_fn(rel2, expr.attr)
        return None

    @staticmethod
    def _dotted(expr: ast.expr) -> Optional[str]:
        parts: List[str] = []
        node = expr
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def _foreign_fn(self, rel: str, name: str) -> Optional[FnNode]:
        hits = [n for n in self._module_fns.get((rel, name), [])
                if n.cls_name is None and n.parent is None]
        if len(hits) == 1:
            return hits[0]
        return None

    # -------------------------------------------------------- edges --
    @staticmethod
    def _bind(call: ast.Call, callee: FnNode,
              bound_method: bool) -> List[Tuple[str, ast.expr]]:
        params = list(callee.pos_params)
        if bound_method and params and params[0] in ("self", "cls"):
            params = params[1:]
        out: List[Tuple[str, ast.expr]] = []
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                break
            if i < len(params):
                out.append((params[i], a))
        for kw in call.keywords:
            if kw.arg and kw.arg in callee.all_params:
                out.append((kw.arg, kw.value))
        return out

    def _collect_calls(self, fn: FnNode) -> None:
        for child in own_nodes(fn):
            if not isinstance(child, ast.Call):
                continue
            stripped: List[str] = []
            callee = self._resolve_ref(fn, child.func,
                                       stripped=stripped)
            if callee is None:
                self.unresolved[fn.src.rel] = (
                    self.unresolved.get(fn.src.rel, 0) + 1)
            elif callee.node is not fn.node:
                bound = (isinstance(child.func, ast.Attribute)
                         and callee.is_method)
                # an alias through partial pre-binds params, shifting
                # the positional map in a way this resolver does not
                # model: keep the edge (the call DOES happen --
                # contexts must flow) but claim no argument bindings
                bindings = ([] if "partial" in stripped
                            else self._bind(child, callee, bound))
                edge = CallEdge(fn, callee, child, bindings)
                fn.edges_out.append(edge)
                callee.edges_in.append(edge)

    def _build(self) -> None:
        for src in self.project.files:
            self._collect_imports(src)
            self._collect_defs(src)
        for fn in self.nodes:
            self._collect_calls(fn)

    # -------------------------------------------------------- roots --
    def _hot_declared(self, src: SourceFile) -> Set[Tuple[str, str]]:
        """(class-or-'', name) pairs from a module-level
        ``ZOOLINT_HOT_PATH = ("fn", "Class.method")`` declaration."""
        out: Set[Tuple[str, str]] = set()
        for node in src.tree.body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == _HOT_DECL
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for e in node.value.elts:
                    if (isinstance(e, ast.Constant)
                            and isinstance(e.value, str)):
                        cls, _, name = e.value.rpartition(".")
                        out.add((cls, name))
        return out

    def _mark_roots(self) -> None:
        for src in self.project.files:
            for jf in jitted_functions(src):
                fn = self.by_node_id.get(id(jf.fn))
                if fn is None:
                    continue  # inline lambda: PR 4 covers its body
                fn.jit_direct = True
                fn.jit_kind = jf.kind
                fn.tracer_params |= jf.params
                fn.contexts.add(CTX_JIT)
                fn.via.setdefault(CTX_JIT, (fn.qname, fn.qname))
                if jf.kind == "shard_map":
                    fn.contexts.add(CTX_COLLECTIVE)
                    fn.via.setdefault(CTX_COLLECTIVE,
                                      (fn.qname, fn.qname))
        declared_by_rel = {src.rel: self._hot_declared(src)
                           for src in self.project.files}
        for fn in self.nodes:
            stages = _HOT_STAGE_METHODS.get(fn.cls_name or "", set())
            declared = declared_by_rel.get(fn.src.rel, set())
            hot = (fn.name in stages
                   or (fn.cls_name or "", fn.name) in declared)
            if hot and fn.name not in _FINALIZE_SEAM:
                fn.contexts.add(CTX_HOTPATH)
                fn.via.setdefault(CTX_HOTPATH, (fn.qname, fn.qname))

    # ------------------------------------- wrapper-call root marking --
    def _wrap_target(self, caller: FnNode, expr: ast.expr,
                     depth: int = 0
                     ) -> Optional[Tuple[FnNode, int, Set[str]]]:
        """Resolve the function being traced in ``shard_map(X, ...)`` /
        ``jit(X)``, carrying partial-binding info the plain
        :meth:`_resolve_ref` discards: returns ``(fn, n_positional
        pre-bound, kw names pre-bound)`` through ``partial`` layers,
        nested wrappers, and one alias hop (``body = partial(f, ...)``;
        ``self._step = jit(step)``). None when unresolvable.

        The Name/self-attr/import branches mirror ``_resolve_ref``
        minus the dotted ``module_root`` form -- a resolution-rule
        change there must land here too, or the two walks drift."""
        if depth > 3:
            return None
        if isinstance(expr, ast.Call):
            name = None
            if isinstance(expr.func, ast.Name):
                name = expr.func.id
            elif isinstance(expr.func, ast.Attribute):
                name = expr.func.attr
            if name == "partial" and expr.args:
                inner = self._wrap_target(caller, expr.args[0],
                                          depth + 1)
                if inner is None:
                    return None
                fn, pos, kws = inner
                kws = kws | {kw.arg for kw in expr.keywords if kw.arg}
                if (any(kw.arg is None for kw in expr.keywords)
                        or any(isinstance(a, ast.Starred)
                               for a in expr.args[1:])):
                    # a *args/**kwargs splat can bind ANY parameter --
                    # which ones is unknowable, so no param may claim
                    # tracer taint (contexts still propagate)
                    kws = kws | {"*"}
                return fn, pos + len(expr.args) - 1, kws
            if name in _JIT_WRAPPERS and expr.args:
                return self._wrap_target(caller, expr.args[0],
                                         depth + 1)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in caller.all_params:
                # a function passed IN (params lexically shadow outer
                # defs): resolvable one level up, at the caller's own
                # call sites (the _ring_shard_call idiom) -- hand back
                # a marker for the deferred pass
                return ("param", expr.id), 0, set()
            hit = self._lookup_local(caller, expr.id)
            if hit is not None:
                return hit, 0, set()
            imp = self._imports.get(caller.src.rel, {}).get(expr.id)
            if imp is not None and imp[0] == "symbol":
                fn = self._foreign_fn(imp[1], imp[2])
                return None if fn is None else (fn, 0, set())
            for scope in (caller.scope(),
                          self._module_scope(caller.src.rel)):
                if expr.id in scope.tainted:
                    return None
                assigns = scope.assigns.get(expr.id, [])
                if len(assigns) == 1:
                    return self._wrap_target(caller, assigns[0],
                                             depth + 1)
                if assigns:
                    return None
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            own_cls = caller.owning_class()
            if (isinstance(base, ast.Name) and base.id in ("self",
                                                           "cls")
                    and own_cls is not None):
                hits = self._methods.get(
                    (caller.src.rel, own_cls, expr.attr), [])
                if len(hits) == 1:
                    return hits[0], 0, set()
                if hits:
                    return None
                exprs = self._self_attrs.get(
                    (caller.src.rel, own_cls), {}).get(expr.attr, [])
                if len(exprs) == 1:
                    return self._wrap_target(caller, exprs[0],
                                             depth + 1)
                return None
            if isinstance(base, ast.Name):
                imp = self._imports.get(caller.src.rel,
                                        {}).get(base.id)
                if imp is not None and imp[0] == "module":
                    fn = self._foreign_fn(imp[1], expr.attr)
                    return None if fn is None else (fn, 0, set())
        return None

    def _mark_wrapper_call_roots(self) -> None:
        """Mark functions traced through a wrapper CALL (not a
        decorator): ``shard_map(body, mesh, ...)`` where ``body =
        partial(_pipeline_local, stage_fn=...)`` -- the pipeline /
        ring-attention / zouwu idiom. The PR-4 detection only sees
        decorators, ``jit(name)`` by direct name, and inline lambdas,
        so these bodies carried no collective context at all; this is
        THE resolution gap that hid the jax-0.4.x ``lax.axis_size``
        crashes (collective-version-api in deep_rules)."""
        deferred: List[Tuple[FnNode, str, ast.Call, str, int,
                             Set[str]]] = []
        for fn in self.nodes:
            for child in own_nodes(fn):
                if isinstance(child, ast.Call):
                    self._mark_one_wrapper_call(fn, child, deferred)
        # higher-order, one level: ``fn`` wraps its own PARAMETER
        # (``_ring_shard_call(local_fn, ...)`` -> ``shard_map(
        # partial(local_fn, ...), ...)``); the wrapped function is
        # whatever fn's resolved call sites pass for that parameter
        for fn, wname, call, pname, pos, kws in deferred:
            for edge in fn.edges_in:
                for bname, aexpr in edge.bindings:
                    if bname != pname:
                        continue
                    info = self._wrap_target(edge.caller, aexpr)
                    if info is None or not isinstance(info[0], FnNode):
                        continue
                    self._mark_root_fn(info[0], wname, call,
                                       edge.caller,
                                       pos + info[1], kws | info[2])

    def _mark_one_wrapper_call(
            self, caller: FnNode, call: ast.Call,
            deferred: List[Tuple[FnNode, str, ast.Call, str, int,
                                 Set[str]]]) -> None:
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            if _attr_root(func) != "jax":
                return  # jax.jit / jax.experimental...shard_map only
        else:
            return
        if name not in ("jit", "pmap", "shard_map") or not call.args:
            return
        info = self._wrap_target(caller, call.args[0])
        if info is None:
            return
        target, pos_bound, kw_bound = info
        if isinstance(target, FnNode):
            self._mark_root_fn(target, name, call, caller, pos_bound,
                               kw_bound)
        else:  # ("param", pname): resolve at caller's call sites
            deferred.append((caller, name, call, target[1], pos_bound,
                             kw_bound))

    def _mark_root_fn(self, callee: FnNode, wrapper: str,
                      call: ast.Call, caller: FnNode, pos_bound: int,
                      kw_bound: Set[str]) -> None:
        if callee.jit_direct:
            return  # PR-4 saw it; its static_argnums params stand
        callee.contexts.add(CTX_JIT)
        callee.via.setdefault(CTX_JIT, (callee.qname, caller.qname))
        if wrapper == "shard_map":
            callee.contexts.add(CTX_COLLECTIVE)
            callee.via.setdefault(CTX_COLLECTIVE,
                                  (callee.qname, caller.qname))
        if "*" in kw_bound:
            return  # a splat layer: param binding unknowable, no taint
        static = _static_params(call, callee.node)
        for pname in callee.pos_params[pos_bound:]:
            if pname in ("self", "cls") or pname in kw_bound \
                    or pname in static:
                continue
            callee.tracer_params.add(pname)

    # -------------------------------------------------- propagation --
    def _propagate(self) -> None:
        changed = True
        guard = 0
        while changed and guard < 100:
            changed = False
            guard += 1
            for fn in self.nodes:
                # containment: nested defs inherit enclosing contexts
                for child in fn.children:
                    for ctx in fn.contexts:
                        if ctx == CTX_HOTPATH and (
                                child.name in _FINALIZE_SEAM):
                            continue
                        if ctx not in child.contexts:
                            child.contexts.add(ctx)
                            child.via.setdefault(
                                ctx, (fn.root_of(ctx), fn.qname))
                            changed = True
                for edge in fn.edges_out:
                    callee = edge.callee
                    for ctx in fn.contexts:
                        if ctx == CTX_HOTPATH and (
                                callee.name in _FINALIZE_SEAM):
                            continue  # the sanctioned sync barrier
                        if ctx not in callee.contexts:
                            callee.contexts.add(ctx)
                            callee.via.setdefault(
                                ctx, (fn.root_of(ctx), fn.qname))
                            changed = True
                    if (CTX_JIT in fn.contexts
                            or CTX_COLLECTIVE in fn.contexts):
                        params = fn.effective_tracer_params()
                        for pname, aexpr in edge.bindings:
                            if (pname not in callee.tracer_params
                                    and _is_tracer_expr(aexpr, params)):
                                callee.tracer_params.add(pname)
                                changed = True
                    if CTX_HOTPATH in fn.contexts:
                        for pname, aexpr in edge.bindings:
                            if (pname not in callee.device_params
                                    and is_device_expr(aexpr, fn)):
                                callee.device_params.add(pname)
                                changed = True

    # ------------------------------------------------------- export --
    def to_dict(self) -> Dict:
        """The ``--graph`` debug dump: what resolved, what contexts
        propagated where, which params carry taint."""
        fns = []
        for fn in sorted(self.nodes, key=lambda n: n.qname):
            if not (fn.contexts or fn.edges_out or fn.edges_in):
                continue
            fns.append({
                "qname": fn.qname,
                "contexts": sorted(fn.contexts),
                "jit_direct": fn.jit_direct,
                "tracer_params": sorted(fn.tracer_params),
                "device_params": sorted(fn.device_params),
                "via": {k: list(v) for k, v in sorted(fn.via.items())},
                "calls": sorted({e.callee.qname
                                 for e in fn.edges_out}),
            })
        return {
            "functions": fns,
            "unresolved_calls": dict(sorted(self.unresolved.items())),
            "counts": {
                "functions": len(self.nodes),
                "edges": sum(len(f.edges_out) for f in self.nodes),
                "unresolved": sum(self.unresolved.values()),
            },
        }


# --------------------------------------------------------------------- #
# device-derivation walk (shared with deep_rules' hot-path family)       #
# --------------------------------------------------------------------- #
_DEVICE_ATTRS = {"predict_async"}
_DEVICE_MODULES = {"jnp"}


def _device_call(call: ast.Call, fn: FnNode,
                 _seen: Optional[Set[str]] = None) -> bool:
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None)
    if name in _DEVICE_ATTRS:
        return True
    if name == "device_put":
        return True
    root = _attr_root(func) if isinstance(func, ast.Attribute) else None
    if root in _DEVICE_MODULES:
        # jnp ops produce device arrays (jnp.asarray of host data is
        # itself the transfer, so it is a device source too)
        return True
    if name in ("tree_map", "tree_leaves"):
        return any(is_device_expr(a, fn, _seen) for a in call.args)
    # a call to a jit-wrapped function in the same graph
    graph = getattr(fn, "_graph", None)
    if graph is not None:
        callee = graph._resolve_ref(fn, func)
        if callee is not None and callee.jit_direct:
            return True
    return False


def is_device_expr(expr: ast.AST, fn: FnNode,
                   _seen: Optional[Set[str]] = None) -> bool:
    """Proven device-derived: a value the walk can trace to an async
    dispatch (``predict_async``), a jit-wrapped call, a ``jnp`` op,
    ``jax.device_put``, or a parameter that inherited device taint.
    Unknown derivations return False -- the caller must not claim."""
    if _seen is None:
        _seen = set()
    if isinstance(expr, ast.Name):
        if expr.id in fn.device_params:
            return True
        if expr.id in _seen:
            # self-referential assignment (``acc = acc + ...``): the
            # cycle itself proves nothing -- the OTHER operands decide
            return False
        _seen = _seen | {expr.id}
        scope = fn.scope()
        if expr.id in scope.tainted:
            # tuple-unpack of a device-producing call is the worker
            # idiom (``preds, n = model.predict_async(x)``); Scope
            # taints those, so look for the unpack assignment directly
            return _unpack_device(expr.id, fn, _seen)
        assigns = scope.assigns.get(expr.id, [])
        return bool(assigns) and all(
            is_device_expr(a, fn, _seen) for a in assigns)
    if isinstance(expr, ast.Call):
        return _device_call(expr, fn, _seen)
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            # x.shape / x.dtype / x.ndim on a device array is host
            # metadata -- reading it costs no d2h sync
            return False
        return is_device_expr(expr.value, fn, _seen)
    if isinstance(expr, ast.Subscript):
        return is_device_expr(expr.value, fn, _seen)
    if isinstance(expr, ast.BinOp):
        return (is_device_expr(expr.left, fn, _seen)
                or is_device_expr(expr.right, fn, _seen))
    return False


def _unpack_device(name: str, fn: FnNode,
                   _seen: Optional[Set[str]] = None) -> bool:
    """True when every ``a, b = <call>`` binding of ``name`` in this
    function unpacks a device-producing call."""
    found = False
    for stmt in ast.walk(fn.node):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        t = stmt.targets[0]
        if not isinstance(t, (ast.Tuple, ast.List)):
            continue
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        if name not in names:
            continue
        if not (isinstance(stmt.value, ast.Call)
                and _device_call(stmt.value, fn, _seen)):
            return False
        found = True
    return found


def build_call_graph(project: Project) -> CallGraph:
    graph = CallGraph(project)
    for fn in graph.nodes:
        fn._graph = graph  # backref for the device walk
    return graph
