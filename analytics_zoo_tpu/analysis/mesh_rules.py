"""Checker family 6: mesh / collective correctness (shardcheck).

Every remaining scaling direction (multi-chip serving, continuous
batching, the fleet) routes work through ``parallel/`` -- ``shard_map``
bodies calling ``psum``/``ppermute``/``all_gather`` over named mesh
axes. An ``axis_name`` typo or an ``in_specs`` arity mismatch fails
only at runtime on a real multi-device mesh, the most expensive place
to find it. These rules validate the distributed plan statically, on
top of the :mod:`analytics_zoo_tpu.analysis.dataflow` layer so one
level of variable indirection (``axis = config_axis("model")``,
``AXIS = "tp"``) resolves to the value at the use site.

Ground truth (found structurally, so fixture projects work):

- the ``zoo.mesh.axis.<role>`` entries of any scanned module's
  ``_DEFAULTS`` dict -- both the *roles* and their default axis-name
  values;
- module-level ``*_AXIS = "<name>"`` constants (``DATA_AXIS`` etc. in
  ``parallel/mesh.py``);
- axis names literally present in the ``in_specs``/``out_specs`` of
  the ``shard_map`` call wrapping the function under scrutiny.

Rules:

``mesh-axis-unbound`` (error)
    A collective whose axis argument *resolves* to a string that no
    vocabulary source declares and the enclosing specs never mention,
    or to ``config_axis("<role>")`` with an undeclared role. An
    unresolvable axis (function parameter, computed value) is never a
    finding -- the walk is conservative.

``mesh-spec-arity`` (error)
    A ``shard_map`` call whose literal ``in_specs`` tuple length
    cannot match the wrapped function's positional signature (specs
    are the exact argument tuple the mapped call receives).

``mesh-unsharded-axis`` (warning)
    A collective inside a ``shard_map`` body over a *declared* axis
    that the wrapping call's fully-literal specs never shard: the
    operand is replicated over that axis, so e.g. ``psum`` silently
    multiplies by the axis size. Skipped whenever the specs contain
    anything non-literal (the set of sharded axes is then unknown).

``mesh-nested-collective`` (warning)
    A collective whose operand expression already contains a
    collective over the same axis name (``psum(psum(x, "a"), "a")``):
    almost always a double reduction from refactored helper layers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, SourceFile, register)
from analytics_zoo_tpu.analysis.dataflow import (
    ConfigAxis, ScopeChain, walk_with_scopes)

# collective name -> positional index of its axis-name argument
_COLLECTIVES: Dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
    "all_gather": 1, "psum_scatter": 1, "ppermute": 1,
    "all_to_all": 1, "axis_index": 0, "axis_size": 0,
    # parallel.collectives wrappers (same contract, repo idiom)
    "all_reduce_sum": 1, "all_reduce_mean": 1, "reduce_scatter": 1,
    "ring_permute": 1, "global_norm": 1,
    # EQuARX-idiom quantized collectives (serving shard layer): same
    # axis-name contract, so typo'd axes fail lint before a mesh run
    "quantized_psum": 1, "quantized_all_gather": 1,
}
_AXIS_KWARG = "axis_name"
# DATA_AXIS / FSDP_AXIS / ... declaration-constant naming (suffix
# anchored so e.g. an _AXIS_KWARG helper string is not a declaration)
_AXIS_CONST_RE = re.compile(r"(^|_)AXIS$")


def _call_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _axis_arg(node: ast.Call) -> Optional[ast.expr]:
    """The axis-name argument expression of a collective call."""
    name = _call_name(node.func)
    idx = _COLLECTIVES.get(name or "")
    if idx is None:
        return None
    for kw in node.keywords:
        if kw.arg == _AXIS_KWARG:
            return kw.value
    if len(node.args) > idx:
        return node.args[idx]
    return None


def _spec_axes(node: ast.AST) -> Tuple[Set[str], bool]:
    """(axis names, fully_literal) of a specs expression: every string
    constant inside counts as an axis; any Name/Call other than
    ``P``/``PartitionSpec`` construction makes the set incomplete."""
    axes: Set[str] = set()
    complete = True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant):
            if isinstance(sub.value, str):
                axes.add(sub.value)
        elif isinstance(sub, ast.Call):
            if _call_name(sub.func) not in ("P", "PartitionSpec"):
                complete = False
        elif isinstance(sub, ast.Name):
            if sub.id not in ("P", "PartitionSpec", "None"):
                complete = False
        elif not isinstance(sub, (ast.Tuple, ast.List, ast.Load,
                                  ast.Attribute, ast.keyword,
                                  ast.Starred)):
            if not isinstance(sub, ast.expr_context):
                complete = False
    return axes, complete


def _positional_arity(fn: ast.AST) -> Optional[Tuple[int, int]]:
    """(min, max) positional-argument count of a def/lambda, or None
    when *args makes it unbounded."""
    args = getattr(fn, "args", None)
    if args is None:
        return None
    if args.vararg is not None:
        return None
    pos = list(args.posonlyargs) + list(args.args)
    n = len(pos)
    return n - len(args.defaults), n


class _ShardMapInfo:
    """One shard_map call: the wrapped fn (when statically known), the
    axes its literal specs shard, and whether that set is complete."""

    def __init__(self, call: ast.Call):
        self.call = call
        self.axes: Set[str] = set()
        self.complete = True
        self.in_specs: Optional[ast.expr] = None
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                if kw.arg == "in_specs":
                    self.in_specs = kw.value
                axes, complete = _spec_axes(kw.value)
                self.axes |= axes
                self.complete = self.complete and complete
            elif kw.arg == "axis_names":
                axes, complete = _spec_axes(kw.value)
                self.axes |= axes
                self.complete = self.complete and complete


@register
class MeshCollectiveChecker(Checker):
    name = "mesh"
    rules = {
        "mesh-axis-unbound": "collective axis name resolves to a "
                             "string no zoo.mesh.axis.* key, *_AXIS "
                             "constant, or enclosing shard_map spec "
                             "declares (typo'd axis)",
        "mesh-spec-arity": "shard_map in_specs tuple length cannot "
                           "match the wrapped function's positional "
                           "signature",
        "mesh-unsharded-axis": "collective over a declared axis the "
                               "enclosing shard_map's specs never "
                               "shard (replicated operand: psum "
                               "multiplies by axis size)",
        "mesh-nested-collective": "collective nested inside another "
                                  "collective over the same axis "
                                  "(double reduction)",
    }

    # ---------------------------------------------------- vocabulary --
    @staticmethod
    def _axis_vocabulary(project: Project
                         ) -> Tuple[Set[str], Set[str]]:
        """(axis-name values, config roles) declared anywhere in the
        scanned tree."""
        values: Set[str] = set()
        roles: Set[str] = set()
        for src in project.files:
            for node in src.tree.body:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    value = getattr(node, "value", None)
                    if (t.id == "_DEFAULTS"
                            and isinstance(value, ast.Dict)):
                        for k, v in zip(value.keys, value.values):
                            if (isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)
                                    and k.value.startswith(
                                        "zoo.mesh.axis.")):
                                roles.add(
                                    k.value[len("zoo.mesh.axis."):])
                                if (isinstance(v, ast.Constant)
                                        and isinstance(v.value, str)):
                                    values.add(v.value)
                    elif (_AXIS_CONST_RE.search(t.id)
                          and isinstance(value, ast.Constant)
                          and isinstance(value.value, str)):
                        values.add(value.value)
        return values, roles

    # -------------------------------------------------- per-file scan --
    @staticmethod
    def _shard_map_wrappings(src: SourceFile
                             ) -> Tuple[Dict[str, List[_ShardMapInfo]],
                                        List[Tuple[ast.Lambda,
                                                   _ShardMapInfo]]]:
        """{fn name: wrapping shard_map calls} + (lambda, wrapping)."""
        by_name: Dict[str, List[_ShardMapInfo]] = {}
        lambdas: List[Tuple[ast.Lambda, _ShardMapInfo]] = []
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node.func) == "shard_map"
                    and node.args):
                continue
            info = _ShardMapInfo(node)
            target = node.args[0]
            if isinstance(target, ast.Name):
                by_name.setdefault(target.id, []).append(info)
            elif isinstance(target, ast.Lambda):
                lambdas.append((target, info))
        return by_name, lambdas

    def check_project(self, project: Project) -> Iterable[Finding]:
        vocab_values, vocab_roles = self._axis_vocabulary(project)
        for src in project.files:
            yield from self._check_file(src, vocab_values, vocab_roles)

    def _check_file(self, src: SourceFile, vocab_values: Set[str],
                    vocab_roles: Set[str]) -> Iterable[Finding]:
        by_name, wrapped_lambdas = self._shard_map_wrappings(src)

        # defs by name (for arity + body context); ambiguous names
        # (two defs sharing one name) are skipped everywhere below
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        # ---- mesh-spec-arity -------------------------------------- --
        for fname, infos in by_name.items():
            fns = defs.get(fname, [])
            if len(fns) != 1:
                continue
            yield from self._check_arity(src, fns[0], fname, infos)
        for lam, info in wrapped_lambdas:
            yield from self._check_arity(src, lam, "<lambda>", [info])

        # ---- body context: fn node -> wrapping info ----------------- --
        body_ctx: Dict[int, Tuple[Set[str], bool]] = {}
        for fname, infos in by_name.items():
            fns = defs.get(fname, [])
            if len(fns) != 1:
                continue
            axes: Set[str] = set()
            complete = len(infos) == 1
            for info in infos:
                axes |= info.axes
                complete = complete and info.complete
            body_ctx[id(fns[0])] = (axes, complete)
        for lam, info in wrapped_lambdas:
            body_ctx[id(lam)] = (set(info.axes), info.complete)

        # ---- collectives ------------------------------------------- --
        # track the innermost enclosing shard_map-wrapped fn while
        # walking with scopes (nested defs inherit the body context)
        yield from self._check_collectives(src, body_ctx, vocab_values,
                                           vocab_roles)

    def _check_arity(self, src: SourceFile, fn: ast.AST, fname: str,
                     infos: List[_ShardMapInfo]) -> Iterable[Finding]:
        arity = _positional_arity(fn)
        if arity is None:
            return
        lo, hi = arity
        for info in infos:
            spec = info.in_specs
            if not isinstance(spec, (ast.Tuple, ast.List)):
                continue  # single-spec prefix or computed: no claim
            if any(isinstance(e, ast.Starred) for e in spec.elts):
                continue
            n = len(spec.elts)
            if not (lo <= n <= hi):
                want = (str(hi) if lo == hi
                        else f"between {lo} and {hi}")
                yield Finding(
                    "mesh-spec-arity", "error", src.rel,
                    spec.lineno,
                    f"shard_map wraps '{fname}' with {n} in_specs "
                    f"but its signature takes {want} positional "
                    "argument(s); the mapped call passes exactly one "
                    "operand per spec")

    def _check_collectives(self, src: SourceFile,
                           body_ctx: Dict[int, Tuple[Set[str], bool]],
                           vocab_values: Set[str],
                           vocab_roles: Set[str]) -> Iterable[Finding]:
        have_vocab = bool(vocab_values or vocab_roles)
        # enclosing wrapped-body context per node: recompute by walking
        # parents via a stack of (node, ctx)
        ctx_of_node: Dict[int, Tuple[Set[str], bool]] = {}

        def paint(node: ast.AST, ctx: Optional[Tuple[Set[str], bool]]):
            here = body_ctx.get(id(node), ctx)
            if here is not None:
                ctx_of_node[id(node)] = here
            for child in ast.iter_child_nodes(node):
                paint(child, here)

        paint(src.tree, None)

        for node, chain in walk_with_scopes(src.tree):
            if not isinstance(node, ast.Call):
                continue
            axis_expr = _axis_arg(node)
            if axis_expr is None:
                continue
            cname = _call_name(node.func)
            values = chain.resolve_strings(axis_expr)
            if values is None:
                continue  # unresolvable: conservative, no claim
            ctx = ctx_of_node.get(id(node))
            bound = ctx[0] if ctx else set()
            complete = ctx[1] if ctx else False
            for v in sorted(values, key=repr):
                if v is None:
                    continue
                if isinstance(v, ConfigAxis):
                    if vocab_roles and v.role not in vocab_roles:
                        yield Finding(
                            "mesh-axis-unbound", "error", src.rel,
                            node.lineno,
                            f"collective '{cname}' uses config_axis"
                            f"('{v.role}') but no zoo.mesh.axis."
                            f"{v.role} key is declared (known roles: "
                            f"{', '.join(sorted(vocab_roles))})")
                    continue
                if have_vocab and v not in vocab_values | bound:
                    yield Finding(
                        "mesh-axis-unbound", "error", src.rel,
                        node.lineno,
                        f"collective '{cname}' over axis '{v}': no "
                        "zoo.mesh.axis.* default, *_AXIS constant, or "
                        "enclosing shard_map spec declares that axis "
                        "name (typo, or declare the axis)")
                elif (complete and bound and v not in bound
                      and v in vocab_values):
                    yield Finding(
                        "mesh-unsharded-axis", "warning", src.rel,
                        node.lineno,
                        f"collective '{cname}' reduces over axis "
                        f"'{v}' but the enclosing shard_map specs "
                        f"only shard {sorted(bound)}; the operand is "
                        "replicated over that axis (psum would "
                        "multiply by its size)")
            # nested collective over the same axis
            single = (next(iter(values))
                      if len(values) == 1 else None)
            if isinstance(single, str):
                for sub in ast.walk(
                        node.args[0] if node.args else axis_expr):
                    if (isinstance(sub, ast.Call) and sub is not node
                            and _call_name(sub.func) in _COLLECTIVES):
                        sub_axis = _axis_arg(sub)
                        if sub_axis is None:
                            continue
                        sub_vals = chain.resolve_strings(sub_axis)
                        if sub_vals == frozenset([single]):
                            yield Finding(
                                "mesh-nested-collective", "warning",
                                src.rel, node.lineno,
                                f"collective '{cname}' over axis "
                                f"'{single}' already contains a "
                                f"'{_call_name(sub.func)}' over the "
                                "same axis (double reduction)")
