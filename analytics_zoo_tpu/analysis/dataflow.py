"""Lightweight intraprocedural dataflow for zoolint checkers.

Checkers built on the one-parse :class:`~analytics_zoo_tpu.analysis.
core.SourceFile` often need to answer "what *string* does this
expression hold at the use site?" -- an ``axis_name`` handed to
``lax.psum``, a wire key indexed out of a decoded blob, a prefix
passed to ``startswith``. A pure literal scan misses the repo's
dominant indirection idioms::

    axis = config_axis("model")          # helper-wrapper call
    SPEC_AXIS = "seq"                    # module-level constant
    lax.psum(x, axis)                    # <- resolve to the value

This module implements the minimal machinery those checkers need:
**reaching definitions** (which assignments can bind a name at a use
site, walking lexical scopes inward-out) plus **literal/constant
propagation** (folding constants, ``+``-concatenation, constant
f-strings, and ternaries into a *set of possible values*).

Design rules:

- **Conservative by construction.** Anything the walk cannot prove
  returns ``None`` ("unknown") and the caller must not report a
  finding. A name bound by a loop target, ``with ... as``, unpacking,
  augmented assignment, a ``match`` capture, or a function parameter
  is unknown. A name assigned several times resolves only when every
  assignment resolves to the SAME value set -- the walk has no
  statement ordering, so differing reassignments (``axis = "model"``
  ... ``axis = status_msg``) are unknown rather than a union that
  would let a later unrelated value indict an earlier correct use.
- **Intraprocedural.** Resolution never crosses a call boundary; the
  one sanctioned exception is :class:`ConfigAxis`, a symbolic marker
  for the ``parallel.mesh.config_axis("<role>")`` helper so mesh
  checkers can validate the *role* against declared
  ``zoo.mesh.axis.*`` keys without knowing the deployment's axis
  spelling.
- **Scope chains are explicit.** Callers pass the lexical nesting
  (module node outermost, then each enclosing function) so closures
  resolve through enclosing-function and module constants exactly
  like Python's own name lookup (minus ``global``/``nonlocal``
  rebinding, which taints the name to unknown).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

# resolution result values are python constants (str/int/float/bool/
# None) or ConfigAxis markers; a result SET is always hashable


@dataclasses.dataclass(frozen=True)
class ConfigAxis:
    """Symbolic value of ``config_axis(role[, fallback])`` -- the
    mesh-axis helper that reads ``zoo.mesh.axis.<role>``. ``fallback``
    is the literal fallback when it was resolvable, else None."""

    role: str
    fallback: Optional[str] = None


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_MAX_DEPTH = 20  # cycle/depth guard for a = b; b = a chains


def _param_names(node: ast.AST) -> Set[str]:
    args = getattr(node, "args", None)
    if args is None:
        return set()
    names = {a.arg for a in (list(args.posonlyargs) + list(args.args)
                             + list(args.kwonlyargs))}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


class Scope:
    """Name bindings of one lexical scope (module or function body).

    ``assigns`` holds the value expressions of *simple* assignments
    (``name = expr`` / annotated form); ``tainted`` holds names bound
    any other way (params, loop targets, ``with as``, unpacking,
    imports, ``+=``, walrus, ``global``/``nonlocal``) -- those resolve
    to unknown.
    """

    def __init__(self, node: ast.AST):
        self.node = node
        self.assigns: Dict[str, List[ast.expr]] = {}
        self.tainted: Set[str] = set(_param_names(node))
        body = getattr(node, "body", [])
        if isinstance(body, ast.expr):  # Lambda: expression body
            body = []
        for stmt in body:
            self._visit_stmt(stmt)

    # -- statement walk that stays inside this scope ------------------
    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, _SCOPE_NODES + (ast.ClassDef,)):
            return  # nested scope: its bindings are not ours
        if isinstance(stmt, ast.Assign):
            if (len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                self.assigns.setdefault(
                    stmt.targets[0].id, []).append(stmt.value)
            else:
                for t in stmt.targets:
                    self._taint_target(t)
        elif isinstance(stmt, ast.AnnAssign):
            if isinstance(stmt.target, ast.Name):
                if stmt.value is not None:
                    self.assigns.setdefault(
                        stmt.target.id, []).append(stmt.value)
            else:
                self._taint_target(stmt.target)
        elif isinstance(stmt, ast.AugAssign):
            self._taint_target(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._taint_target(stmt.target)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._taint_target(item.optional_vars)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                self.tainted.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(stmt, (ast.Global, ast.Nonlocal)):
            # a rebinding declaration makes local reasoning unsound
            self.tainted.update(stmt.names)
        # walrus assignments anywhere in expressions taint their name
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.NamedExpr) and isinstance(
                    sub.target, ast.Name):
                self.tainted.add(sub.target.id)
        # recurse into compound-statement bodies (same scope)
        for field in ("body", "orelse", "finalbody"):
            for child in getattr(stmt, field, []) or []:
                if isinstance(child, ast.stmt):
                    self._visit_stmt(child)
        for handler in getattr(stmt, "handlers", []) or []:
            if handler.name:
                self.tainted.add(handler.name)
            for child in handler.body:
                self._visit_stmt(child)
        # match statements: capture patterns bind names (unknown), and
        # case bodies are this scope too -- skipping them would leave
        # their rebindings invisible and make resolution wrong rather
        # than conservatively unknown
        for case in getattr(stmt, "cases", []) or []:
            for sub in ast.walk(case.pattern):
                name = getattr(sub, "name", None)
                if isinstance(name, str):
                    self.tainted.add(name)
                rest = getattr(sub, "rest", None)
                if isinstance(rest, str):
                    self.tainted.add(rest)
            for child in case.body:
                self._visit_stmt(child)

    def _taint_target(self, target: ast.expr) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                self.tainted.add(node.id)

    def binds(self, name: str) -> bool:
        return name in self.assigns or name in self.tainted


class ScopeChain:
    """Lexical chain outermost-module -> ... -> innermost function.

    Built lazily from raw AST nodes; :meth:`resolve` answers with a
    frozenset of possible constant values or ``None`` for unknown.
    """

    def __init__(self, nodes: Sequence[ast.AST]):
        self._scopes = [Scope(n) for n in nodes]

    def push(self, node: ast.AST) -> "ScopeChain":
        child = ScopeChain.__new__(ScopeChain)
        child._scopes = self._scopes + [Scope(node)]
        return child

    # ---------------------------------------------------- resolution --
    def resolve(self, node: ast.AST,
                _depth: int = 0) -> Optional[FrozenSet]:
        """Set of possible values of ``node``, or None when unknown."""
        if _depth > _MAX_DEPTH:
            return None
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (str, int, float, bool,
                                       type(None))):
                return frozenset([node.value])
            return None
        if isinstance(node, ast.Name):
            return self._resolve_name(node.id, _depth)
        if isinstance(node, ast.IfExp):
            a = self.resolve(node.body, _depth + 1)
            b = self.resolve(node.orelse, _depth + 1)
            if a is None or b is None:
                return None
            return a | b
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = self.resolve(node.left, _depth + 1)
            right = self.resolve(node.right, _depth + 1)
            if left is None or right is None:
                return None
            out = set()
            for l in left:
                for r in right:
                    if isinstance(l, str) and isinstance(r, str):
                        out.add(l + r)
                    else:
                        return None
            return frozenset(out)
        if isinstance(node, ast.JoinedStr):
            # constant f-string (every piece a literal) folds; any
            # formatted hole makes it unknown
            parts: List[FrozenSet] = []
            for value in node.values:
                if isinstance(value, ast.Constant) and isinstance(
                        value.value, str):
                    parts.append(frozenset([value.value]))
                elif isinstance(value, ast.FormattedValue):
                    inner = self.resolve(value.value, _depth + 1)
                    if inner is None or not all(
                            isinstance(v, str) for v in inner):
                        return None
                    parts.append(inner)
                else:
                    return None
            outs = {""}
            for part in parts:
                outs = {a + b for a in outs for b in part}
            return frozenset(outs)
        if isinstance(node, ast.Call):
            return self._resolve_call(node, _depth)
        return None

    def _resolve_name(self, name: str,
                      _depth: int) -> Optional[FrozenSet]:
        for scope in reversed(self._scopes):
            if not scope.binds(name):
                continue
            if name in scope.tainted:
                return None
            sets: List[FrozenSet] = []
            for expr in scope.assigns[name]:
                resolved = self.resolve(expr, _depth + 1)
                if resolved is None:
                    return None
                sets.append(resolved)
            # no statement ordering here: several assignments resolve
            # only when they agree, else the binding is unknown (a
            # union would let an unrelated later value indict an
            # earlier correct use)
            if any(s != sets[0] for s in sets[1:]):
                return None
            return sets[0]
        return None  # free name (import/builtin): unknown

    def _resolve_call(self, node: ast.Call,
                      _depth: int) -> Optional[FrozenSet]:
        func = node.func
        fname = None
        if isinstance(func, ast.Name):
            fname = func.id
        elif isinstance(func, ast.Attribute):
            fname = func.attr
        if fname == "config_axis" and node.args:
            role = self.resolve(node.args[0], _depth + 1)
            if role is None or len(role) != 1:
                return None
            (role_v,) = role
            if not isinstance(role_v, str):
                return None
            fallback: Optional[str] = None
            for kw in node.keywords:
                if kw.arg == "fallback":
                    fb = self.resolve(kw.value, _depth + 1)
                    if fb is not None and len(fb) == 1:
                        (fb_v,) = fb
                        if isinstance(fb_v, str):
                            fallback = fb_v
            if fallback is None and len(node.args) > 1:
                fb = self.resolve(node.args[1], _depth + 1)
                if fb is not None and len(fb) == 1:
                    (fb_v,) = fb
                    if isinstance(fb_v, str):
                        fallback = fb_v
            return frozenset([ConfigAxis(role_v, fallback)])
        if fname == "str" and len(node.args) == 1:
            inner = self.resolve(node.args[0], _depth + 1)
            if inner is not None and all(isinstance(v, (str, ConfigAxis))
                                         for v in inner):
                return inner
        return None

    def resolve_strings(self, node: ast.AST
                        ) -> Optional[FrozenSet]:
        """Like :meth:`resolve`, but only accepts results made of
        strings, ``None``, and :class:`ConfigAxis` markers (the shapes
        axis/key checkers understand); anything else is unknown."""
        values = self.resolve(node)
        if values is None:
            return None
        if all(v is None or isinstance(v, (str, ConfigAxis))
               for v in values):
            return values
        return None


def module_chain(tree: ast.Module) -> ScopeChain:
    return ScopeChain([tree])


def walk_with_scopes(tree: ast.Module):
    """Yield ``(node, chain)`` for every AST node, where ``chain`` is
    the ScopeChain of lexical scopes *enclosing* the node (the node's
    own scope included once inside its body). Scope objects are built
    once per function, not per node."""
    base = module_chain(tree)

    def visit(node: ast.AST, chain: ScopeChain):
        for child in ast.iter_child_nodes(node):
            child_chain = chain
            if isinstance(child, _SCOPE_NODES):
                child_chain = chain.push(child)
            yield child, child_chain
            yield from visit(child, child_chain)

    yield tree, base
    yield from visit(tree, base)
