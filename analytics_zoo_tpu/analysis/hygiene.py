"""Checker family 5: exception-handling hygiene.

One rule, born from the crash-observability work: the flight recorder
exists so failures leave evidence, yet several of its own fallback
paths swallowed exceptions with ``except Exception: pass`` -- the one
place evidence-free failure is most corrosive.

``silent-except`` (warning)
    A handler catching ``Exception`` / ``BaseException`` / bare
    ``except:`` whose body is only ``pass`` (or ``...``). Narrow the
    exception type, or at minimum ``logger.debug`` what was swallowed;
    where a handler genuinely cannot log (interpreter teardown),
    suppress inline with a rationale comment. Handlers for *narrow*
    types (``except ValueError: pass``) are deliberate control flow
    and exempt.
"""

from __future__ import annotations

import ast
from typing import Iterable

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, SourceFile, register)

_BROAD = {"Exception", "BaseException"}


def _names_broad(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _BROAD
    if isinstance(node, ast.Attribute):  # builtins.Exception
        return node.attr in _BROAD
    if isinstance(node, ast.Tuple):
        return any(_names_broad(e) for e in node.elts)
    return False


def _body_is_silent(body) -> bool:
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


@register
class HygieneChecker(Checker):
    name = "hygiene"
    rules = {
        "silent-except": "broad 'except Exception:' (or bare except) "
                         "whose body is only pass -- failures vanish "
                         "without evidence",
    }

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or _names_broad(node.type)
            if broad and _body_is_silent(node.body):
                caught = ("bare except" if node.type is None
                          else "except Exception")
                yield Finding(
                    "silent-except", "warning", src.rel, node.lineno,
                    f"{caught}: pass swallows failures without a "
                    "trace; narrow the type, debug-log the error, or "
                    "suppress inline with a rationale")
