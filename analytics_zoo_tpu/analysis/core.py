"""zoolint engine: findings, suppression, checker registry, runner.

Design rules (shared by every checker family):

- **One parse per file.** :class:`SourceFile` owns the text, the line
  table, the AST, and the per-line suppression sets; checkers never
  re-read disk.
- **Stable finding identity.** A finding's baseline key is
  ``(rule, path, message)`` -- messages must therefore name *symbols*
  (class, method, attribute, config key), never line numbers, so the
  baseline survives unrelated edits above the finding.
- **Two checker shapes.** ``check_file`` runs per file (trace hazards,
  concurrency, hygiene); ``check_project`` runs once over the whole
  file set (config drift, vocabulary collisions -- anything whose
  ground truth spans modules).
- **Suppression is local and named.** ``# zoolint: disable=<rule>``
  (comma-separated, or ``all``) on the flagged line or the line above
  silences exactly that rule there; a comment anywhere inside a
  multi-line *simple* statement (a ``shard_map(...)`` call spanning
  six lines) covers the whole statement span. Unexplained global
  ignores don't exist. Grandfathered findings go in the baseline file
  with a rationale instead (analysis.baseline).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SEVERITIES = ("error", "warning", "info")

_SUPPRESS_RE = re.compile(r"#\s*zoolint:\s*disable=([A-Za-z0-9_\-, ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit. ``path`` is root-relative with ``/`` separators;
    ``line`` is 1-based (0 for whole-file/project findings)."""

    rule: str
    severity: str
    path: str
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers excluded on purpose so the
        baseline survives edits elsewhere in the file."""
        return (self.rule, self.path, self.message)

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.rule}] {self.message}")


class SourceFile:
    """One parsed python file: text, lines, AST, suppressions,
    docstring-constant ids (so string scans can skip docs prose)."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._suppress: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self._suppress[i] = rules
        self._span_suppress = self._collect_span_suppressions(self.tree)
        self._docstrings = self._collect_docstrings(self.tree)

    # compound statements own sub-statements with their own spans; only
    # SIMPLE statements (an Assign/Expr holding a multi-line call) get
    # whole-span suppression, so a disable comment inside a 50-line
    # ``if`` body never silences sibling lines. Match/TryStar exist
    # only on newer pythons, hence the getattr defaults.
    _COMPOUND_STMTS = (ast.If, ast.For, ast.AsyncFor, ast.While,
                       ast.With, ast.AsyncWith, ast.Try,
                       ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef,
                       getattr(ast, "Match", ast.Try),
                       getattr(ast, "TryStar", ast.Try))

    def _collect_span_suppressions(self, tree: ast.AST
                                   ) -> Dict[int, Set[str]]:
        """{line: rules} spreading each simple statement's suppression
        comments (plus the line above the statement) over its full
        [lineno, end_lineno] span -- a multi-line ``shard_map(...)``
        call is suppressible no matter which line the finding names."""
        out: Dict[int, Set[str]] = {}
        for node in ast.walk(tree):
            if (not isinstance(node, ast.stmt)
                    or isinstance(node, self._COMPOUND_STMTS)):
                continue
            end = getattr(node, "end_lineno", None) or node.lineno
            if end <= node.lineno:
                continue  # single-line: the plain lookup covers it
            rules: Set[str] = set()
            for ln in range(node.lineno - 1, end + 1):
                rules |= self._suppress.get(ln, set())
            if rules:
                for ln in range(node.lineno, end + 1):
                    out.setdefault(ln, set()).update(rules)
        return out

    @staticmethod
    def _collect_docstrings(tree: ast.AST) -> Set[int]:
        ids: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                body = getattr(node, "body", [])
                if (body and isinstance(body[0], ast.Expr)
                        and isinstance(body[0].value, ast.Constant)
                        and isinstance(body[0].value.value, str)):
                    ids.add(id(body[0].value))
        return ids

    def is_docstring(self, node: ast.AST) -> bool:
        return id(node) in self._docstrings

    def suppressed(self, rule: str, line: int) -> bool:
        """True when the line (or the line directly above it) carries
        ``# zoolint: disable=`` naming this rule or ``all`` -- or when
        the line sits inside a multi-line simple statement any of whose
        lines (or the line above it) does."""
        for ln in (line, line - 1):
            rules = self._suppress.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        rules = self._span_suppress.get(line)
        return bool(rules and (rule in rules or "all" in rules))


class Project:
    """The unit ``check_project`` sees: every parsed file plus the
    repo root (for the docs glossary scan)."""

    def __init__(self, files: Sequence[SourceFile],
                 repo_root: Optional[str] = None):
        self.files = list(files)
        self.repo_root = repo_root
        self._by_rel = {f.rel: f for f in self.files}

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel.replace(os.sep, "/"))

    def docs_text(self) -> str:
        """Concatenated ``docs/*.md`` under the repo root (empty when
        there is no docs tree -- checkers skip doc rules then)."""
        if not self.repo_root:
            return ""
        docs = os.path.join(self.repo_root, "docs")
        if not os.path.isdir(docs):
            return ""
        parts = []
        for name in sorted(os.listdir(docs)):
            if name.endswith(".md"):
                try:
                    with open(os.path.join(docs, name)) as f:
                        parts.append(f.read())
                except OSError:
                    continue
        return "\n".join(parts)


class Checker:
    """Base class. Subclasses set ``name`` (family), ``rules``
    ({rule: one-line description}), and override ``check_file``
    and/or ``check_project``."""

    name: str = ""
    rules: Dict[str, str] = {}

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator adding a Checker to the global registry."""
    if not issubclass(cls, Checker) or not cls.name:
        raise TypeError(f"{cls!r} is not a named Checker")
    _REGISTRY[cls.name] = cls
    return cls


def _load_builtin_checkers() -> None:
    # import for side effect: each module @register-s its checkers
    from analytics_zoo_tpu.analysis import (  # noqa: F401
        concurrency, config_keys, deep_rules, hygiene,
        lifecycle_rules, mesh_rules, protocol, trace_hazards,
        vocabulary)


def all_checkers() -> List[Checker]:
    _load_builtin_checkers()
    return [cls() for _, cls in sorted(_REGISTRY.items())]


def all_rules() -> Dict[str, str]:
    """{rule: description} across every registered family."""
    _load_builtin_checkers()
    out: Dict[str, str] = {}
    for _, cls in sorted(_REGISTRY.items()):
        out.update(cls.rules)
    return out


# ------------------------------------------------------------------ #
# file collection + run                                               #
# ------------------------------------------------------------------ #
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def _find_repo_root(start: str) -> str:
    """Walk up from ``start`` to the first dir holding ``docs/`` or
    ``.git`` (the baseline + glossary anchor); fall back to start."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    probe = cur
    while True:
        if (os.path.isdir(os.path.join(probe, "docs"))
                or os.path.isdir(os.path.join(probe, ".git"))):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return cur
        probe = parent


def collect_files(paths: Sequence[str],
                  repo_root: Optional[str] = None
                  ) -> Tuple[List[SourceFile], str]:
    """Parse every ``.py`` under ``paths``. Returns (files, repo_root);
    ``rel`` paths are relative to the repo root. Unparsable files
    raise -- a lint that skips syntax errors hides the worst finding."""
    if repo_root is None:
        repo_root = _find_repo_root(paths[0] if paths else ".")
    out: List[SourceFile] = []
    seen: Set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            targets = [p]
        else:
            targets = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                targets.extend(os.path.join(dirpath, f)
                               for f in sorted(filenames)
                               if f.endswith(".py"))
        for path in targets:
            if path in seen:
                continue
            seen.add(path)
            rel = os.path.relpath(path, repo_root)
            with open(path) as f:
                out.append(SourceFile(path, rel, f.read()))
    return out, repo_root


def run_zoolint(paths: Sequence[str],
                rules: Optional[Sequence[str]] = None,
                checkers: Optional[Sequence[Checker]] = None,
                repo_root: Optional[str] = None,
                report_only: Optional[Sequence[str]] = None,
                timings: Optional[Dict[str, float]] = None
                ) -> List[Finding]:
    """Run checkers over ``paths``; returns suppression-filtered
    findings sorted by (path, line, rule). ``rules`` restricts to a
    subset; ``checkers`` overrides the registry (unit tests).

    ``report_only`` (absolute file paths) is the ``--changed`` fast
    path: the whole tree is still parsed -- project checkers need the
    cross-module ground truth (``_DEFAULTS``, vocabulary owners) to
    stay sound -- but per-file checkers run only on the listed files
    and every finding outside them is dropped.

    ``timings``, when given a dict, is filled with wall seconds per
    checker family plus a ``"parse"`` entry (the one-parse cost every
    family shares) -- the ``--profile`` surface."""
    t0 = time.perf_counter()
    files, repo_root = collect_files(paths, repo_root=repo_root)
    project = Project(files, repo_root=repo_root)
    if timings is not None:
        timings["parse"] = time.perf_counter() - t0
    only_rel: Optional[Set[str]] = None
    if report_only is not None:
        only_rel = {
            os.path.relpath(os.path.abspath(p),
                            repo_root).replace(os.sep, "/")
            for p in report_only}
    if checkers is None:
        checkers = all_checkers()
    wanted = set(rules) if rules else None
    if wanted is not None:
        # a --rules subset skips whole families, not just their output
        checkers = [c for c in checkers if wanted & set(c.rules)]
    findings: List[Finding] = []
    for checker in checkers:
        t0 = time.perf_counter()
        for src in files:
            if only_rel is not None and src.rel not in only_rel:
                continue
            findings.extend(checker.check_file(src))
        findings.extend(checker.check_project(project))
        if timings is not None:
            timings[checker.name] = (timings.get(checker.name, 0.0)
                                     + time.perf_counter() - t0)
    kept = []
    for f in findings:
        if wanted is not None and f.rule not in wanted:
            continue
        if only_rel is not None and f.path not in only_rel:
            continue
        src = project.file(f.path)
        if src is not None and f.line and src.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept
