"""Path-sensitive resource-lifecycle & exactly-once-reply rules
(zoolint engine #4: "leakcheck").

Built on :mod:`analysis.cfg` (per-function CFGs with implicit
exception edges) and PR 8's call graph: a bounded product walk over
(CFG node, abstract state) proves pairing properties on *every* path
-- the static twin of the serving stack's runtime delivery ledger.

Resource model (the declarative registry :data:`DEFAULT_SPECS`):

- ``acquire`` call names bind a *token* to the assignment target(s)
  (``bind="result"``), to the call's first argument (``bind="arg"``:
  ``ledger.record(uri, ...)`` tracks ``uri``), or to the receiver
  object (``bind="receiver"``: a bare ``lock.acquire()`` statement).
- ``release`` call names settle the token, matched against an
  argument (``release_on="arg"``) or the receiver
  (``release_on="receiver"``: ``t.join()``). Release-name matching
  ignores leading underscores so ``self._settle(uri)`` counts.
- A token *transfers* (ownership leaves the function; no release owed
  here) when it is returned, stored into an attribute or container
  (``self._streams[slot] = stream``), passed to an unresolved call,
  or passed to a resolved callee whose summary stores or returns it.
  Acquire results consumed directly by ``return``, by another call,
  or by a ``with`` item are born transferred/scoped: never tracked.
- Conservative by construction: anything unresolvable (acquire in a
  branch test -- the ``if not lock.acquire(blocking=False)`` idiom --
  conditional results, receivers that are not dotted names, CFG
  overflow) silently drops tracking. Unknown never becomes a finding.

Exactly-once-reply: a module declares its stage methods with a
module-level ``ZOOLINT_REPLY_OBLIGATED = ("Class.method", ...)``
tuple (mirroring deepcheck's ``ZOOLINT_HOT_PATH``). Every declared
method must reach at least one *resolution* -- a reply/error push, a
settle/ack/requeue, or an ownership hand-off into an instance
container -- on every normal-exit path (exception paths are exempt:
the supervisor's crash requeue covers them), and at most one direct
terminal push *site* on any single path. Duplicates are counted per
call site, not per execution: a single push re-fired through a loop
back edge is the per-batch reply loop (one reply per request), while
two distinct push sites on one path mean one request answered twice.
Entering a loop whose body resolves grants resolution: the
zero-iteration path means zero pulled requests, which is vacuously
settled.

Interprocedural (one level plus a small fixpoint): per-function
summaries record which parameters a callee releases or stores away
and whether it pushes/settles; PR 8 call edges apply them at the call
site, so ``self._push_error(uri, ...)`` settles ``uri`` because
``_push_error`` itself calls ``self._settle(uri)``.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.callgraph import (
    CallGraph, FnNode, build_call_graph, own_nodes)
from analytics_zoo_tpu.analysis.cfg import (
    CFG, Node, _NESTED_SCOPES, build_cfg)
from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, SourceFile, register)

__all__ = ["ResourceSpec", "DEFAULT_SPECS", "LifecycleChecker",
           "REPLY_DECL"]

REPLY_DECL = "ZOOLINT_REPLY_OBLIGATED"


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """One acquire/release pairing the engine tracks.

    ``exc_safe``: exception exits never owe a release (an external
    mechanism -- the supervisor requeue -- covers crashes).
    ``strict_release``: releasing twice / releasing unacquired is a
    bug (False for idempotent releases: ledger settle, thread join).
    ``daemon_exempt``: a ctor called with ``daemon=True`` is
    untracked. ``ctor_roots``: dotted acquire calls must hang off one
    of these root names (``threading.Thread``); bare names also match.
    ``receiver_hints``: the acquire receiver's dotted path must
    contain one of these parts (``self.ledger.record``)."""

    name: str
    describe: str
    acquire: Tuple[str, ...]
    release: Tuple[str, ...]
    bind: str = "result"            # result | arg | receiver
    release_on: str = "arg"         # arg | receiver
    receiver_hints: Tuple[str, ...] = ()
    ctor_roots: Optional[Tuple[str, ...]] = None
    daemon_exempt: bool = False
    exc_safe: bool = False
    strict_release: bool = True


DEFAULT_SPECS: Tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="kv-slot",
        describe="KV-cache slot/page reservation",
        acquire=("admit", "reserve", "import_pages", "import_slot"),
        release=("release", "release_pages", "free"),
        bind="result", release_on="arg"),
    ResourceSpec(
        name="kv-handoff",
        describe="exported KV handoff snapshot",
        # the disaggregation contract (ISSUE-20): an exported
        # snapshot must reach import_pages/import_slot (restored
        # here), _encode_handoff (serialized onto the wire for
        # another replica), or _discard_handoff (the named
        # abandonment on an encode-failure path) -- a snapshot
        # that silently reaches none of them is a stream that will
        # never resume anywhere
        acquire=("export_pages", "export_slot"),
        release=("import_pages", "import_slot", "_encode_handoff",
                 "_discard_handoff"),
        bind="result", release_on="arg",
        exc_safe=True, strict_release=False),
    ResourceSpec(
        name="ledger-entry",
        describe="delivery-ledger entry",
        acquire=("record",),
        release=("settle", "ack", "ack_uris"),
        bind="arg", release_on="arg",
        receiver_hints=("ledger",),
        exc_safe=True, strict_release=False),
    ResourceSpec(
        name="lock",
        describe="lock",
        acquire=("acquire",),
        release=("release",),
        bind="receiver", release_on="receiver"),
    ResourceSpec(
        name="thread",
        describe="thread/process",
        acquire=("Thread", "Process"),
        release=("join", "stop", "terminate"),
        bind="result", release_on="receiver",
        ctor_roots=("threading", "multiprocessing", "mp"),
        daemon_exempt=True, strict_release=False),
    ResourceSpec(
        name="warm-scope",
        describe="warming scope",
        acquire=("warming",),
        release=(),
        bind="result"),
)

# terminal reply pushes (exactly-once accounting); _push_chunk counts
# only with an explicit final=True keyword
_PUSH_NAMES = {"_push", "push", "_push_error", "push_error",
               "_reply_error", "reply_error"}
_PUSH_FINAL_NAMES = {"_push_chunk", "push_chunk"}
# settlement verbs (matched after stripping leading underscores)
_SETTLE_NAMES = {"settle", "ack", "ack_uris", "ack_input", "requeue"}
# container hand-off methods on self-rooted receivers
_HANDOFF_METHODS = {"append", "appendleft", "add", "put", "extend"}
# calls that never take ownership of their arguments
_PURE_BUILTINS = {
    "len", "str", "int", "float", "bool", "repr", "min", "max",
    "sorted", "list", "tuple", "dict", "set", "frozenset",
    "isinstance", "issubclass", "getattr", "hasattr", "format",
    "print", "id", "hash", "abs", "sum", "enumerate", "zip", "range",
    "round", "divmod", "type"}
_LOG_ROOTS = {"logger", "logging", "log"}

_CLEANUP_CALL_NAMES = (_SETTLE_NAMES
                       | {n.lstrip("_") for spec in DEFAULT_SPECS
                          for n in spec.release})


# ------------------------------------------------------------------ #
# small AST helpers                                                   #
# ------------------------------------------------------------------ #
def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _dotted(expr: ast.expr) -> Optional[str]:
    """Render a Name/Attribute-of-Names chain ('self._writing'), or
    None when the chain passes through anything else (a call, a
    subscript): those receivers are untrackable."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _attr_root_name(expr: ast.expr) -> Optional[str]:
    node = expr
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _names_in(node: ast.AST) -> Set[str]:
    """Name ids appearing in ``node``, pruning nested scopes."""
    out: Set[str] = set()
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.Name):
            out.add(cur.id)
        for ch in ast.iter_child_nodes(cur):
            if not isinstance(ch, _NESTED_SCOPES):
                stack.append(ch)
    return out


def _target_names(target: ast.expr) -> List[str]:
    """Name ids bound by an assignment target (flattening tuples);
    empty when any element is not a plain Name."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            if not isinstance(e, ast.Name):
                return []
            out.append(e.id)
        return out
    return []


def _kw_is_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name:
            return (isinstance(kw.value, ast.Constant)
                    and kw.value.value is True)
    return False


def _is_push_call(call: ast.Call) -> bool:
    name = _call_name(call)
    if name in _PUSH_NAMES:
        return True
    return name in _PUSH_FINAL_NAMES and _kw_is_true(call, "final")


def _is_settle_call(call: ast.Call) -> bool:
    name = _call_name(call)
    return name is not None and name.lstrip("_") in _SETTLE_NAMES


def _is_handoff_call(call: ast.Call) -> bool:
    """self-rooted container mutation: ``self._inflight.append(rec)``
    -- the record's ownership moved to instance state."""
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in _HANDOFF_METHODS:
        return False
    return _attr_root_name(call.func.value) == "self"


def _lifecycle_may_raise(stmt: ast.stmt,
                         exempt_ids: frozenset = frozenset()) -> bool:
    """Like ``default_may_raise`` but bare cleanup statements --
    every call a registered release/settle verb, or (``exempt_ids``)
    a resolved call into a helper whose summary releases a parameter
    -- are exempt, or the canonical ``except: release(slot); raise``
    handler and the ``self._fail(slot)`` cleanup-helper idiom would
    themselves grow exception edges on which the release has not
    happened."""
    if isinstance(stmt, ast.Assert):
        return True
    calls: List[ast.Call] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.Call):
            calls.append(cur)
        for ch in ast.iter_child_nodes(cur):
            if not isinstance(ch, _NESTED_SCOPES):
                stack.append(ch)
    if not calls:
        return False
    for c in calls:
        if id(c) in exempt_ids:
            continue
        name = _call_name(c)
        if name is None or name.lstrip("_") not in _CLEANUP_CALL_NAMES:
            return True
    return False


def _may_raise_for(fn: FnNode,
                   summaries: Dict[FnNode, "_Summary"]):
    """Per-function ``may_raise`` predicate: the module-wide cleanup
    verbs plus this function's resolved release-helper call sites."""
    exempt = set()
    for e in fn.edges_out:
        cs = summaries.get(e.callee)
        if cs is not None and cs.param_release:
            exempt.add(id(e.call))
    frozen = frozenset(exempt)
    return lambda stmt: _lifecycle_may_raise(stmt, frozen)


def reply_obligated(src: SourceFile) -> Set[Tuple[str, str]]:
    """(class-or-'', name) pairs from a module-level
    ``ZOOLINT_REPLY_OBLIGATED = ("fn", "Class.method")`` tuple."""
    out: Set[Tuple[str, str]] = set()
    for node in src.tree.body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == REPLY_DECL
                and isinstance(node.value, (ast.Tuple, ast.List))):
            for e in node.value.elts:
                if (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)):
                    cls, _, name = e.value.rpartition(".")
                    out.add((cls, name))
    return out


# ------------------------------------------------------------------ #
# interprocedural summaries                                           #
# ------------------------------------------------------------------ #
class _Summary:
    __slots__ = ("param_release", "param_transfer", "terminal",
                 "resolution")

    def __init__(self) -> None:
        self.param_release: Set[str] = set()
        self.param_transfer: Set[str] = set()
        self.terminal = False
        self.resolution = False


_RELEASE_ARG_NAMES = {n.lstrip("_") for spec in DEFAULT_SPECS
                      if spec.release_on == "arg"
                      for n in spec.release}


def _direct_summary(fn: FnNode) -> _Summary:
    s = _Summary()
    params = fn.all_params
    for sub in own_nodes(fn):
        if isinstance(sub, ast.Call):
            name = _call_name(sub)
            if name is None:
                continue
            if _is_push_call(sub):
                s.terminal = True
                s.resolution = True
            if name.lstrip("_") in _SETTLE_NAMES:
                s.resolution = True
            if name.lstrip("_") in _RELEASE_ARG_NAMES:
                for arg in list(sub.args) + [k.value
                                             for k in sub.keywords]:
                    s.param_release |= _names_in(arg) & params
        elif isinstance(sub, ast.Return) and sub.value is not None:
            s.param_transfer |= _names_in(sub.value) & params
        elif isinstance(sub, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in sub.targets):
                s.param_transfer |= _names_in(sub) & params
    s.param_transfer -= s.param_release
    return s


def _build_summaries(graph: CallGraph) -> Dict[FnNode, _Summary]:
    out = {fn: _direct_summary(fn) for fn in graph.nodes}
    for _ in range(3):  # >= 2 interprocedural hops, bounded
        changed = False
        for fn in graph.nodes:
            s = out[fn]
            for edge in fn.edges_out:
                cs = out.get(edge.callee)
                if cs is None:
                    continue
                if cs.terminal and not s.terminal:
                    s.terminal = changed = True
                if cs.resolution and not s.resolution:
                    s.resolution = changed = True
                for pname, aexpr in edge.bindings:
                    if (not isinstance(aexpr, ast.Name)
                            or aexpr.id not in fn.all_params):
                        continue
                    if (pname in cs.param_release
                            and aexpr.id not in s.param_release):
                        s.param_release.add(aexpr.id)
                        changed = True
                    elif (pname in cs.param_transfer
                          and aexpr.id not in s.param_transfer
                          and aexpr.id not in s.param_release):
                        s.param_transfer.add(aexpr.id)
                        changed = True
        if not changed:
            break
    return out


# ------------------------------------------------------------------ #
# per-node event extraction                                           #
# ------------------------------------------------------------------ #
class _Site:
    """One acquire site. ``keys`` are the binding keys (var names, or
    one dotted receiver); empty for an anonymous acquire (a bare
    ``warming()`` statement) -- unreleasable by construction."""

    __slots__ = ("uid", "spec", "keys", "line", "desc")

    def __init__(self, uid: int, spec: ResourceSpec,
                 keys: Tuple[str, ...], line: int, desc: str):
        self.uid = uid
        self.spec = spec
        self.keys = keys
        self.line = line
        self.desc = desc


class _FnCtx:
    """Extraction output for one function: events per CFG node plus
    the site registry the walker consults."""

    def __init__(self, fn: FnNode, obligated: bool,
                 specs: Tuple[ResourceSpec, ...],
                 summaries: Dict[FnNode, _Summary]):
        self.fn = fn
        self.obligated = obligated
        self.specs = specs
        self.summaries = summaries
        self.params = set(fn.all_params)
        self.edges: Dict[int, List] = {}
        for edge in fn.edges_out:
            self.edges.setdefault(id(edge.call), []).append(edge)
        self.sites: Dict[int, _Site] = {}
        self._site_by_call: Dict[int, _Site] = {}
        self.events: Dict[int, Tuple] = {}
        self._stmt_cache: Dict[Tuple[int, str], Tuple] = {}
        self.acquire_keys: Set[str] = set()
        self.released_keys: Set[str] = set()
        self.credit: Set[int] = set()

    def site_for(self, call: ast.Call, spec: ResourceSpec,
                 keys: Tuple[str, ...], desc: str) -> _Site:
        # keyed on the call AST so duplicated finally copies share one
        # site (one finding per source acquire, not per CFG copy)
        site = self._site_by_call.get(id(call))
        if site is None:
            site = _Site(len(self.sites), spec, keys, call.lineno,
                         desc)
            self.sites[site.uid] = site
            self._site_by_call[id(call)] = site
            self.acquire_keys |= set(keys)
        return site


# events: ("acquire", site) | ("release", key, desc, direct) |
# ("transfer", names) | ("kill", names) | ("push", line) |
# ("resolve",)
def _node_events(node: Node, ctx: _FnCtx) -> Tuple:
    stmt = node.stmt
    if stmt is None:
        return ()
    kind = node.kind
    key = (id(stmt), kind)
    cached = ctx._stmt_cache.get(key)
    if cached is not None:
        return cached
    evs: Tuple
    if kind in ("stmt", "raise"):
        evs = _simple_stmt_events(stmt, ctx)
    elif kind == "loop" and isinstance(stmt, (ast.For, ast.AsyncFor)):
        names = _target_names(stmt.target) or sorted(
            _names_in(stmt.target))
        evs = (("kill", tuple(names)),) if names else ()
    elif kind == "with" and isinstance(stmt, (ast.With, ast.AsyncWith)):
        names = [n for it in stmt.items
                 if it.optional_vars is not None
                 for n in _target_names(it.optional_vars)]
        evs = (("kill", tuple(names)),) if names else ()
    elif kind == "except" and isinstance(stmt, ast.ExceptHandler):
        evs = (("kill", (stmt.name,)),) if stmt.name else ()
    else:  # branch tests, finally/with-exit anchors: no effects here
        evs = ()
    ctx._stmt_cache[key] = evs
    for ev in evs:
        if ev[0] == "release":
            ctx.released_keys.add(ev[1])
    return evs


def _collect_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Calls executing in this statement (nested scopes pruned), with
    a stmt-local parent map for context classification."""
    calls: List[ast.Call] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        cur = stack.pop()
        for ch in ast.iter_child_nodes(cur):
            if isinstance(ch, _NESTED_SCOPES):
                continue
            _PARENTS[id(ch)] = cur
            stack.append(ch)
            if isinstance(ch, ast.Call):
                calls.append(ch)
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


_PARENTS: Dict[int, ast.AST] = {}


def _result_bind_keys(call: ast.Call, stmt: ast.stmt
                      ) -> Optional[Tuple[str, ...]]:
    """Where a result-bound acquire's token lives. Returns a key
    tuple; () means anonymous (bare Expr -- a guaranteed leak); None
    means born transferred or scoped (return / call argument / with
    item / anything unresolvable) -- untracked."""
    cur: ast.AST = call
    while True:
        parent = _PARENTS.get(id(cur))
        if parent is None or parent is stmt:
            break
        if isinstance(parent, (ast.Call, ast.Return, ast.withitem)):
            return None
        if not isinstance(parent, ast.Await):
            return None  # tuple literal, boolop, comparison, ...
        cur = parent
    if isinstance(stmt, ast.Assign) and stmt.value in (call, cur):
        keys: List[str] = []
        for t in stmt.targets:
            names = _target_names(t)
            if not names:
                return None  # attribute/subscript target: stored away
            keys.extend(names)
        return tuple(keys)
    if (isinstance(stmt, ast.AnnAssign) and stmt.value in (call, cur)
            and isinstance(stmt.target, ast.Name)):
        return (stmt.target.id,)
    if isinstance(stmt, ast.Expr) and stmt.value in (call, cur):
        return ()
    if isinstance(stmt, ast.Return):
        return None
    return None


def _classify_acquire(call: ast.Call, stmt: ast.stmt, ctx: _FnCtx
                      ) -> Optional[Tuple]:
    """An ("acquire", site) event when some spec matches this call in
    a trackable position, else None."""
    name = _call_name(call)
    if name is None:
        return None
    recv = (_dotted(call.func.value)
            if isinstance(call.func, ast.Attribute) else None)
    for spec in ctx.specs:
        if name not in spec.acquire:
            continue
        if spec.ctor_roots is not None and isinstance(
                call.func, ast.Attribute):
            root = _attr_root_name(call.func.value)
            if root not in spec.ctor_roots:
                continue
        if spec.receiver_hints:
            parts = set(recv.split(".")) if recv else set()
            if not parts & set(spec.receiver_hints):
                continue
        if spec.daemon_exempt and _kw_is_true(call, "daemon"):
            return None
        desc = _dotted(call.func) or name
        if spec.bind == "arg":
            if not (call.args and isinstance(call.args[0], ast.Name)):
                return None
            site = ctx.site_for(call, spec, (call.args[0].id,), desc)
            return ("acquire", site)
        if spec.bind == "receiver":
            if recv is None or not (isinstance(stmt, ast.Expr)
                                    and stmt.value is call):
                return None  # conditional/derived acquire: untracked
            site = ctx.site_for(call, spec, (recv,), desc)
            return ("acquire", site)
        keys = _result_bind_keys(call, stmt)
        if keys is None:
            return None
        site = ctx.site_for(call, spec, keys, desc)
        return ("acquire", site)
    return None


def _simple_stmt_events(stmt: ast.stmt, ctx: _FnCtx) -> Tuple:
    if isinstance(stmt, _NESTED_SCOPES):
        return ()
    releases: List[Tuple] = []
    marks: List[Tuple] = []
    transfers: List[Tuple] = []
    kills: List[Tuple] = []
    acquires: List[Tuple] = []
    for call in _collect_calls(stmt):
        name = _call_name(call)
        if name is None:
            continue
        desc = _dotted(call.func) or name
        if ctx.obligated:
            if _is_push_call(call):
                marks.append(("push", call.lineno))
            elif _is_settle_call(call) or _is_handoff_call(call):
                marks.append(("resolve",))
        acq = _classify_acquire(call, stmt, ctx)
        if acq is not None:
            acquires.append(acq)
            continue
        lname = name.lstrip("_")
        released_here = False
        for spec in ctx.specs:
            if lname not in {n.lstrip("_") for n in spec.release}:
                continue
            if spec.release_on == "receiver":
                recv = (_dotted(call.func.value)
                        if isinstance(call.func, ast.Attribute)
                        else None)
                if recv is not None:
                    releases.append(("release", recv, desc, True))
                    released_here = True
            else:
                for arg in list(call.args) + [k.value
                                              for k in call.keywords]:
                    for nm in sorted(_names_in(arg)):
                        releases.append(("release", nm, desc, True))
                        released_here = True
        edges = ctx.edges.get(id(call))
        if edges:
            resolution = False
            for edge in edges:
                cs = ctx.summaries.get(edge.callee)
                if cs is None:
                    continue
                resolution |= cs.resolution or cs.terminal
                for pname, aexpr in edge.bindings:
                    if not isinstance(aexpr, ast.Name):
                        continue
                    if pname in cs.param_release:
                        releases.append(
                            ("release", aexpr.id, desc, False))
                    elif pname in cs.param_transfer:
                        transfers.append(("transfer", (aexpr.id,)))
            if resolution and ctx.obligated:
                marks.append(("resolve",))
        elif not released_here:
            # unresolved call: conservatively assume it takes
            # ownership of every plain-name argument
            root = (call.func.id if isinstance(call.func, ast.Name)
                    else _attr_root_name(call.func.value))
            if not (isinstance(call.func, ast.Name)
                    and call.func.id in _PURE_BUILTINS
                    ) and root not in _LOG_ROOTS:
                names: Set[str] = set()
                for arg in list(call.args) + [k.value
                                              for k in call.keywords]:
                    names |= _names_in(arg)
                if names:
                    transfers.append(("transfer",
                                      tuple(sorted(names))))
    # statement-level binds/stores
    if isinstance(stmt, ast.Assign):
        plain: List[str] = []
        stored = False
        for t in stmt.targets:
            names = _target_names(t)
            if names:
                plain.extend(names)
            else:
                stored = True
        if stored:
            transfers.append(("transfer",
                              tuple(sorted(_names_in(stmt)))))
            if ctx.obligated and any(
                    isinstance(t, ast.Subscript)
                    and _attr_root_name(t.value) == "self"
                    for t in stmt.targets):
                marks.append(("resolve",))
        if plain:
            kills.append(("kill", tuple(plain)))
    elif isinstance(stmt, ast.AnnAssign):
        if isinstance(stmt.target, ast.Name) and stmt.value is not None:
            kills.append(("kill", (stmt.target.id,)))
    elif isinstance(stmt, ast.AugAssign):
        if isinstance(stmt.target, ast.Name):
            kills.append(("kill", (stmt.target.id,)))
    elif isinstance(stmt, ast.Return):
        if stmt.value is not None:
            names = _names_in(stmt.value)
            if names:
                transfers.append(("transfer", tuple(sorted(names))))
    elif isinstance(stmt, ast.Delete):
        names = [t.id for t in stmt.targets
                 if isinstance(t, ast.Name)]
        if names:
            kills.append(("kill", tuple(names)))
    return tuple(releases + marks + transfers + kills + acquires)


# ------------------------------------------------------------------ #
# the product walk: (CFG node, abstract state)                        #
# ------------------------------------------------------------------ #
# binds: ((key, uid-or-None), ...)  None = known-rebound tombstone
# status: ((uid, "H"|"R"|"T"), ...)  pushes: sorted tuple of distinct
# push call-site lines hit on this path (capped at 2 -- beyond that
# the verdict is already settled); pending: 0 none / 1 implicit
# exception in flight / 2 explicit raise in flight
_St = collections.namedtuple(
    "_St", ("binds", "status", "pushes", "resolved", "pending"))

_STATE_CAP = 80_000


def _frz(d: Dict) -> Tuple:
    return tuple(sorted(d.items()))


def _label(site: _Site) -> str:
    return ", ".join(site.keys)


def _strict_key(ctx: _FnCtx, key: str) -> bool:
    return any(key in s.keys and s.spec.strict_release
               for s in ctx.sites.values())


def _leak_finding(ctx: _FnCtx, site: _Site, phrase: str, where: str,
                  rel: str) -> Finding:
    spec = site.spec
    if not site.keys:
        msg = (f"{where}: the {site.desc}() result is discarded -- "
               f"the {spec.describe} can never close; use "
               f"`with {site.desc}():` (or bind and release it)")
    else:
        rel_desc = "/".join(spec.release) or "a with-scope"
        msg = (f"{where}: {spec.describe} '{_label(site)}' acquired "
               f"via {site.desc}() can leave the function on "
               f"{phrase} without {rel_desc} or an ownership "
               "transfer; release it on every path (try/except -> "
               "release + re-raise, or a finally block)")
    return Finding("leak-on-path", "error", rel, site.line, msg)


def _apply(ctx: _FnCtx, node: Node, st: "_St", where: str, rel: str,
           out: Dict) -> "_St":
    evs = ctx.events.get(node.idx)
    if not evs:
        return st
    binds = dict(st.binds)
    status = dict(st.status)
    pushes, resolved = st.pushes, st.resolved
    for ev in evs:
        k = ev[0]
        if k == "release":
            key = ev[1]
            if key in binds:
                uid = binds[key]
                if uid is None:
                    continue
                site = ctx.sites[uid]
                c = status.get(uid)
                if c == "H":
                    status[uid] = "R"
                elif c == "R" and site.spec.strict_release:
                    out.setdefault(("double", uid, node.line), Finding(
                        "double-release", "error", rel, node.line,
                        f"{where}: {site.spec.describe} "
                        f"'{_label(site)}' from {site.desc}() is "
                        "released more than once on a single path -- "
                        "a second release can free a resource "
                        "re-acquired by a concurrent request; make "
                        "one site own the release"))
            elif ev[3]:  # direct release of a never-bound key
                if (key in ctx.acquire_keys
                        and key not in ctx.params
                        and _strict_key(ctx, key)):
                    out.setdefault(("unacq", key, node.line), Finding(
                        "release-unacquired", "error", rel, node.line,
                        f"{where}: '{key}' is released on a path "
                        "where no acquire bound it (the acquire is "
                        "conditional or on another branch); guard "
                        "the release with the same condition"))
        elif k == "transfer":
            for nm in ev[1]:
                uid = binds.get(nm)
                if uid is not None and status.get(uid) == "H":
                    status[uid] = "T"
        elif k == "kill":
            for nm in ev[1]:
                if nm in binds:
                    binds[nm] = None
        elif k == "acquire":
            site = ev[1]
            for key in site.keys:
                binds[key] = site.uid
            status[site.uid] = "H"
        elif k == "push":
            resolved = True
            # per-SITE, not per-execution: the same site re-fired via
            # a loop back edge is the per-batch reply loop, not a
            # duplicate reply for one request
            if ev[1] not in pushes and len(pushes) < 2:
                pushes = tuple(sorted(pushes + (ev[1],)))
                if len(pushes) == 2:
                    out.setdefault(("dup", node.line), Finding(
                        "reply-duplicated-on-path", "error", rel,
                        node.line,
                        f"{where}: two distinct terminal reply "
                        "pushes can both fire for one request on a "
                        "single path -- consumers would see a "
                        "duplicate; make exactly one reachable "
                        "(exactly-once contract)"))
        else:  # resolve
            resolved = True
    return _St(_frz(binds), _frz(status), pushes, resolved,
               st.pending)


def _finalize(ctx: _FnCtx, st: "_St", exceptional: bool,
              prev_line: int, where: str, rel: str,
              out: Dict) -> None:
    for uid, c in st.status:
        if c != "H":
            continue
        site = ctx.sites[uid]
        spec = site.spec
        if exceptional:
            if spec.exc_safe:
                continue
            implicit = st.pending != 2
            has_release = any(k in ctx.released_keys
                              for k in site.keys)
            if implicit and has_release:
                out.setdefault(("cleanup", uid), Finding(
                    "cleanup-not-in-finally", "warning", rel,
                    site.line,
                    f"{where}: the release of {spec.describe} "
                    f"'{_label(site)}' (acquired via {site.desc}()) "
                    "runs only on the fall-through path -- an "
                    "exception between the acquire and the release "
                    "skips it; move the release into a finally "
                    "block, or a try/except that releases and "
                    "re-raises"))
            else:
                key = ("leak", uid, "anon" if not site.keys
                       else "exc")
                out.setdefault(key, _leak_finding(
                    ctx, site, "an exception path", where, rel))
        else:
            key = ("leak", uid, "anon" if not site.keys else "norm")
            out.setdefault(key, _leak_finding(
                ctx, site, "an early-return or fall-through path",
                where, rel))
    if ctx.obligated and not exceptional and not st.resolved:
        out.setdefault(("missing", prev_line), Finding(
            "reply-missing-on-path", "error", rel, prev_line,
            f"{where}: a pulled request can reach a normal return "
            "with no reply, error-reply, requeue, or ownership "
            "hand-off on that path -- the exactly-once contract "
            "requires each path to resolve the request exactly once "
            "(suppress with a rationale only for intentional drops)"))


def _walk(ctx: _FnCtx, cfg: CFG, rel: str, out: Dict) -> None:
    fn = ctx.fn
    where = (f"{fn.cls_name}.{fn.name}" if fn.cls_name else fn.name)
    init = _St((), (), (), False, 0)
    seen: Set[Tuple] = set()
    stack = [(cfg.entry, init, getattr(fn.node, "lineno", 0))]
    while stack:
        node, st, prev_line = stack.pop()
        mkey = (node.idx, st)
        if mkey in seen:
            continue
        seen.add(mkey)
        if len(seen) > _STATE_CAP:
            return  # bail out; findings discovered so far stand
        kind = node.kind
        if kind == "exit":
            _finalize(ctx, st, False, prev_line, where, rel, out)
            continue
        if kind == "raise-exit":
            _finalize(ctx, st, True, prev_line, where, rel, out)
            continue
        if kind == "except" and st.pending:
            st = st._replace(pending=0)  # the handler caught it
        post = _apply(ctx, node, st, where, rel, out)
        if (kind == "loop" and node.idx in ctx.credit
                and ctx.obligated and not post.resolved):
            # zero iterations = zero pulled requests: vacuously
            # settled, so entering a resolving loop grants resolution
            post = post._replace(resolved=True)
        line = node.line or prev_line
        for succ, label in node.succ:
            if label == "mayraise":
                # effects have NOT happened on an implicit edge
                nxt = st if st.pending else st._replace(pending=1)
            elif label == "raise":
                nxt = st._replace(pending=2)
            else:  # next/true/false/back/return/break/case/exc
                nxt = post
            stack.append((succ, nxt, line))


# ------------------------------------------------------------------ #
# checker                                                             #
# ------------------------------------------------------------------ #
def _walk_pruned(node: ast.AST) -> Iterable[ast.AST]:
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for ch in ast.iter_child_nodes(cur):
            if not isinstance(ch, _NESTED_SCOPES):
                stack.append(ch)


@register
class LifecycleChecker(Checker):
    """Engine #4: path-sensitive pairing over per-function CFGs."""

    name = "lifecycle"
    rules = {
        "leak-on-path": "an acquired resource (KV slot, ledger "
                        "entry, lock, thread, warming scope) escapes "
                        "on some path without release or ownership "
                        "transfer",
        "double-release": "a resource is released twice along a "
                          "single path",
        "release-unacquired": "a release runs on a path where its "
                              "acquire never did",
        "cleanup-not-in-finally": "happy-path-only cleanup: an "
                                  "exception edge skips the release",
        "reply-missing-on-path": "a ZOOLINT_REPLY_OBLIGATED stage "
                                 "method can return without "
                                 "resolving the pulled request",
        "reply-duplicated-on-path": "a stage method can push two "
                                    "terminal replies on one path",
    }

    def __init__(self, specs: Optional[Iterable[ResourceSpec]] = None):
        self.specs: Tuple[ResourceSpec, ...] = (
            tuple(specs) if specs is not None else DEFAULT_SPECS)
        self._acq_names = {n for s in self.specs for n in s.acquire}

    # ------------------------------------------------------ driver --
    def check_project(self, project: Project) -> Iterable[Finding]:
        graph = build_call_graph(project)
        summaries = _build_summaries(graph)
        decls: Dict[str, Set[Tuple[str, str]]] = {}
        findings: List[Finding] = []
        for fn in graph.nodes:
            rel = fn.src.rel
            if rel not in decls:
                decls[rel] = reply_obligated(fn.src)
            obligated = (fn.cls_name or "", fn.name) in decls[rel]
            if not obligated and not self._prescan(fn):
                continue
            cfg = build_cfg(fn.node,
                            may_raise=_may_raise_for(fn, summaries))
            if cfg is None:
                continue  # overflow: no knowledge, never a finding
            ctx = _FnCtx(fn, obligated, self.specs, summaries)
            for node in cfg.nodes:
                ctx.events[node.idx] = _node_events(node, ctx)
            self._loop_credit(cfg, ctx)
            out: Dict[Tuple, Finding] = {}
            _walk(ctx, cfg, rel, out)
            for uid in ctx.sites:
                # a site leaking on a normal path also leaks on its
                # exception paths; one finding carries the fix
                if ("leak", uid, "norm") in out:
                    out.pop(("leak", uid, "exc"), None)
            findings.extend(out.values())
        return findings

    def _prescan(self, fn: FnNode) -> bool:
        """Only functions that acquire anything get a CFG built."""
        for sub in own_nodes(fn):
            if (isinstance(sub, ast.Call)
                    and _call_name(sub) in self._acq_names):
                return True
        return False

    @staticmethod
    def _loop_credit(cfg: CFG, ctx: _FnCtx) -> None:
        if not ctx.obligated:
            return
        for node in cfg.nodes:
            if node.kind != "loop" or node.idx in ctx.credit:
                continue
            for s in getattr(node.stmt, "body", []):
                for sub in _walk_pruned(s):
                    if isinstance(sub, ast.Call) and (
                            _is_push_call(sub) or _is_settle_call(sub)
                            or _is_handoff_call(sub)):
                        ctx.credit.add(node.idx)
                        break
                    if (isinstance(sub, ast.Assign) and any(
                            isinstance(t, ast.Subscript)
                            and _attr_root_name(t.value) == "self"
                            for t in sub.targets)):
                        ctx.credit.add(node.idx)
                        break
                if node.idx in ctx.credit:
                    break
