"""Checker family 4: metric-name + event-type vocabulary enforcement.

The naming rules themselves live where they always did -- the
``zoo_<subsystem>_<name>_<unit>`` convention in
``obs.metrics.check_metric_name`` and the ``EVENT_TYPES`` registry in
``obs.events`` -- this checker is the *scanner* half, migrated from
the hand-rolled walkers in ``tests/test_metric_names.py`` so every
naming rule reports through one framework (same suppression, same
baseline, same CLI). The test file remains as thin wrappers over
:func:`collect_registrations` / :func:`collect_emissions`, keeping
its assertions alive.

``metric-name`` (error)
    A literal registry registration (``<reg>.counter/gauge/
    histogram("...")``) whose name breaks the convention.

``metric-collision`` (error)
    One metric family registered from more than one module: help
    text, labels, and ownership fragment. Register once, import the
    family object.

``event-type`` (error)
    A literal ``emit("<type>", ...)`` whose type is not
    lower_snake_case or not registered in ``obs.events.EVENT_TYPES``.

``event-vocab-module`` (error)
    ``EVENT_TYPES`` assigned outside ``obs/events.py`` -- a second
    vocabulary module would fragment the event namespace exactly the
    way cross-module metric registration fragments families.

Registry-receiver heuristic (unchanged from the test it replaces): a
bare name containing ``reg`` or a direct ``get_registry().x(...)``
chain counts; the per-instance Timer API (``self.timer.gauge``) does
not -- sampled local stats are not registry families.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    Checker, Finding, Project, register)

_REGISTER_METHODS = ("counter", "gauge", "histogram")
_EVENTS_REL_SUFFIX = "obs/events.py"


def _is_registry_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return "reg" in node.id.lower()
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id == "get_registry"
    return False


def _is_emit_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in ("emit", "emit_event")
    if isinstance(func, ast.Attribute):
        return func.attr == "emit"
    return False


def collect_registrations(project: Project
                          ) -> List[Tuple[str, str, str, int]]:
    """(module_rel, kind, name, line) for every literal-name registry
    registration in the project."""
    found = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTER_METHODS
                    and _is_registry_receiver(node.func.value)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                found.append((src.rel, node.func.attr,
                              node.args[0].value, node.lineno))
    return found


def collect_emissions(project: Project
                      ) -> List[Tuple[str, str, int]]:
    """(module_rel, event_type, line) for every literal-type emit call
    in the project."""
    found = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call) and _is_emit_call(node)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                found.append((src.rel, node.args[0].value,
                              node.lineno))
    return found


def collect_vocab_owners(project: Project) -> List[Tuple[str, int]]:
    """(module_rel, line) for every module assigning EVENT_TYPES."""
    owners = []
    for src in project.files:
        for node in ast.walk(src.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "EVENT_TYPES":
                    owners.append((src.rel, node.lineno))
    return owners


@register
class VocabularyChecker(Checker):
    name = "vocabulary"
    rules = {
        "metric-name": "registered metric name breaks the "
                       "zoo_<subsystem>_<name>_<unit> convention",
        "metric-collision": "one metric family registered from "
                            "multiple modules",
        "event-type": "emitted event type not lower_snake_case or "
                      "not registered in obs.events.EVENT_TYPES",
        "event-vocab-module": "EVENT_TYPES assigned outside "
                              "obs/events.py (one vocabulary module)",
    }

    def check_project(self, project: Project) -> Iterable[Finding]:
        # the conventions live in obs; import lazily so the engine
        # itself stays importable in stripped-down fixture runs
        from analytics_zoo_tpu.obs.events import check_event_type
        from analytics_zoo_tpu.obs.metrics import check_metric_name

        regs = collect_registrations(project)
        for rel, kind, mname, line in regs:
            try:
                check_metric_name(mname, kind)
            except ValueError as e:
                yield Finding("metric-name", "error", rel, line,
                              str(e))
        owners: Dict[str, Set[str]] = {}
        first_site: Dict[str, Tuple[str, int]] = {}
        for rel, _kind, mname, line in regs:
            owners.setdefault(mname, set()).add(rel)
            first_site.setdefault(mname, (rel, line))
        for mname, mods in sorted(owners.items()):
            if len(mods) > 1:
                rel, line = first_site[mname]
                yield Finding(
                    "metric-collision", "error", rel, line,
                    f"metric family '{mname}' registered from "
                    f"{len(mods)} modules ({', '.join(sorted(mods))});"
                    " move the registration to one owner and import "
                    "the family")

        for rel, etype, line in collect_emissions(project):
            try:
                check_event_type(etype)
            except ValueError as e:
                yield Finding("event-type", "error", rel, line,
                              str(e))

        for rel, line in collect_vocab_owners(project):
            if not rel.endswith(_EVENTS_REL_SUFFIX):
                yield Finding(
                    "event-vocab-module", "error", rel, line,
                    "EVENT_TYPES assigned outside obs/events.py; the "
                    "event vocabulary has exactly one home (use "
                    "obs.events.register_event_type to extend it)")
