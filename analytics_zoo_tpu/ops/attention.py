"""Attention dispatch: Pallas flash kernels on TPU, jnp reference on CPU.

Replaces the reference's O(L^2)-materialized attention
(ref: zoo/.../keras/layers/TransformerLayer.scala attn -- builds the full
[B, H, L, L] score matrix through BigDL ops). On TPU the flash kernels
never materialize scores in HBM:

- head_dim % 64 == 0 -> the framework's own Pallas kernel
  (``pallas_attention.pallas_flash_attention_fwd``, exact custom_vjp;
  covers BERT-base head_dim 64 since r5);
- otherwise -> the stock fused fwd+bwd kernel, which also serves
  key-padding masks (lowered to segment ids).

The jnp reference path handles CPU, arbitrary 4-D masks, and attention
dropout (flash kernels don't support prob dropout -- same trade-off every
flash implementation makes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def reference_attention(q, k, v, mask=None, causal: bool = False,
                        scale: Optional[float] = None):
    """Exact jnp attention; the single source of truth the Pallas kernels
    are tested against and the custom_vjp backward recomputes through."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _einsum_attention(q, k, v, mask=None, causal: bool = False,
                      scale: Optional[float] = None):
    """MXU-shaped exact attention: scores accumulate in f32 (softmax
    numerics), probabilities drop back to the value dtype so the PV
    matmul rides the fast bf16 MXU path instead of a full-precision
    one. Same math as ``reference_attention`` (golden-tested against
    it); this is the variant the dispatcher uses."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _platform(q) -> str:
    try:
        dev = q.devices() if hasattr(q, "devices") else None
        return list(dev)[0].platform if dev else jax.default_backend()
    except Exception:
        return jax.default_backend()


def dot_product_attention(q, k, v, mask=None, key_padding_mask=None,
                          causal: bool = False,
                          scale: Optional[float] = None,
                          dropout_rate: float = 0.0, dropout_rng=None):
    """q,k,v: [B, H, L, D]. ``mask``: arbitrary [B, H, Lq, Lk]-broadcastable
    (1 = attend; forces the jnp path). ``key_padding_mask``: [B, Lk] with
    1 = real token -- flash-compatible (lowered to segment ids).
    Returns [B, H, Lq, D]."""
    d = q.shape[-1]
    l, lk = q.shape[2], k.shape[2]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    if causal and l > lk:
        # with the bottom-right-aligned diagonal the first lq-lk rows
        # attend to nothing; every backend would return garbage for them
        raise ValueError("causal attention requires len(q) <= len(kv)")

    from analytics_zoo_tpu.common.config import get_config

    cfg = get_config()
    impl = cfg.get("zoo.ops.attention_impl")
    if impl == "auto" and max(l, lk) <= int(
            cfg.get("zoo.ops.attention_flash_min_seq")):
        # short sequences: the [L, L] scores are small enough that
        # XLA's fused batched-matmul attention beats the blockwise
        # kernels (measured ~2x on v5e at BERT-base L=384/d=64)
        impl = "einsum"
    flash_ok = (impl != "einsum"
                and mask is None and dropout_rate == 0.0
                and _platform(q) == "tpu"
                and l % 128 == 0 and lk % 128 == 0
                and not (causal and l > lk))
    if flash_ok and d % 64 == 0:
        from analytics_zoo_tpu.ops.pallas_attention import (
            pallas_flash_attention_fwd)

        if key_padding_mask is None:
            return pallas_flash_attention_fwd(q, k, v, causal, scale)
        # padding masks fall through to the stock kernel's segment ids
    # the stock kernel's causal mask is top-left aligned (no cross-length
    # offset), so it only agrees with reference_attention when lq == lk
    if flash_ok and d <= 128 and (not causal or l == lk):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            SegmentIds, flash_attention)

        seg = None
        if key_padding_mask is not None:
            kv_seg = key_padding_mask.astype(jnp.int32)
            q_seg = (kv_seg if lk == l
                     else jnp.ones((q.shape[0], l), jnp.int32))
            seg = SegmentIds(q=q_seg, kv=kv_seg)
        return flash_attention(q, k, v, segment_ids=seg, causal=causal,
                               sm_scale=scale)

    if key_padding_mask is not None:
        pm = key_padding_mask[:, None, None, :].astype(bool)
        mask = pm if mask is None else (mask.astype(bool) & pm)
    if dropout_rate == 0.0:
        return _einsum_attention(q, k, v, mask=mask, causal=causal,
                                 scale=scale)
    if dropout_rate > 0.0 and dropout_rng is not None:
        # dropout needs the materialized probs; inline the reference math
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            cm = jnp.tril(jnp.ones((l, lk), bool), k=lk - l)
            logits = jnp.where(cm[None, None], logits, NEG_INF)
        if mask is not None:
            logits = jnp.where(mask.astype(bool), logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return reference_attention(q, k, v, mask=mask, causal=causal,
                               scale=scale)
