"""Hand-written Pallas TPU flash-attention (forward) kernel.

The fused attention hot op for inference and the building block the
framework owns end-to-end (training additionally uses the stock fused
fwd+bwd kernel via ``ops.attention``). Blockwise online-softmax: the grid
walks (batch*heads, q-blocks, kv-blocks) with the kv dimension innermost;
running (max, sum, acc) live in VMEM scratch across kv iterations, so the
[L, L] score matrix never exists in HBM.

Gradients: wrapped in ``custom_vjp`` whose backward recomputes through
the jnp reference path (exact; flash backward kernel is future work).

Constraints: seq % block == 0, head_dim % 128 == 0 (MXU lane tiling);
callers fall back to the jnp path otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                      causal: bool, scale: float, block_q: int,
                      block_k: int, causal_offset: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip fully-masked kv blocks under causal masking
    run = True if not causal else (ki * block_k <= qi * block_q +
                                   (block_q - 1) + causal_offset)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k = k_ref[0].astype(jnp.float32)          # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            # diagonal aligned bottom-right like the jnp reference path
            # (reference_attention tril with k=lk-lq), so cross-length
            # q/kv gives identical results on both dispatch paths
            q_pos = qi * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_scr[:, :1]                     # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                    # [BQ, BK]
        corr = jnp.exp(m_prev - m_new)            # [BQ, 1]
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[...] = acc

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, scale: float, block_q: int,
               block_k: int):
    b, h, l, d = q.shape
    lk = k.shape[2]
    if l % block_q or lk % block_k:
        raise ValueError(f"seq lens ({l},{lk}) must divide blocks "
                         f"({block_q},{block_k})")
    if d % 128:
        raise ValueError(f"head_dim {d} must be a multiple of 128")
    if causal and l > lk:
        # rows attending to nothing are undefined under flash semantics
        raise ValueError("causal attention requires len(q) <= len(kv)")
    qr = q.reshape(b * h, l, d)
    kr = k.reshape(b * h, lk, d)
    vr = v.reshape(b * h, lk, d)
    grid = (b * h, l // block_q, lk // block_k)
    # interpret mode runs the kernel logic on CPU (tests); compiled on TPU
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          causal_offset=lk - l),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, l, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, l, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def pallas_flash_attention_fwd(q, k, v, causal: bool = False,
                               scale: Optional[float] = None,
                               block_q: int = 128, block_k: int = 128):
    """Flash attention on [B, H, L, D]; exact softmax attention."""
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)


def _vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out = pallas_flash_attention_fwd(q, k, v, causal, scale, block_q,
                                     block_k)
    return out, (q, k, v)


def _vjp_bwd(causal, scale, block_q, block_k, res, g):
    from analytics_zoo_tpu.ops.attention import reference_attention

    q, k, v = res
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    _, vjp = jax.vjp(
        lambda a, b, c: reference_attention(a, b, c, causal=causal,
                                            scale=s).astype(a.dtype),
        q, k, v)
    return vjp(g)


pallas_flash_attention_fwd.defvjp(_vjp_fwd, _vjp_bwd)
