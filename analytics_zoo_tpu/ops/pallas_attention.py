"""Hand-written Pallas TPU flash-attention kernels (forward + backward).

The fused attention hot op the framework owns end-to-end. Blockwise
online-softmax forward: the grid walks (batch*heads, q-blocks, kv-blocks)
with the kv dimension innermost; running (max, sum, acc) live in VMEM
scratch across kv iterations, so the [L, L] score matrix never exists in
HBM. The forward also emits the per-row logsumexp, which the backward
kernels use to regenerate probabilities blockwise:

- dQ kernel: grid (BH, q-blocks, kv-blocks), accumulates
  dq_i = sum_j (p_ij * (do_i v_j^T - delta_i)) k_j in VMEM scratch;
- dK/dV kernel: grid (BH, kv-blocks, q-blocks), accumulates
  dv_j = sum_i p_ij^T do_i and dk_j = sum_i ds_ij^T q_i.

Training memory is O(L) on this kernel (saves only q, k, v, o, lse) --
the flash backward recurrence of Dao et al., re-derived for the TPU
memory hierarchy. Replaces the reference's O(L^2)-materialized attention
(ref: zoo/.../keras/layers/TransformerLayer.scala attn).

Constraints: seq % block == 0, head_dim % 64 == 0 (64 keeps the MXU at
half lane-width on the QK/PV contractions -- the same geometry every
d=64 attention pays, incl. XLA's einsum -- while 128-multiples ride it
full); callers fall back to the jnp path otherwise. Causal masking
aligns the diagonal bottom-right (tril k=lk-lq) to match
``reference_attention``; causal with len(q) > len(kv) is rejected.

The grid is declared (parallel, parallel, arbitrary) so Mosaic
pipelines the sequential kv/q accumulation dimension while batch and
row blocks schedule freely.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _auto_block(length: int, cap: int = 1024) -> int:
    """Largest 128-multiple block <= ``cap`` dividing ``length``: big
    blocks amortize the per-block VPU softmax work against the MXU
    matmuls (measured ~2.5x fwd+bwd at L=4096 vs 128-blocks) while
    staying inside VMEM (s/p tiles at [1024, 1024] f32 = 4 MB each).

    The backward kernels pass ``_bwd_cap``: 512 at d >= 128 -- they
    hold three [BQ, BK] f32 intermediates (s, p, dp) plus
    q/k/v/do/lse/delta tiles and scratch, which at 1024^2 blocks
    (~12 MB of intermediates alone) would crowd the ~16 MB per-core
    VMEM budget -- but 1024 at d <= 64 / L >= 2048, where the halved
    tiles fit and measure 6-7% faster (see _bwd_cap)."""
    for b in (1024, 896, 768, 640, 512, 384, 256, 128):
        if b <= cap and length % b == 0:
            return b
    return 128


def _causal_run(qi, ki, block_q: int, block_k: int, causal: bool,
                offset: int):
    """Whether kv-block ki overlaps the causal region of q-block qi."""
    if not causal:
        return True
    return ki * block_k <= qi * block_q + (block_q - 1) + offset


def _causal_mask(s, qi, ki, block_q: int, block_k: int, offset: int):
    """Mask scores above the bottom-right-aligned diagonal
    (reference_attention tril with k=lk-lq), so cross-length q/kv gives
    identical results on every dispatch path."""
    q_pos = qi * block_q + offset + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, causal: bool,
                      scale: float, block_q: int, block_k: int,
                      causal_offset: int, with_lse: bool):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref, (m_scr, l_scr, acc_scr) = None, rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = _causal_run(qi, ki, block_q, block_k, causal, causal_offset)

    @pl.when(run)
    def _body():
        # matmul operands stay in input dtype (bf16 rides the fast MXU
        # path; f32 accumulate via preferred_element_type) -- upcasting
        # here would silently fall to the slow full-precision MXU mode
        q = q_ref[0]                              # [BQ, D]
        k = k_ref[0]                              # [BK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, causal_offset)

        m_prev = m_scr[:, :1]                     # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                    # [BQ, BK]
        corr = jnp.exp(m_prev - m_new)            # [BQ, 1]
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[...] = acc

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if with_lse:
            lse_ref[0] = (m_scr[...] + jnp.log(l)).astype(lse_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, scale: float, block_q: int,
               block_k: int, with_lse: bool):
    """Returns out [B,H,L,D] and, when ``with_lse``, the per-row
    logsumexp at [B*H, L, 128] (value broadcast across the 128 lanes --
    the TPU-native row-stat layout the stock flash kernel also uses;
    inference passes ``with_lse=False`` so nothing extra hits HBM)."""
    b, h, l, d = q.shape
    lk = k.shape[2]
    block_q = block_q or _auto_block(l)
    block_k = block_k or _auto_block(lk)
    if l % block_q or lk % block_k:
        raise ValueError(f"seq lens ({l},{lk}) must divide blocks "
                         f"({block_q},{block_k})")
    if d % 64:
        raise ValueError(f"head_dim {d} must be a multiple of 64")
    if causal and l > lk:
        # rows attending to nothing are undefined under flash semantics
        raise ValueError("causal attention requires len(q) <= len(kv)")
    qr = q.reshape(b * h, l, d)
    kr = k.reshape(b * h, lk, d)
    vr = v.reshape(b * h, lk, d)
    grid = (b * h, l // block_q, lk // block_k)
    out_specs = [pl.BlockSpec((1, block_q, d),
                              lambda bh, qi, ki: (bh, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, l, d), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, block_q, 128),
                                      lambda bh, qi, ki: (bh, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b * h, l, 128),
                                              jnp.float32))
    res = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          causal_offset=lk - l, with_lse=with_lse),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_grid_semantics(),
        interpret=_interpret(),
    )(qr, kr, vr)
    out = res[0]
    lse = res[1] if with_lse else None
    return out.reshape(b, h, l, d), lse


def _interpret() -> bool:
    # interpret mode runs the kernel logic on CPU (tests); compiled on TPU
    return jax.default_backend() != "tpu"


def _grid_semantics():
    """All three kernels iterate their LAST grid dim sequentially (the
    online-softmax / gradient accumulation over kv- or q-blocks) while
    the leading (batch*heads, row-block) dims are independent; telling
    Mosaic so lets it overlap the next block's HBM->VMEM copies with
    the current block's compute instead of assuming a serial grid."""
    if _interpret():
        return None  # interpret mode takes no TPU compiler params
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dq_scr, *, causal: bool, scale: float,
                     block_q: int, block_k: int, causal_offset: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = _causal_run(qi, ki, block_q, block_k, causal, causal_offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]                                # [BQ, D]
        k = k_ref[0]                                # [BK, D]
        v = v_ref[0]
        do = do_ref[0]                              # [BQ, D]
        lse = lse_ref[0][:, :1]                     # [BQ, 1]
        delta = delta_ref[0][:, :1]                 # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, causal_offset)
        p = jnp.exp(s - lse)                        # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [BQ, BK]
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                      scale: float, block_q: int, block_k: int,
                      causal_offset: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = _causal_run(qi, ki, block_q, block_k, causal, causal_offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]                                # [BQ, D]
        k = k_ref[0]                                # [BK, D]
        v = v_ref[0]
        do = do_ref[0]                              # [BQ, D]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, causal_offset)
        p = jnp.exp(s - lse)                        # [BQ, BK]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [BQ, BK]
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [BK, D]

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_cap(length: int, d: int) -> int:
    """Backward block cap: 512 keeps the three [BQ, BK] f32
    intermediates inside VMEM at d=128; at d <= 64 every q/k/v/do tile
    halves, so 1024-blocks fit AND measure 6-7% faster at L >= 2048
    (scripts/perf_flash_blocks.py) -- but only when the sequential
    grid dim keeps >= 2 steps, else Mosaic has nothing to pipeline
    and L=1024 regresses ~25%."""
    return 1024 if (d <= 64 and length >= 2048) else 512


def _flash_bwd(q, k, v, o, lse, g, causal: bool, scale: float,
               block_q: int, block_k: int):
    b, h, l, d = q.shape
    lk = k.shape[2]
    block_q = block_q or _auto_block(l, cap=_bwd_cap(l, d))
    block_k = block_k or _auto_block(lk, cap=_bwd_cap(lk, d))
    bh = b * h
    qr = q.reshape(bh, l, d)
    kr = k.reshape(bh, lk, d)
    vr = v.reshape(bh, lk, d)
    dor = g.reshape(bh, l, d)
    # delta_i = rowsum(do_i * o_i): one fused elementwise pass, O(L*D)
    delta = jnp.sum(dor.astype(jnp.float32) *
                    o.reshape(bh, l, d).astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = jnp.broadcast_to(delta, (bh, l, 128))
    common = dict(causal=causal, scale=scale, block_q=block_q,
                  block_k=block_k, causal_offset=lk - l)
    q_spec = pl.BlockSpec((1, block_q, d), lambda bh_, a, b_: (bh_, a, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh_, a, b_: (bh_, b_, 0))
    row_spec = pl.BlockSpec((1, block_q, 128),
                            lambda bh_, a, b_: (bh_, a, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, **common),
        grid=(bh, l // block_q, lk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh_, a, b_: (bh_, a, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, l, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_grid_semantics(),
        interpret=_interpret(),
    )(qr, kr, vr, dor, lse, delta)

    # dk/dv walk kv-blocks in the outer grid dim with q innermost; the
    # index maps swap (a, b_) roles relative to the dq kernel
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda bh_, a, b_: (bh_, b_, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda bh_, a, b_: (bh_, a, 0))
    row_spec2 = pl.BlockSpec((1, block_q, 128),
                             lambda bh_, a, b_: (bh_, b_, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, **common),
        grid=(bh, lk // block_k, l // block_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2,
                  row_spec2],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, a, b_: (bh_, a, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, a, b_: (bh_, a, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_grid_semantics(),
        interpret=_interpret(),
    )(qr, kr, vr, dor, lse, delta)
    return (dq.reshape(b, h, l, d), dk.reshape(b, h, lk, d),
            dv.reshape(b, h, lk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def pallas_flash_attention_fwd(q, k, v, causal: bool = False,
                               scale: Optional[float] = None,
                               block_q: Optional[int] = None,
                               block_k: Optional[int] = None):
    """Flash attention on [B, H, L, D]; exact softmax attention.
    ``block_q``/``block_k`` default to the largest 128-multiple divisor
    of each sequence length, capped at 1024."""
    out, _ = _flash_fwd(q, k, v, causal, _resolve_scale(scale, q),
                        block_q, block_k, with_lse=False)
    return out


def _resolve_scale(scale, q) -> float:
    return scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])


def _vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    s = _resolve_scale(scale, q)
    out, lse = _flash_fwd(q, k, v, causal, s, block_q, block_k,
                          with_lse=True)
    return out, (q, k, v, out, lse, s)


def _vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse, s = res
    return _flash_bwd(q, k, v, out, lse, g, causal, s, block_q, block_k)


pallas_flash_attention_fwd.defvjp(_vjp_fwd, _vjp_bwd)
