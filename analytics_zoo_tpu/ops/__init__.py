"""TPU kernels (Pallas) + attention dispatch.

The analog of the reference's native compute layer: where BigDL calls
MKL/MKL-DNN kernels behind every module (SURVEY.md section 2.4), the hot
ops here are Pallas TPU kernels with jnp fallbacks for CPU tracing/tests.
"""

from analytics_zoo_tpu.ops.attention import (  # noqa: F401
    dot_product_attention,
    reference_attention,
)
from analytics_zoo_tpu.ops.pallas_attention import (  # noqa: F401
    pallas_flash_attention_fwd,
)
