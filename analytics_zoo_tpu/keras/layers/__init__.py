"""Keras layer library (ref: zoo/.../pipeline/api/keras/layers -- 120
layer files; re-exported here by family)."""

from analytics_zoo_tpu.keras.layers.core import (  # noqa: F401
    Activation,
    Dense,
    Dropout,
    Flatten,
    GaussianDropout,
    GaussianNoise,
    GaussianSampler,
    Highway,
    InputLayer,
    Lambda,
    Masking,
    MaxoutDense,
    Permute,
    RepeatVector,
    Reshape,
    SpatialDropout1D,
    SpatialDropout2D,
    SpatialDropout3D,
    SReLU,
)
from analytics_zoo_tpu.keras.layers.convolutional import (  # noqa: F401
    AtrousConvolution1D,
    AtrousConvolution2D,
    Convolution1D,
    Convolution2D,
    Convolution3D,
    Cropping1D,
    Cropping2D,
    Cropping3D,
    Deconvolution2D,
    LocallyConnected1D,
    LocallyConnected2D,
    ResizeBilinear,
    SeparableConvolution2D,
    UpSampling1D,
    UpSampling2D,
    UpSampling3D,
    ZeroPadding1D,
    ZeroPadding2D,
    ZeroPadding3D,
)
from analytics_zoo_tpu.keras.layers.pooling import (  # noqa: F401
    AveragePooling1D,
    AveragePooling2D,
    AveragePooling3D,
    GlobalAveragePooling1D,
    GlobalAveragePooling2D,
    GlobalAveragePooling3D,
    GlobalMaxPooling1D,
    GlobalMaxPooling2D,
    GlobalMaxPooling3D,
    MaxPooling1D,
    MaxPooling2D,
    MaxPooling3D,
)
from analytics_zoo_tpu.keras.layers.normalization import (  # noqa: F401
    BatchNormalization,
    LayerNormalization,
    LRN2D,
)
from analytics_zoo_tpu.keras.layers.embedding import (  # noqa: F401
    Embedding,
    SparseDense,
    SparseEmbedding,
    WordEmbedding,
)
from analytics_zoo_tpu.keras.layers.recurrent import (  # noqa: F401
    GRU,
    LSTM,
    Bidirectional,
    ConvLSTM2D,
    ConvLSTM3D,
    SimpleRNN,
    TimeDistributed,
)
from analytics_zoo_tpu.keras.layers.merge import (  # noqa: F401
    Merge,
    average,
    concatenate,
    dot,
    maximum,
    multiply,
)
from analytics_zoo_tpu.keras.layers.merge import add as merge_add  # noqa: F401
from analytics_zoo_tpu.keras.layers.advanced_activations import (  # noqa: F401
    ELU,
    LeakyReLU,
    PReLU,
    ThresholdedReLU,
)
from analytics_zoo_tpu.keras.layers.transformer import (  # noqa: F401
    BERT,
    BERTModule,
    TransformerBlock,
    TransformerLayer,
    TransformerModule,
)
