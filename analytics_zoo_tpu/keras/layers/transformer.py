"""Transformer and BERT layers.

The analog of ``TransformerLayer.scala`` (GPT-style decoder stack) and
``BERT.scala`` (ref: zoo/.../keras/layers/{TransformerLayer,BERT}.scala),
re-designed TPU-first: attention goes through ``ops.attention`` (Pallas
flash kernel on TPU, never materializing the [L, L] score matrix the
reference builds), all matmuls MXU-shaped, gelu fused by XLA.

North-star workload #4 (BERT-base fine-tune) builds on BERT here.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.layers.base import KerasLayer
from analytics_zoo_tpu.ops.attention import dot_product_attention

_zigzag_shape_warned = False


def _warn_zigzag_shape_once(l, seq_size):
    global _zigzag_shape_warned
    if not _zigzag_shape_warned:
        _zigzag_shape_warned = True
        from analytics_zoo_tpu.common.log import get_logger

        get_logger(__name__).warning(
            "ring_schedule=zigzag requested but seq_len %d is not "
            "divisible by 2*seq_axis_size (%d); falling back to the "
            "contiguous causal ring (~2x more attention compute)",
            l, 2 * seq_size)


class MultiHeadSelfAttention(nn.Module):
    """``seq_axis``: name of a mesh axis to shard the sequence over --
    when set (and the context mesh has that axis with size > 1 and no
    explicit mask), attention runs as exact ring attention over the
    axis (``parallel.ring_attention``), giving long-context sequence
    parallelism inside any model built on this layer; attention-prob
    dropout applies tile-wise inside the ring. Otherwise dispatches to
    the flash/jnp kernels."""

    hidden_size: int
    n_head: int
    attn_dropout: float = 0.0
    causal: bool = False
    dtype: Any = jnp.float32  # compute dtype; params stay fp32
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, key_padding_mask=None,
                 train: bool = False):
        b, l, _ = x.shape
        hd = self.hidden_size // self.n_head
        # fused projection with kernel [H, 3, H]: one MXU matmul, and
        # the q/k/v sections sit on their own axis so tensor-parallel
        # sharding of the last dim stays head-aligned (megatron layout;
        # a flat [H, 3H] kernel puts tp shard boundaries across the
        # q|k|v concatenation)
        qkv = nn.DenseGeneral((3, self.hidden_size), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]

        out = None
        if (self.seq_axis is not None and mask is None
                and key_padding_mask is None):
            from analytics_zoo_tpu.parallel.mesh import (
                default_mesh, mesh_axis_size)
            from analytics_zoo_tpu.parallel.ring_attention import (
                ring_attention)

            mesh = default_mesh()
            seq_size = mesh_axis_size(mesh, self.seq_axis)
            data_size = mesh_axis_size(
                mesh, "data") if "data" in mesh.axis_names else 1
            # shard_map preconditions: both sharded dims must divide --
            # fall back to the dense path like the mask/dropout cases
            if seq_size > 1 and l % seq_size == 0 and b % data_size == 0:
                ring_rng = (self.make_rng("dropout")
                            if train and self.attn_dropout > 0 else None)
                # ring layout [B, L, H, D]; shard_map nests inside the
                # outer jit and reshards q/k/v along the seq axis.
                # Prob-dropout applies tile-wise inside the ring (exact;
                # see ring_attention's numerator-only masking). Causal
                # stacks take the zigzag schedule when shapes divide:
                # same exact softmax, ~2x less compute (ring_schedule
                # config: auto|zigzag|contiguous)
                from analytics_zoo_tpu.common.config import get_config
                from analytics_zoo_tpu.parallel.ring_attention import (
                    zigzag_ring_attention)

                schedule = get_config().get("zoo.ops.ring_schedule")
                if schedule not in ("auto", "zigzag", "contiguous"):
                    raise ValueError(
                        f"zoo.ops.ring_schedule must be auto|zigzag|"
                        f"contiguous, got {schedule!r}")
                divides = l % (2 * seq_size) == 0
                if schedule == "zigzag" and self.causal and not divides:
                    _warn_zigzag_shape_once(l, seq_size)
                use_zigzag = (self.causal
                              and schedule in ("auto", "zigzag")
                              and divides)
                ring_fn = (zigzag_ring_attention if use_zigzag
                           else partial(ring_attention,
                                        causal=self.causal))
                out = ring_fn(
                    q.reshape(b, l, self.n_head, hd),
                    k.reshape(b, l, self.n_head, hd),
                    v.reshape(b, l, self.n_head, hd),
                    mesh, axis_name=self.seq_axis,
                    dropout_rate=self.attn_dropout if train else 0.0,
                    dropout_rng=ring_rng,
                ).reshape(b, l, self.hidden_size)
        if out is None:
            def heads(t):
                return t.reshape(b, l, self.n_head,
                                 hd).transpose(0, 2, 1, 3)

            rng = (self.make_rng("dropout")
                   if train and self.attn_dropout > 0 else None)
            out = dot_product_attention(
                heads(q), heads(k), heads(v), mask=mask,
                key_padding_mask=key_padding_mask, causal=self.causal,
                dropout_rate=self.attn_dropout if train else 0.0,
                dropout_rng=rng)
            out = out.transpose(0, 2, 1, 3).reshape(b, l,
                                                    self.hidden_size)
        return nn.Dense(self.hidden_size, dtype=self.dtype,
                        name="proj")(out)


class TransformerBlock(nn.Module):
    """Pre/post-LN encoder-or-decoder block (the reference uses post-LN,
    ref: TransformerLayer.scala block)."""

    hidden_size: int
    n_head: int
    intermediate_size: int
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    causal: bool = False
    activation: str = "gelu"
    ln_eps: float = 1e-5
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, key_padding_mask=None,
                 train: bool = False):
        # "gelu" keeps the tanh approximation (GPT lineage + saved
        # checkpoints); "gelu_exact" is the erf form BERT/torch use --
        # the two diverge ~1e-3, so each model family pins its own
        if self.activation == "gelu_exact":
            act = lambda t: jax.nn.gelu(t, approximate=False)  # noqa: E731
        elif self.activation == "gelu":
            act = jax.nn.gelu
        else:
            act = jax.nn.relu
        attn = MultiHeadSelfAttention(
            self.hidden_size, self.n_head, attn_dropout=self.attn_dropout,
            causal=self.causal, dtype=self.dtype,
            seq_axis=self.seq_axis, name="attention")(
                x, mask=mask, key_padding_mask=key_padding_mask,
                train=train)
        attn = nn.Dropout(self.hidden_dropout,
                          deterministic=not train)(attn)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         name="ln_attn")(x + attn)
        h = nn.Dense(self.intermediate_size, dtype=self.dtype,
                     name="ffn_in")(x)
        h = act(h)
        h = nn.Dense(self.hidden_size, dtype=self.dtype,
                     name="ffn_out")(h)
        h = nn.Dropout(self.hidden_dropout, deterministic=not train)(h)
        return nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                            name="ln_ffn")(x + h)


class TransformerModule(nn.Module):
    """GPT-style decoder stack over token ids
    (ref: TransformerLayer.scala)."""

    vocab: int
    seq_len: int
    hidden_size: int = 768
    n_head: int = 12
    n_block: int = 12
    intermediate_size: Optional[int] = None
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    output_all_block: bool = False
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        ids = x.astype(jnp.int32)
        b, l = ids.shape
        tok = nn.Embed(self.vocab, self.hidden_size, name="token_embed")(ids)
        pos = self.param("position_embed",
                         nn.initializers.normal(0.01),
                         (self.seq_len, self.hidden_size))
        h = tok + pos[None, :l]
        h = nn.Dropout(self.hidden_dropout, deterministic=not train)(h)
        outs = []
        inter = self.intermediate_size or 4 * self.hidden_size
        for i in range(self.n_block):
            h = TransformerBlock(
                self.hidden_size, self.n_head, inter,
                hidden_dropout=self.hidden_dropout,
                attn_dropout=self.attn_dropout, causal=True,
                dtype=self.dtype, seq_axis=self.seq_axis,
                name=f"block_{i}")(h, train=train)
            outs.append(h)
        return tuple(outs) if self.output_all_block else h


class BERTModule(nn.Module):
    """BERT encoder (ref: BERT.scala): token + position + segment
    embeddings, post-LN encoder blocks, tanh pooler over [CLS].

    Input: dict with ``input_ids`` [B, L]; optional ``token_type_ids``
    [B, L] and ``attention_mask`` [B, L] (1 = real token).
    Returns (sequence_output [B, L, H], pooled_output [B, H]).
    """

    vocab: int
    hidden_size: int = 768
    n_block: int = 12
    n_head: int = 12
    intermediate_size: int = 3072
    max_position_len: int = 512
    type_vocab: int = 2
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        if isinstance(x, dict):
            ids = x["input_ids"].astype(jnp.int32)
            segs = x.get("token_type_ids")
            attn_mask = x.get("attention_mask")
        else:
            ids, segs, attn_mask = x.astype(jnp.int32), None, None
        b, l = ids.shape
        h = nn.Embed(self.vocab, self.hidden_size, name="token_embed")(ids)
        pos = self.param("position_embed", nn.initializers.normal(0.02),
                         (self.max_position_len, self.hidden_size))
        h = h + pos[None, :l]
        if segs is not None:
            h = h + nn.Embed(self.type_vocab, self.hidden_size,
                             name="segment_embed")(segs.astype(jnp.int32))
        h = nn.LayerNorm(epsilon=1e-12, name="embed_ln")(h)
        h = nn.Dropout(self.hidden_dropout, deterministic=not train)(h)

        # padding mask stays [B, L]: flash-kernel-compatible (lowered to
        # segment ids) instead of a materialized 4-D mask
        for i in range(self.n_block):
            h = TransformerBlock(
                self.hidden_size, self.n_head, self.intermediate_size,
                hidden_dropout=self.hidden_dropout,
                attn_dropout=self.attn_dropout, causal=False,
                activation="gelu_exact", ln_eps=1e-12,
                dtype=self.dtype, seq_axis=self.seq_axis,
                name=f"encoder_{i}")(h, key_padding_mask=attn_mask,
                                     train=train)
        pooled = jnp.tanh(nn.Dense(self.hidden_size, name="pooler")
                          (h[:, 0]))
        return h, pooled


class TransformerLayerKL(KerasLayer):
    """Keras-layer wrapper for the decoder stack
    (ref: TransformerLayer.scala companion object init)."""

    def __init__(self, vocab: int, seq_len: int, hidden_size: int = 768,
                 n_head: int = 12, n_block: int = 12, **kwargs):
        extra = {k: kwargs.pop(k) for k in list(kwargs)
                 if k in ("intermediate_size", "hidden_dropout",
                          "attn_dropout", "output_all_block")}
        super().__init__(**kwargs)
        self._cfg = dict(vocab=vocab, seq_len=seq_len,
                         hidden_size=hidden_size, n_head=n_head,
                         n_block=n_block, **extra)

    def _make_module(self):
        return TransformerModule(**self._cfg)


class BERTKL(KerasLayer):
    """Keras-layer wrapper for BERT (ref: BERT.scala companion init)."""

    def __init__(self, vocab: int, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12,
                 intermediate_size: int = 3072,
                 max_position_len: int = 512, **kwargs):
        extra = {k: kwargs.pop(k) for k in list(kwargs)
                 if k in ("type_vocab", "hidden_dropout", "attn_dropout")}
        super().__init__(**kwargs)
        self._cfg = dict(vocab=vocab, hidden_size=hidden_size,
                         n_block=n_block, n_head=n_head,
                         intermediate_size=intermediate_size,
                         max_position_len=max_position_len, **extra)

    def _make_module(self):
        return BERTModule(**self._cfg)


# public names matching the reference layer files
TransformerLayer = TransformerLayerKL
BERT = BERTKL
