"""Torch-style elementwise / shape / threshold layers.

The reference's Keras library carries a band of thin torch-lineage
layers (ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/
keras/layers/{AddConstant,MulConstant,CAdd,CMul,Mul,Scale,Exp,Log,Sqrt,
Square,Power,Negative,Identity,Expand,ExpandDim,Squeeze,Select,Narrow,
Max,Threshold,BinaryThreshold,HardShrink,SoftShrink,HardTanh,RReLU,
Softmax,LayerNorm,GetShape,WithinChannelLRN2D,ShareConvolution2D}.scala
-- each wraps the matching BigDL module). Here they are jnp one-liners
(XLA fuses them away) or small parameterized flax modules; parameters
follow the reference semantics (CAdd/CMul/Scale learn, the rest don't).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer
from analytics_zoo_tpu.keras.layers.normalization import (
    LayerNormalization as _LayerNormalization)

__all__ = [
    "AddConstant", "MulConstant", "CAdd", "CMul", "Mul", "Scale",
    "Exp", "Log", "Sqrt", "Square", "Power", "Negative", "Identity",
    "Expand", "ExpandDim", "Squeeze", "Select", "Narrow", "Max",
    "Threshold", "BinaryThreshold", "HardShrink", "SoftShrink",
    "HardTanh", "RReLU", "Softmax", "LayerNorm", "GetShape",
    "WithinChannelLRN2D", "ShareConvolution2D",
]


def _axis(dim: int, ndim: int) -> int:
    """Reference layers count dims EXCLUDING batch; negative dims count
    from the end. Out-of-range dims raise rather than silently landing
    on the batch axis."""
    if dim >= ndim - 1 or dim < -(ndim - 1):
        raise ValueError(f"dim {dim} out of range for {ndim - 1} "
                         "non-batch dims")
    return dim % ndim if dim < 0 else dim + 1


def _expand_axis(dim: int, ndim: int) -> int:
    """Like ``_axis`` but the insertion point may sit one past the last
    existing non-batch dim."""
    if dim > ndim - 1 or dim < -ndim:
        raise ValueError(f"dim {dim} out of range to insert into "
                         f"{ndim - 1} non-batch dims")
    return dim % (ndim + 1) if dim < 0 else dim + 1


class _FnLayer(KerasLayer):
    """KerasLayer over a pure function of the input."""

    def _fn(self, x):
        raise NotImplementedError

    def _make_module(self):
        return FnModule(fn=self._fn)


# ------------------------------------------------------- const math --
class AddConstant(_FnLayer):
    """x + c (ref: AddConstant.scala)."""

    def __init__(self, constant: float, **kwargs):
        super().__init__(**kwargs)
        self.constant = float(constant)

    def _fn(self, x):
        return x + self.constant


class MulConstant(_FnLayer):
    """x * c (ref: MulConstant.scala)."""

    def __init__(self, constant: float, **kwargs):
        super().__init__(**kwargs)
        self.constant = float(constant)

    def _fn(self, x):
        return x * self.constant


class Exp(_FnLayer):
    def _fn(self, x):
        return jnp.exp(x)


class Log(_FnLayer):
    def _fn(self, x):
        return jnp.log(x)


class Sqrt(_FnLayer):
    def _fn(self, x):
        return jnp.sqrt(x)


class Square(_FnLayer):
    def _fn(self, x):
        return jnp.square(x)


class Power(_FnLayer):
    """(shift + scale * x) ** power (ref: Power.scala semantics)."""

    def __init__(self, power: float, scale: float = 1.0,
                 shift: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.power, self.scale, self.shift = power, scale, shift

    def _fn(self, x):
        return (self.shift + self.scale * x) ** self.power


class Negative(_FnLayer):
    def _fn(self, x):
        return -x


class Identity(_FnLayer):
    def _fn(self, x):
        return x


# -------------------------------------------------- learned scaling --
class _CAddModule(nn.Module):
    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = self.param("bias", nn.initializers.zeros, self.shape)
        return x + b


class CAdd(KerasLayer):
    """Learned per-element bias of the given shape, broadcast onto the
    input (ref: CAdd.scala)."""

    def __init__(self, shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.shape = tuple(int(s) for s in shape)

    def _make_module(self):
        return _CAddModule(shape=self.shape)


class _CMulModule(nn.Module):
    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.param("weight", nn.initializers.ones, self.shape)
        return x * w


class CMul(KerasLayer):
    """Learned per-element scale (ref: CMul.scala)."""

    def __init__(self, shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.shape = tuple(int(s) for s in shape)

    def _make_module(self):
        return _CMulModule(shape=self.shape)


class _MulModule(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.param("weight", nn.initializers.ones, ())
        return x * w


class Mul(KerasLayer):
    """Single learned scalar multiplier (ref: Mul.scala)."""

    def _make_module(self):
        return _MulModule()


class _ScaleModule(nn.Module):
    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.param("weight", nn.initializers.ones, self.shape)
        b = self.param("bias", nn.initializers.zeros, self.shape)
        return x * w + b


class Scale(KerasLayer):
    """Learned affine x*w + b of the given broadcast shape
    (ref: Scale.scala = CMul then CAdd)."""

    def __init__(self, shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.shape = tuple(int(s) for s in shape)

    def _make_module(self):
        return _ScaleModule(shape=self.shape)


# ------------------------------------------------------- shape ops --
class Expand(_FnLayer):
    """Broadcast size-1 dims to the target shape (batch dim excluded;
    ref: Expand.scala / InternalExpand)."""

    def __init__(self, shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.shape = tuple(int(s) for s in shape)

    def _fn(self, x):
        return jnp.broadcast_to(x, (x.shape[0],) + self.shape)


class ExpandDim(_FnLayer):
    """Insert a size-1 axis (ref: ExpandDim.scala); ``dim`` counts
    non-batch axes like the reference."""

    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = int(dim)

    def _fn(self, x):
        return jnp.expand_dims(x, _expand_axis(self.dim, x.ndim))


class Squeeze(_FnLayer):
    """Drop size-1 axes (ref: Squeeze.scala); ``dim`` non-batch."""

    def __init__(self, dim: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def _fn(self, x):
        if self.dim is None:
            keep = tuple(i for i, s in enumerate(x.shape)
                         if i == 0 or s != 1)
            return x.reshape(tuple(x.shape[i] for i in keep))
        return jnp.squeeze(x, _axis(self.dim, x.ndim))


class Select(_FnLayer):
    """Index one slice along a non-batch dim (ref: Select.scala)."""

    def __init__(self, dim: int, index: int, **kwargs):
        super().__init__(**kwargs)
        self.dim, self.index = int(dim), int(index)

    def _fn(self, x):
        return jnp.take(x, self.index, axis=_axis(self.dim, x.ndim))


class Narrow(_FnLayer):
    """Slice ``length`` elements from ``offset`` along a non-batch dim
    (ref: Narrow.scala)."""

    def __init__(self, dim: int, offset: int, length: int = 1, **kwargs):
        super().__init__(**kwargs)
        self.dim, self.offset, self.length = int(dim), int(offset), \
            int(length)

    def _fn(self, x):
        return jax.lax.slice_in_dim(x, self.offset,
                                    self.offset + self.length,
                                    axis=_axis(self.dim, x.ndim))


class Max(_FnLayer):
    """Max over a non-batch dim (ref: Max.scala / InternalMax)."""

    def __init__(self, dim: int, keepdims: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.dim, self.keepdims = int(dim), keepdims

    def _fn(self, x):
        return jnp.max(x, axis=_axis(self.dim, x.ndim),
                       keepdims=self.keepdims)


class GetShape(_FnLayer):
    """The input's (static) shape, one row PER SAMPLE [B, ndim]
    (ref: GetShape.scala returns the bare shape; the per-row form is
    what survives predict's chunked batching -- a rank-1 result would
    concatenate wrongly across batches)."""

    def _fn(self, x):
        shape = jnp.asarray(x.shape, jnp.int32)
        return jnp.broadcast_to(shape, (x.shape[0], len(x.shape)))


# ----------------------------------------------- threshold family --
class Threshold(_FnLayer):
    """x if x > th else value (ref: Threshold.scala)."""

    def __init__(self, th: float = 1e-6, value: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.th, self.value = th, value

    def _fn(self, x):
        return jnp.where(x > self.th, x, self.value)


class BinaryThreshold(_FnLayer):
    """1 where x > th else 0 (ref: BinaryThreshold.scala)."""

    def __init__(self, th: float = 1e-6, **kwargs):
        super().__init__(**kwargs)
        self.th = th

    def _fn(self, x):
        return (x > self.th).astype(jnp.float32)


class HardShrink(_FnLayer):
    """0 inside [-lambda, lambda] (ref: HardShrink.scala)."""

    def __init__(self, value: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.value = value

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.value, x, 0.0)


class SoftShrink(_FnLayer):
    """Shrink toward zero by lambda (ref: SoftShrink.scala)."""

    def __init__(self, value: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.value = value

    def _fn(self, x):
        return (jnp.where(x > self.value, x - self.value, 0.0)
                + jnp.where(x < -self.value, x + self.value, 0.0))


class HardTanh(_FnLayer):
    """Clip to [min_value, max_value] (ref: HardTanh.scala)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class _RReLUModule(nn.Module):
    lower: float
    upper: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        if train:
            rng = self.make_rng("dropout")
            slope = jax.random.uniform(rng, x.shape, x.dtype,
                                       self.lower, self.upper)
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, x * slope)


class RReLU(KerasLayer):
    """Randomized leaky ReLU: slope ~ U[lower, upper] in training,
    the mean slope at inference (ref: RReLU.scala)."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 **kwargs):
        super().__init__(**kwargs)
        self.lower, self.upper = lower, upper

    def _make_module(self):
        return _RReLUModule(lower=self.lower, upper=self.upper)


class Softmax(_FnLayer):
    """Softmax over the last dim (ref: Softmax.scala)."""

    def _fn(self, x):
        return jax.nn.softmax(x, axis=-1)


class LayerNorm(_LayerNormalization):
    """Last-dim layer normalization with learned scale/bias
    (ref: LayerNorm.scala / InternalLayerNorm) -- the torch-style
    (eps) spelling of :class:`LayerNormalization`."""

    def __init__(self, eps: float = 1e-5, **kwargs):
        # the reference exposes (nOutput, eps); nOutput is inferred here
        kwargs.pop("n_output", None)
        super().__init__(epsilon=eps, **kwargs)


# ------------------------------------------------------ conv extras --
class WithinChannelLRN2D(_FnLayer):
    """Local response normalization pooled WITHIN each channel over a
    spatial window (ref: WithinChannelLRN2D.scala; channels-last)."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, **kwargs):
        super().__init__(**kwargs)
        self.size, self.alpha, self.beta = size, alpha, beta

    def _fn(self, x):
        sq = jnp.square(x)
        window = (1, self.size, self.size, 1)
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, window, (1, 1, 1, 1), "SAME")
        count = jax.lax.reduce_window(
            jnp.ones_like(sq), 0.0, jax.lax.add, window, (1, 1, 1, 1),
            "SAME")
        denom = (1.0 + self.alpha * summed / count) ** self.beta
        return x / denom


class ShareConvolution2D(KerasLayer):
    """API-parity alias of Convolution2D: under SPMD there is one
    weight copy by construction, which is exactly what BigDL's
    ShareConvolution provided (shared storage across replicas,
    ref: ShareConvolution2D.scala)."""

    def __new__(cls, *args, **kwargs):
        from analytics_zoo_tpu.keras.layers.convolutional import (
            Convolution2D)

        return Convolution2D(*args, **kwargs)
