"""Pooling layers (ref: zoo/.../keras/layers/{MaxPooling*,AveragePooling*,
GlobalMaxPooling*,GlobalAveragePooling*}.scala). Channels-last layouts."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer
from analytics_zoo_tpu.keras.layers.convolutional import _tup


def _pool_layer(rank, op):
    class _Pool(KerasLayer):
        def __init__(self, pool_size=2, strides=None,
                     border_mode: str = "valid", **kwargs):
            super().__init__(**kwargs)
            self.pool_size = _tup(pool_size, rank)
            self.strides = (_tup(strides, rank) if strides is not None
                            else self.pool_size)
            self.border_mode = border_mode.upper()

        def _make_module(self):
            ps, st, pad = self.pool_size, self.strides, self.border_mode
            if op == "max":
                fn = lambda x: nn.max_pool(x, ps, strides=st, padding=pad)
            else:
                fn = lambda x: nn.avg_pool(x, ps, strides=st, padding=pad)
            return FnModule(fn=fn)

    return _Pool


MaxPooling1D = _pool_layer(1, "max")
MaxPooling1D.__name__ = "MaxPooling1D"
MaxPooling2D = _pool_layer(2, "max")
MaxPooling2D.__name__ = "MaxPooling2D"
MaxPooling3D = _pool_layer(3, "max")
MaxPooling3D.__name__ = "MaxPooling3D"
AveragePooling1D = _pool_layer(1, "avg")
AveragePooling1D.__name__ = "AveragePooling1D"
AveragePooling2D = _pool_layer(2, "avg")
AveragePooling2D.__name__ = "AveragePooling2D"
AveragePooling3D = _pool_layer(3, "avg")
AveragePooling3D.__name__ = "AveragePooling3D"


def _global_pool_layer(rank, op):
    class _GlobalPool(KerasLayer):
        def _make_module(self):
            axes = tuple(range(1, rank + 1))
            if op == "max":
                return FnModule(fn=lambda x: jnp.max(x, axis=axes))
            return FnModule(fn=lambda x: jnp.mean(x, axis=axes))

    return _GlobalPool


GlobalMaxPooling1D = _global_pool_layer(1, "max")
GlobalMaxPooling1D.__name__ = "GlobalMaxPooling1D"
GlobalMaxPooling2D = _global_pool_layer(2, "max")
GlobalMaxPooling2D.__name__ = "GlobalMaxPooling2D"
GlobalMaxPooling3D = _global_pool_layer(3, "max")
GlobalMaxPooling3D.__name__ = "GlobalMaxPooling3D"
GlobalAveragePooling1D = _global_pool_layer(1, "avg")
GlobalAveragePooling1D.__name__ = "GlobalAveragePooling1D"
GlobalAveragePooling2D = _global_pool_layer(2, "avg")
GlobalAveragePooling2D.__name__ = "GlobalAveragePooling2D"
GlobalAveragePooling3D = _global_pool_layer(3, "avg")
GlobalAveragePooling3D.__name__ = "GlobalAveragePooling3D"
