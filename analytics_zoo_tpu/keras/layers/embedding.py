"""Embedding layers (ref: zoo/.../keras/layers/{Embedding,WordEmbedding,
SparseEmbedding}.scala)."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.layers.base import KerasLayer


class _EmbedModule(nn.Module):
    vocab: int
    dim: int
    init_weights: Optional[tuple] = None  # (np array wrapped) or None
    trainable: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.init_weights is not None:
            w = self.init_weights[0]
            init = lambda *_: jnp.asarray(w)
        else:
            init = nn.initializers.uniform(scale=0.05)
        table = self.param("embedding", init, (self.vocab, self.dim))
        if not self.trainable:
            table = jax.lax.stop_gradient(table)
        return jnp.take(table, x.astype(jnp.int32), axis=0)


class Embedding(KerasLayer):
    """(ref: keras/layers/Embedding.scala). ids in [0, input_dim)."""

    def __init__(self, input_dim: int, output_dim: int, weights=None,
                 trainable: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.weights = weights
        self.trainable = trainable

    def _make_module(self):
        init = None
        if self.weights is not None:
            w = np.asarray(self.weights, np.float32)
            if w.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"weights shape {w.shape} != "
                    f"{(self.input_dim, self.output_dim)}")
            init = (w,)
        return _EmbedModule(vocab=self.input_dim, dim=self.output_dim,
                            init_weights=init, trainable=self.trainable)


class WordEmbedding(Embedding):
    """Pretrained word vectors, frozen by default
    (ref: keras/layers/WordEmbedding.scala -- loads GloVe; here the
    embedding matrix is passed directly or via ``from_glove``)."""

    def __init__(self, input_dim: int, output_dim: int, weights=None,
                 trainable: bool = False, **kwargs):
        super().__init__(input_dim, output_dim, weights=weights,
                         trainable=trainable, **kwargs)

    @staticmethod
    def from_glove(path: str, word_index: dict, trainable: bool = False
                   ) -> "WordEmbedding":
        """Build from a GloVe text file restricted to ``word_index``
        (word -> id, ids in [1, n]; id 0 is the padding row)."""
        dim = None
        vectors = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                word = parts[0]
                if word in word_index:
                    vec = np.asarray(parts[1:], np.float32)
                    dim = len(vec)
                    vectors[word] = vec
        if dim is None:
            raise ValueError(f"no words of word_index found in {path!r}")
        n = max(word_index.values()) + 1
        table = np.zeros((n, dim), np.float32)
        for w, i in word_index.items():
            if w in vectors:
                table[i] = vectors[w]
        return WordEmbedding(n, dim, weights=table, trainable=trainable)


class _SparseEmbedModule(nn.Module):
    vocab: int
    dim: int
    combiner: str

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: [B, K] int ids padded with 0; id 0 is the "no entry" slot.
        # The reference feeds SparseTensor rows (SparseEmbedding.scala);
        # the TPU-native encoding is padded dense ids + mask -- the
        # gather rides the MXU-adjacent sparsecore/gather units and the
        # pad rows contribute exactly zero.
        ids = x.astype(jnp.int32)
        table = nn.Embed(self.vocab + 1, self.dim, name="embedding")
        emb = table(ids)                               # [B, K, D]
        mask = (ids > 0).astype(emb.dtype)[..., None]  # [B, K, 1]
        summed = jnp.sum(emb * mask, axis=-2)
        if self.combiner == "sum":
            return summed
        count = jnp.maximum(jnp.sum(mask, axis=-2), 1.0)
        if self.combiner == "mean":
            return summed / count
        if self.combiner == "sqrtn":
            return summed / jnp.sqrt(count)
        raise ValueError(self.combiner)


class SparseEmbedding(KerasLayer):
    """Embedding-sum over variable-length id bags encoded as 0-padded
    [B, K] ids (ref: keras/layers/SparseEmbedding.scala over
    SparseTensor input; combiner semantics of tf.nn.embedding_lookup_sparse)."""

    def __init__(self, input_dim: int, output_dim: int,
                 combiner: str = "sum", **kwargs):
        super().__init__(**kwargs)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.combiner = combiner

    def _make_module(self):
        return _SparseEmbedModule(vocab=self.input_dim,
                                  dim=self.output_dim,
                                  combiner=self.combiner)


class SparseDense(KerasLayer):
    """Dense layer over sparse-coded inputs (ref:
    keras/layers/SparseDense.scala takes SparseTensor rows). TPU-first
    collapse: XLA/MXU has no win for sparse activations at these sizes,
    so inputs arrive 0-padded dense and this is ``Dense`` -- kept as a
    distinct type for API parity."""

    def __init__(self, output_dim: int, activation=None,
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        from analytics_zoo_tpu.keras import activations as acts

        self.output_dim = output_dim
        self.activation = acts.get(activation)
        self.bias = bias

    def _make_module(self):
        from analytics_zoo_tpu.keras.layers.core import _DenseModule

        return _DenseModule(units=self.output_dim,
                            activation=self.activation,
                            use_bias=self.bias)
