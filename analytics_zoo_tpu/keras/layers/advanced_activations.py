"""Advanced activation layers (ref: zoo/.../keras/layers/{LeakyReLU,ELU,
PReLU,ThresholdedReLU}.scala)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer


class LeakyReLU(KerasLayer):
    def __init__(self, alpha: float = 0.3, **kwargs):
        super().__init__(**kwargs)
        self.alpha = alpha

    def _make_module(self):
        a = self.alpha
        return FnModule(fn=lambda x: jnp.where(x >= 0, x, a * x))


class ELU(KerasLayer):
    def __init__(self, alpha: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.alpha = alpha

    def _make_module(self):
        a = self.alpha
        return FnModule(fn=lambda x: jax.nn.elu(x, alpha=a))


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.theta = theta

    def _make_module(self):
        t = self.theta
        return FnModule(fn=lambda x: jnp.where(x > t, x, 0.0))


class _PReLUModule(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        alpha = self.param("alpha", nn.initializers.constant(0.25),
                           (x.shape[-1],))
        return jnp.where(x >= 0, x, alpha * x)


class PReLU(KerasLayer):
    def _make_module(self):
        return _PReLUModule()
