"""Normalization layers (ref: zoo/.../keras/layers/BatchNormalization.scala,
zoo/.../keras/layers/internal LayerNorm used by Transformer/BERT)."""

from __future__ import annotations

import flax.linen as nn

from analytics_zoo_tpu.keras.layers.base import KerasLayer


class _BatchNormModule(nn.Module):
    momentum: float
    epsilon: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum,
                            epsilon=self.epsilon)(x)


class BatchNormalization(KerasLayer):
    """(ref: keras/layers/BatchNormalization.scala; running stats live in
    the ``batch_stats`` collection the Estimator threads through)."""

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.epsilon = epsilon

    def _make_module(self):
        return _BatchNormModule(momentum=self.momentum,
                                epsilon=self.epsilon)


class _LayerNormModule(nn.Module):
    epsilon: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.LayerNorm(epsilon=self.epsilon)(x)


class LayerNormalization(KerasLayer):
    """(ref: TransformerLayer.scala's internal LayerNorm)."""

    def __init__(self, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.epsilon = epsilon

    def _make_module(self):
        return _LayerNormModule(epsilon=self.epsilon)
