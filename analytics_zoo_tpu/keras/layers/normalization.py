"""Normalization layers (ref: zoo/.../keras/layers/BatchNormalization.scala,
zoo/.../keras/layers/internal LayerNorm used by Transformer/BERT)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer


class SampledBatchNorm(nn.Module):
    """BatchNorm whose TRAIN-time statistics come from the first
    ``stat_rows`` batch rows (0 = whole batch, exact nn.BatchNorm
    semantics).

    Why: on TPU the batch-statistics reduce is a pure-HBM-bandwidth
    pass over every activation map -- the r4 ResNet-50 device trace
    put it at 31% of step time (BENCH_NOTES.md). Sampling the stats
    over K of B rows cuts that pass's traffic B/K-fold while every
    row is still normalized (the normalize pass is unchanged). The
    estimate is noisier -- statistically the same trade as training
    with batch K for BN purposes (ghost-batch-norm territory, known
    to be mildly regularizing) -- so it is strictly OPT-IN:
    ``zoo.models.bn_stat_rows`` routes the image backbones here, and
    the default (0) keeps exact full-batch statistics.

    Inference (``use_running_average=True``) is identical to
    nn.BatchNorm: running stats, updated with the same momentum EMA.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-3
    dtype: Optional[Any] = None
    stat_rows: int = 0
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(feat, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(feat, jnp.float32))
        scale = self.param("scale", self.scale_init, (feat,))
        bias = self.param("bias", self.bias_init, (feat,))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            k = self.stat_rows
            xs = x if k <= 0 or k >= x.shape[0] else x[:k]
            xf = xs.astype(jnp.float32)
            axes = tuple(range(xf.ndim - 1))
            mean = jnp.mean(xf, axes)
            # E[x^2] - E[x]^2: both reduces share one input pass (XLA
            # multi-output fusion), vs the two-pass (x - mean)^2 form
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), axes) - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        dt = self.dtype or x.dtype
        inv = jax.lax.rsqrt(var + self.epsilon) * scale
        return (x.astype(dt) * inv.astype(dt)
                + (bias - mean * inv).astype(dt))


def batch_norm(train: bool, dtype, momentum: float = 0.9,
               epsilon: float = 1e-3):
    """The backbone BN factory: flax ``nn.BatchNorm`` by default, or
    :class:`SampledBatchNorm` when ``zoo.models.bn_stat_rows`` is set
    (opt-in stat sampling -- see the class docstring). Read at TRACE
    time, like the ``zoo.ops`` kernel-dispatch keys."""
    from functools import partial

    from analytics_zoo_tpu.common.config import get_config

    rows = int(get_config().get("zoo.models.bn_stat_rows", 0) or 0)
    if rows > 0:
        return partial(SampledBatchNorm, use_running_average=not train,
                       momentum=momentum, epsilon=epsilon, dtype=dtype,
                       stat_rows=rows)
    return partial(nn.BatchNorm, use_running_average=not train,
                   momentum=momentum, epsilon=epsilon, dtype=dtype)


class _BatchNormModule(nn.Module):
    momentum: float
    epsilon: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum,
                            epsilon=self.epsilon)(x)


class BatchNormalization(KerasLayer):
    """(ref: keras/layers/BatchNormalization.scala; running stats live in
    the ``batch_stats`` collection the Estimator threads through)."""

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.epsilon = epsilon

    def _make_module(self):
        return _BatchNormModule(momentum=self.momentum,
                                epsilon=self.epsilon)


class _LayerNormModule(nn.Module):
    epsilon: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.LayerNorm(epsilon=self.epsilon)(x)


class LayerNormalization(KerasLayer):
    """(ref: TransformerLayer.scala's internal LayerNorm)."""

    def __init__(self, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.epsilon = epsilon

    def _make_module(self):
        return _LayerNormModule(epsilon=self.epsilon)


class LRN2D(KerasLayer):
    """Local response normalization across channels on [B, H, W, C]
    (ref: keras/layers/LRN2D.scala):
    ``x / (k + alpha/n * sum_{local n channels} x^2)^beta``."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float =
                 0.75, n: int = 5, **kwargs):
        super().__init__(**kwargs)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, n

    def _make_module(self):
        alpha, k, beta, n = self.alpha, self.k, self.beta, self.n

        def fn(x):
            sq = x * x
            half = n // 2
            pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
            padded = jnp.pad(sq, pad)
            acc = jnp.zeros_like(x)
            for i in range(n):
                acc = acc + padded[..., i:i + x.shape[-1]]
            return x / jnp.power(k + (alpha / n) * acc, beta)

        return FnModule(fn=fn)
