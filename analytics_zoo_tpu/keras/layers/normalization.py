"""Normalization layers (ref: zoo/.../keras/layers/BatchNormalization.scala,
zoo/.../keras/layers/internal LayerNorm used by Transformer/BERT)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer


class _BatchNormModule(nn.Module):
    momentum: float
    epsilon: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum,
                            epsilon=self.epsilon)(x)


class BatchNormalization(KerasLayer):
    """(ref: keras/layers/BatchNormalization.scala; running stats live in
    the ``batch_stats`` collection the Estimator threads through)."""

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.epsilon = epsilon

    def _make_module(self):
        return _BatchNormModule(momentum=self.momentum,
                                epsilon=self.epsilon)


class _LayerNormModule(nn.Module):
    epsilon: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.LayerNorm(epsilon=self.epsilon)(x)


class LayerNormalization(KerasLayer):
    """(ref: TransformerLayer.scala's internal LayerNorm)."""

    def __init__(self, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.epsilon = epsilon

    def _make_module(self):
        return _LayerNormModule(epsilon=self.epsilon)


class LRN2D(KerasLayer):
    """Local response normalization across channels on [B, H, W, C]
    (ref: keras/layers/LRN2D.scala):
    ``x / (k + alpha/n * sum_{local n channels} x^2)^beta``."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float =
                 0.75, n: int = 5, **kwargs):
        super().__init__(**kwargs)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, n

    def _make_module(self):
        alpha, k, beta, n = self.alpha, self.k, self.beta, self.n

        def fn(x):
            sq = x * x
            half = n // 2
            pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
            padded = jnp.pad(sq, pad)
            acc = jnp.zeros_like(x)
            for i in range(n):
                acc = acc + padded[..., i:i + x.shape[-1]]
            return x / jnp.power(k + (alpha / n) * acc, beta)

        return FnModule(fn=fn)
