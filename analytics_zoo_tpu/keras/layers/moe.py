"""Mixture-of-experts FFN with expert parallelism.

New capability relative to the reference (data-parallel only, SURVEY.md
section 2.3: "no tensor/pipeline/sequence/expert/context parallelism
anywhere"). Completes the parallelism alphabet next to dp/tp/sp/pp:

- **Dense path** (no mesh axis): every expert runs on every token and
  the top-k gate weights select -- the exact "dense MoE" computation,
  used as the numeric reference and the small-scale fallback.
- **Expert-parallel, broadcast layout** (``layout="broadcast"``):
  expert parameters shard over a mesh axis (one slice of experts per
  device). Each device computes ONLY its resident experts on the
  (replicated) token stream, gates zero out non-selected experts, and
  one ``psum`` over the expert axis merges contributions -- exact
  equality with the dense path by construction. Comm is a single psum
  of activations over ICI, but every expert still runs on every token:
  it shards expert MEMORY, not compute.
- **Expert-parallel, dispatch layout** (``layout="dispatch"``): the
  GShard/Switch all-to-all layout. Tokens shard over (data x expert)
  devices; each source device packs per-expert capacity buffers
  (``capacity_factor``; overflow tokens are DROPPED -- slot-major
  priority, first choices ahead of second), one ``all_to_all`` over
  the expert axis carries each buffer to the expert's home device,
  each expert runs on only its ~n*k/E routed tokens, and the inverse
  ``all_to_all`` + combine weights scatter results back. Compute AND
  memory scale 1/ep; kept tokens match the dense path exactly, dropped
  tokens contribute zero (the residual path carries them).

The router is a standard softmax top-k with renormalized gates and the
switch-transformer load-balance auxiliary loss, sown into the
``losses`` collection as ``moe_aux_loss`` (fetch with
``mutable=["losses"]`` and add it to the objective).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras.activations import get as get_activation
from analytics_zoo_tpu.keras.layers.base import KerasLayer


def resolve_expert_axis(value: Optional[str]) -> Optional[str]:
    """``"auto"`` -> the ``zoo.mesh.axis.expert`` config key; any other
    value (an explicit axis name, or None for the dense path) passes
    through unchanged."""
    if value == "auto":
        from analytics_zoo_tpu.parallel.mesh import config_axis

        return config_axis("expert")
    return value

__all__ = ["MoEFFN", "MoE", "MoETransformerBlock"]


class MoEFFN(nn.Module):
    """Top-k routed expert FFN band: x [B, L, H] -> [B, L, H].

    Args:
      hidden_size / intermediate_size: per-expert FFN dims.
      n_experts: expert count; must divide by the expert-axis size
        when expert parallelism engages.
      top_k: experts per token (1 = switch routing, 2 = classic MoE).
      expert_axis: mesh axis name to shard experts over ("auto" reads
        the ``zoo.mesh.axis.expert`` config key); engages when
        the context mesh carries that axis with size > 1 dividing
        ``n_experts``. None = always dense.
      layout: "broadcast" (exact, shards memory only) or "dispatch"
        (all_to_all token routing with ``capacity_factor``; shards
        compute too, overflow tokens drop). Dispatch requires the
        batch dim to divide by data_size * ep_size.
      capacity_factor: dispatch-layout expert capacity multiplier:
        each source device offers C = ceil(cf * n_local * top_k / E)
        slots per expert.
      aux_weight: multiplier folded into the sown load-balance loss.
    """

    hidden_size: int
    intermediate_size: int
    n_experts: int
    top_k: int = 2
    expert_axis: Optional[str] = None
    layout: str = "broadcast"
    capacity_factor: float = 1.25
    activation: str = "gelu"
    aux_weight: float = 0.01
    dtype: Any = jnp.float32

    def _act(self, h):
        return get_activation(self.activation)(h)

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.top_k < 1 or self.top_k > self.n_experts:
            raise ValueError(
                f"top_k must be in [1, {self.n_experts}], "
                f"got {self.top_k}")
        if self.layout not in ("broadcast", "dispatch"):
            raise ValueError("layout must be broadcast|dispatch, "
                             f"got {self.layout!r}")
        h = x.shape[-1]
        if h != self.hidden_size:
            raise ValueError(
                f"input feature dim {h} != hidden_size "
                f"{self.hidden_size}")
        e = self.n_experts
        # router stays fp32: tiny matmul, and gate ordering decides
        # discrete routing -- bf16 ties would flap expert assignment
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)          # [B, L, E]
        top_p, top_idx = jax.lax.top_k(probs, self.top_k)
        top_p = top_p / jnp.maximum(
            jnp.sum(top_p, -1, keepdims=True), 1e-9)
        # dense gate map [B, L, E]: renormalized weight where selected
        onehot = jax.nn.one_hot(top_idx, e, dtype=probs.dtype)
        gates = jnp.einsum("blk,blke->ble", top_p, onehot)

        # switch-transformer load-balance loss: E * sum_e f_e * p_e
        # (f = fraction of tokens routed to e, p = mean router prob)
        frac = jnp.mean(jnp.sum(onehot, axis=2), axis=(0, 1))  # [E]
        mean_p = jnp.mean(probs, axis=(0, 1))                  # [E]
        aux = self.aux_weight * e * jnp.sum(frac * mean_p)
        self.sow("losses", "moe_aux_loss", aux)

        # stacked expert params [E, ...] -- shardable over expert_axis
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (e, h, self.intermediate_size))
        bi = self.param("bi", nn.initializers.zeros,
                        (e, self.intermediate_size))
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (e, self.intermediate_size, h))
        bo = self.param("bo", nn.initializers.zeros, (e, h))

        xc = x.astype(self.dtype)
        gc = gates.astype(self.dtype)

        def experts_contrib(x_s, wi_s, bi_s, wo_s, bo_s, gates_s):
            """Sum of gated expert outputs for an expert slice; expert
            params cast to the compute dtype (params stay fp32)."""
            wi_c = wi_s.astype(self.dtype)
            wo_c = wo_s.astype(self.dtype)
            hmid = self._act(
                jnp.einsum("blh,ehm->eblm", x_s, wi_c)
                + bi_s.astype(self.dtype)[:, None, None])
            y = (jnp.einsum("eblm,emh->eblh", hmid, wo_c)
                 + bo_s.astype(self.dtype)[:, None, None])
            return jnp.einsum("ble,eblh->blh", gates_s, y)

        ep_size = 0
        mesh = None
        expert_axis = resolve_expert_axis(self.expert_axis)
        if expert_axis is not None:
            from analytics_zoo_tpu.parallel.mesh import (
                default_mesh, mesh_axis_size)

            mesh = default_mesh()
            if expert_axis in mesh.axis_names:
                ep_size = mesh_axis_size(mesh, expert_axis)
        if ep_size > 1 and e % ep_size == 0 \
                and self.layout == "dispatch" \
                and not self.is_initializing():
            # init traces with a 1-row example that cannot shard over
            # the token mesh; the dense path creates the IDENTICAL
            # parameter set, so init falls through below
            out = self._dispatch_ep(xc, wi, bi, wo, bo, top_idx, top_p,
                                    mesh, ep_size)
        elif ep_size > 1 and e % ep_size == 0:
            from jax.sharding import PartitionSpec as P

            axis = expert_axis
            # batch stays sharded over the data axis (dp x ep): each
            # device computes local_batch x local_experts, the psum
            # runs over the expert axis only
            data = ("data" if "data" in mesh.axis_names
                    and x.shape[0] % mesh_axis_size(mesh, "data") == 0
                    else None)

            def local(x_s, wi_s, bi_s, wo_s, bo_s, gates_s):
                out = experts_contrib(x_s, wi_s, bi_s, wo_s, bo_s,
                                      gates_s)
                # every device contributed only its resident experts;
                # the psum over the expert axis completes the routed sum
                return jax.lax.psum(out, axis)

            from analytics_zoo_tpu.parallel.mesh import shard_map

            espec = P(axis)
            out = shard_map(
                local, mesh,
                in_specs=(P(data, None, None), espec, espec, espec,
                          espec, P(data, None, axis)),
                out_specs=P(data, None, None))(
                xc, wi, bi, wo, bo, gc)
        else:
            out = experts_contrib(xc, wi, bi, wo, bo, gc)
        return out.astype(x.dtype)

    def _dispatch_ep(self, xc, wi, bi, wo, bo, top_idx, top_p, mesh,
                     ep_size):
        """GShard/Switch all-to-all dispatch: tokens shard over
        (data x expert) devices, experts shard over the expert axis,
        one all_to_all each way moves capacity buffers, not the full
        token stream. Slot-major priority queueing: across the local
        token shard, every first-choice assignment ranks ahead of any
        second choice; assignments past the per-expert capacity are
        dropped (contribute zero -- the caller's residual carries the
        token)."""
        import math

        from jax import lax
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu.parallel.mesh import mesh_axis_size

        axis = resolve_expert_axis(self.expert_axis)
        e, k = self.n_experts, self.top_k
        e_loc = e // ep_size
        data = ("data" if "data" in mesh.axis_names
                and mesh_axis_size(mesh, "data") > 1 else None)
        d_size = mesh_axis_size(mesh, "data") if data else 1
        shards = d_size * ep_size
        if xc.shape[0] % shards != 0:
            raise ValueError(
                f"dispatch MoE shards tokens over batch: batch "
                f"{xc.shape[0]} must divide by data*expert = {shards}")
        n_local = (xc.shape[0] // shards) * xc.shape[1]
        cap = max(1, math.ceil(self.capacity_factor * n_local * k / e))
        act, dtype = self._act, self.dtype

        def local(x_s, wi_s, bi_s, wo_s, bo_s, idx_s, w_s):
            b, L, h = x_s.shape
            n = b * L
            xf = x_s.reshape(n, h)
            sel = idx_s.reshape(n, k)
            w = w_s.reshape(n, k).astype(dtype)
            # slot-major priority: flatten (slot, token) so slot 0 of
            # every token enqueues before any slot 1 (Switch ordering)
            oh = jax.nn.one_hot(sel, e, dtype=jnp.int32)   # [n, k, E]
            ohf = oh.transpose(1, 0, 2).reshape(k * n, e)
            pos = jnp.cumsum(ohf, axis=0) - ohf            # queue pos
            keep = (pos < cap) & (ohf > 0)
            slot = jax.nn.one_hot(jnp.minimum(pos, cap - 1), cap,
                                  dtype=dtype)             # [k*n,E,C]
            disp_k = (keep[..., None] * slot).reshape(k, n, e, cap)
            dispatch = disp_k.sum(0)                       # [n, E, C]
            combine = jnp.einsum("knec,nk->nec", disp_k, w)

            # pack per-expert capacity buffers and ship each to the
            # expert's home device; tiled all_to_all over dim 0 is an
            # involution, so the same call routes results back
            buf = jnp.einsum("nec,nh->ech", dispatch, xf)  # [E, C, H]
            buf = lax.all_to_all(buf, axis, 0, 0, tiled=True)
            # received layout: dim 0 = (source peer, local expert)
            z = (buf.reshape(ep_size, e_loc, cap, h)
                 .transpose(1, 0, 2, 3).reshape(e_loc, ep_size * cap,
                                                h))
            hmid = act(jnp.einsum("egh,ehm->egm", z,
                                  wi_s.astype(dtype))
                       + bi_s.astype(dtype)[:, None])
            y = (jnp.einsum("egm,emh->egh", hmid, wo_s.astype(dtype))
                 + bo_s.astype(dtype)[:, None])
            y = (y.reshape(e_loc, ep_size, cap, h)
                 .transpose(1, 0, 2, 3).reshape(e, cap, h))
            y = lax.all_to_all(y, axis, 0, 0, tiled=True)
            out = jnp.einsum("nec,ech->nh", combine, y)
            return out.reshape(b, L, h)

        from analytics_zoo_tpu.parallel.mesh import shard_map

        tspec = P((data, axis) if data else axis, None, None)
        espec = P(axis)
        return shard_map(
            local, mesh,
            in_specs=(tspec, espec, espec, espec, espec,
                      P((data, axis) if data else axis, None, None),
                      P((data, axis) if data else axis, None, None)),
            out_specs=tspec)(
            xc, wi, bi, wo, bo, top_idx, top_p)


class MoE(KerasLayer):
    """Keras-layer wrapper for :class:`MoEFFN`."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 n_experts: int, top_k: int = 2,
                 expert_axis: Optional[str] = None,
                 layout: str = "broadcast",
                 capacity_factor: float = 1.25,
                 activation: str = "gelu", aux_weight: float = 0.01,
                 dtype: Any = jnp.float32, **kwargs):
        super().__init__(**kwargs)
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.n_experts = n_experts
        self.top_k = top_k
        self.expert_axis = expert_axis
        self.layout = layout
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.aux_weight = aux_weight
        self.dtype = dtype

    def _make_module(self):
        return MoEFFN(hidden_size=self.hidden_size,
                      intermediate_size=self.intermediate_size,
                      n_experts=self.n_experts, top_k=self.top_k,
                      expert_axis=self.expert_axis,
                      layout=self.layout,
                      capacity_factor=self.capacity_factor,
                      activation=self.activation,
                      aux_weight=self.aux_weight, dtype=self.dtype)


class MoETransformerBlock(nn.Module):
    """Post-LN transformer block whose FFN is a routed expert band --
    the standard MoE-transformer layer (attention unchanged, so it
    composes with the seq_axis ring/zigzag path like any block).

    Interleave with dense ``TransformerBlock``s for the usual
    every-other-layer MoE stack; the sown ``moe_aux_loss`` reaches the
    optimizer through the Estimator's ``aux_loss_collections``.
    """

    hidden_size: int
    n_head: int
    intermediate_size: int
    n_experts: int = 8
    top_k: int = 2
    expert_axis: Optional[str] = None
    layout: str = "broadcast"
    capacity_factor: float = 1.25
    activation: str = "gelu"
    aux_weight: float = 0.01
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    causal: bool = False
    ln_eps: float = 1e-5
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, key_padding_mask=None,
                 train: bool = False):
        from analytics_zoo_tpu.keras.layers.transformer import (
            MultiHeadSelfAttention)

        attn = MultiHeadSelfAttention(
            self.hidden_size, self.n_head,
            attn_dropout=self.attn_dropout, causal=self.causal,
            dtype=self.dtype, seq_axis=self.seq_axis,
            name="attention")(x, mask=mask,
                              key_padding_mask=key_padding_mask,
                              train=train)
        attn = nn.Dropout(self.hidden_dropout,
                          deterministic=not train)(attn)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                         name="ln_attn")(x + attn)
        h = MoEFFN(hidden_size=self.hidden_size,
                   intermediate_size=self.intermediate_size,
                   n_experts=self.n_experts, top_k=self.top_k,
                   expert_axis=self.expert_axis, layout=self.layout,
                   capacity_factor=self.capacity_factor,
                   activation=self.activation,
                   aux_weight=self.aux_weight, dtype=self.dtype,
                   name="moe_ffn")(x, train=train)
        h = nn.Dropout(self.hidden_dropout, deterministic=not train)(h)
        return nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype,
                            name="ln_ffn")(x + h)
