"""KerasLayer base: declarative layer config that builds a flax module.

Every built module has the uniform signature ``__call__(x, train=False)``
so Sequential / graph execution can thread the training flag blindly
(the analog of the reference's ``KerasLayer`` adapter that gives BigDL
modules Keras semantics, ref: zoo/.../keras/layers/KerasLayer via
``KerasUtils``).
"""

from __future__ import annotations

import itertools
from typing import Callable, Optional

import flax.linen as nn

_uid = itertools.count()


class KerasLayer:
    def __init__(self, name: Optional[str] = None, input_shape=None):
        self.name = name or f"{type(self).__name__.lower()}_{next(_uid)}"
        self.input_shape = (tuple(input_shape)
                            if input_shape is not None else None)
        self._built = None

    def build(self) -> nn.Module:
        """Return the (unbound) flax module implementing this layer."""
        if self._built is None:
            self._built = self._make_module()
        return self._built

    def _make_module(self) -> nn.Module:
        raise NotImplementedError

    def __call__(self, x):
        """Symbolic call on KTensor(s): records a graph Node."""
        from analytics_zoo_tpu.keras.engine import KTensor, Node

        inputs = list(x) if isinstance(x, (list, tuple)) else [x]
        if not all(isinstance(t, KTensor) for t in inputs):
            raise TypeError(
                "layers are called on symbolic KTensors (from Input()); "
                "to run on data, put the layer in a Sequential/Model and "
                "call predict")
        node = Node(self, inputs)
        return KTensor(node)

    def __repr__(self) -> str:
        return f"{type(self).__name__}(name={self.name!r})"


class FnModule(nn.Module):
    """Stateless layer module from a pure function."""

    fn: Callable

    @nn.compact
    def __call__(self, x, train: bool = False):
        return self.fn(x)
