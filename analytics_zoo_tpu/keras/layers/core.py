"""Core layers (ref: zoo/.../keras/layers/{Dense,Dropout,Flatten,Reshape,
Permute,RepeatVector,Highway,SReLU,GaussianNoise,...}.scala)."""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras import activations
from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer


class _DenseModule(nn.Module):
    units: int
    activation: Callable
    use_bias: bool

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Dense(self.units, use_bias=self.use_bias)(x)
        return self.activation(y)


class Dense(KerasLayer):
    """(ref: keras/layers/Dense.scala)."""

    def __init__(self, output_dim: int, activation=None, bias: bool = True,
                 **kwargs):
        super().__init__(**kwargs)
        self.output_dim = output_dim
        self.activation = activations.get(activation)
        self.bias = bias

    def _make_module(self):
        return _DenseModule(units=self.output_dim,
                            activation=self.activation, use_bias=self.bias)


class Activation(KerasLayer):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self.activation = activations.get(activation)

    def _make_module(self):
        return FnModule(fn=self.activation)


class _DropoutModule(nn.Module):
    rate: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dropout(self.rate, deterministic=not train)(x)


class Dropout(KerasLayer):
    """(ref: keras/layers/Dropout.scala)."""

    def __init__(self, p: float, **kwargs):
        super().__init__(**kwargs)
        self.p = p

    def _make_module(self):
        return _DropoutModule(rate=self.p)


class _GaussianNoiseModule(nn.Module):
    sigma: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train:
            return x
        rng = self.make_rng("dropout")
        return x + self.sigma * jax.random.normal(rng, x.shape, x.dtype)


class GaussianNoise(KerasLayer):
    """(ref: keras/layers/GaussianNoise.scala)."""

    def __init__(self, sigma: float, **kwargs):
        super().__init__(**kwargs)
        self.sigma = sigma

    def _make_module(self):
        return _GaussianNoiseModule(sigma=self.sigma)


class Flatten(KerasLayer):
    def _make_module(self):
        return FnModule(fn=lambda x: x.reshape(x.shape[0], -1))


class Reshape(KerasLayer):
    """target_shape excludes the batch dim; one -1 allowed
    (ref: keras/layers/Reshape.scala)."""

    def __init__(self, target_shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.target_shape = tuple(target_shape)

    def _make_module(self):
        ts = self.target_shape
        return FnModule(fn=lambda x: x.reshape((x.shape[0],) + ts))


class Permute(KerasLayer):
    """1-based dim indices excluding batch (keras1 convention,
    ref: keras/layers/Permute.scala)."""

    def __init__(self, dims: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.dims = tuple(dims)

    def _make_module(self):
        perm = (0,) + tuple(d for d in self.dims)
        return FnModule(fn=lambda x: jnp.transpose(x, perm))


class RepeatVector(KerasLayer):
    """[B, D] -> [B, n, D] (ref: keras/layers/RepeatVector.scala)."""

    def __init__(self, n: int, **kwargs):
        super().__init__(**kwargs)
        self.n = n

    def _make_module(self):
        n = self.n
        return FnModule(fn=lambda x: jnp.repeat(x[:, None, :], n, axis=1))


class Lambda(KerasLayer):
    """Wrap an arbitrary jax-traceable function
    (ref: api/autograd Lambda.scala / CustomLoss pattern)."""

    def __init__(self, fn: Callable, **kwargs):
        super().__init__(**kwargs)
        self.fn = fn

    def _make_module(self):
        return FnModule(fn=self.fn)


class InputLayer(KerasLayer):
    def _make_module(self):
        return FnModule(fn=lambda x: x)


class _HighwayModule(nn.Module):
    activation: Callable

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = x.shape[-1]
        h = self.activation(nn.Dense(d, name="transform")(x))
        t = jax.nn.sigmoid(nn.Dense(
            d, name="gate",
            bias_init=nn.initializers.constant(-2.0))(x))
        return h * t + x * (1.0 - t)


class Highway(KerasLayer):
    """(ref: keras/layers/Highway.scala; gate bias init -2 per paper)."""

    def __init__(self, activation="tanh", **kwargs):
        super().__init__(**kwargs)
        self.activation = activations.get(activation)

    def _make_module(self):
        return _HighwayModule(activation=self.activation)


class _SReLUModule(nn.Module):
    """S-shaped ReLU with learnable (t_left, a_left, t_right, a_right)
    per-channel (ref: keras/layers/SReLU.scala; Jin et al. 2015)."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        shape = (x.shape[-1],)
        t_l = self.param("t_left", nn.initializers.zeros, shape)
        a_l = self.param("a_left", nn.initializers.constant(0.2), shape)
        t_r = self.param("t_right", nn.initializers.constant(1.0), shape)
        a_r = self.param("a_right", nn.initializers.ones, shape)
        below = t_l + a_l * (x - t_l)
        above = t_r + a_r * (x - t_r)
        mid = x
        return jnp.where(x < t_l, below, jnp.where(x > t_r, above, mid))


class SReLU(KerasLayer):
    def _make_module(self):
        return _SReLUModule()


class Masking(KerasLayer):
    """Zero out timesteps whose features ALL equal ``mask_value``
    (ref: keras/layers/Masking.scala -- BigDL likewise zeroes masked
    steps): [B, T, ...] -> same shape with masked steps zeroed.
    Sum/max pooling then ignores them; RNNs still run their recurrence
    over the zeroed steps (no mask channel propagates -- same as the
    reference's BigDL layer set)."""

    def __init__(self, mask_value: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.mask_value = mask_value

    def _make_module(self):
        mv = self.mask_value

        def fn(x):
            reduce_axes = tuple(range(2, x.ndim))
            keep = jnp.any(x != mv, axis=reduce_axes) if reduce_axes \
                else (x != mv)
            shape = keep.shape + (1,) * (x.ndim - keep.ndim)
            return x * keep.reshape(shape).astype(x.dtype)

        return FnModule(fn=fn)


class _MaxoutDenseModule(nn.Module):
    units: int
    nb_feature: int
    use_bias: bool

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Dense(self.units * self.nb_feature,
                     use_bias=self.use_bias)(x)
        y = y.reshape(y.shape[:-1] + (self.nb_feature, self.units))
        return jnp.max(y, axis=-2)


class MaxoutDense(KerasLayer):
    """Max over ``nb_feature`` linear pieces
    (ref: keras/layers/MaxoutDense.scala)."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.bias = bias

    def _make_module(self):
        return _MaxoutDenseModule(units=self.output_dim,
                                  nb_feature=self.nb_feature,
                                  use_bias=self.bias)


class _GaussianDropoutModule(nn.Module):
    rate: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.rate <= 0:
            return x
        rng = self.make_rng("dropout")
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + stddev * jax.random.normal(rng, x.shape,
                                                     x.dtype))


class GaussianDropout(KerasLayer):
    """Multiplicative 1-centered gaussian noise
    (ref: keras/layers/GaussianDropout.scala)."""

    def __init__(self, p: float, **kwargs):
        super().__init__(**kwargs)
        if not 0.0 <= p < 1.0:
            raise ValueError(f"p must be in [0, 1), got {p}")
        self.p = p

    def _make_module(self):
        return _GaussianDropoutModule(rate=self.p)


class _SpatialDropoutModule(nn.Module):
    rate: float
    spatial_ndim: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.rate <= 0:
            return x
        # drop whole channels: mask [B, 1, ..., 1, C]
        rng = self.make_rng("dropout")
        shape = (x.shape[0],) + (1,) * self.spatial_ndim + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, 1.0 - self.rate, shape)
        return x * keep.astype(x.dtype) / (1.0 - self.rate)


class _SpatialDropoutBase(KerasLayer):
    spatial_ndim = 1

    def __init__(self, p: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        if not 0.0 <= p < 1.0:
            raise ValueError(f"p must be in [0, 1), got {p}")
        self.p = p

    def _make_module(self):
        return _SpatialDropoutModule(rate=self.p,
                                     spatial_ndim=self.spatial_ndim)


class SpatialDropout1D(_SpatialDropoutBase):
    """Channel-wise dropout on [B, T, C]
    (ref: keras/layers/SpatialDropout1D.scala; channels-last)."""

    spatial_ndim = 1


class SpatialDropout2D(_SpatialDropoutBase):
    """Channel-wise dropout on [B, H, W, C]
    (ref: keras/layers/SpatialDropout2D.scala)."""

    spatial_ndim = 2


class SpatialDropout3D(_SpatialDropoutBase):
    """Channel-wise dropout on [B, D, H, W, C]
    (ref: keras/layers/SpatialDropout3D.scala)."""

    spatial_ndim = 3


class _GaussianSamplerModule(nn.Module):
    @nn.compact
    def __call__(self, xs, train: bool = False):
        if not isinstance(xs, (list, tuple)) or len(xs) != 2:
            raise ValueError("GaussianSampler expects [mean, log_var]")
        mean, log_var = xs
        if not train:
            return mean
        rng = self.make_rng("dropout")
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps


class GaussianSampler(KerasLayer):
    """VAE reparameterization: sample N(mean, exp(log_var)) while
    training, mean at inference (ref: keras/layers/GaussianSampler.scala
    -- the reference samples unconditionally; returning the mean at
    inference is the standard VAE deployment behavior)."""

    def _make_module(self):
        return _GaussianSamplerModule()
