"""Merge layers (ref: zoo/.../keras/layers/Merge.scala -- modes sum/mul/
max/ave/concat/dot/cos; keras functional merge helpers)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import KerasLayer


class _MergeModule(nn.Module):
    mode: str
    concat_axis: int
    dot_axes: int

    @nn.compact
    def __call__(self, xs, train: bool = False):
        if not isinstance(xs, (list, tuple)):
            raise ValueError("Merge expects a list of inputs")
        mode = self.mode
        if mode == "concat":
            return jnp.concatenate(list(xs), axis=self.concat_axis)
        if mode == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if mode == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if mode == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if mode == "ave":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out / len(xs)
        if mode == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=self.dot_axes, keepdims=True)
        if mode == "cos":
            a, b = xs
            na = jnp.linalg.norm(a, axis=self.dot_axes, keepdims=True)
            nb = jnp.linalg.norm(b, axis=self.dot_axes, keepdims=True)
            return (jnp.sum(a * b, axis=self.dot_axes, keepdims=True)
                    / jnp.maximum(na * nb, 1e-7))
        raise ValueError(f"unknown merge mode {mode!r}")


class Merge(KerasLayer):
    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 dot_axes: int = -1, **kwargs):
        super().__init__(**kwargs)
        self.mode = mode
        self.concat_axis = concat_axis
        self.dot_axes = dot_axes

    def _make_module(self):
        return _MergeModule(mode=self.mode, concat_axis=self.concat_axis,
                            dot_axes=self.dot_axes)


def concatenate(tensors: Sequence, axis: int = -1):
    return Merge(mode="concat", concat_axis=axis)(list(tensors))


def add(tensors: Sequence):
    return Merge(mode="sum")(list(tensors))


def multiply(tensors: Sequence):
    return Merge(mode="mul")(list(tensors))


def average(tensors: Sequence):
    return Merge(mode="ave")(list(tensors))


def maximum(tensors: Sequence):
    return Merge(mode="max")(list(tensors))


def dot(tensors: Sequence, axes: int = -1):
    return Merge(mode="dot", dot_axes=axes)(list(tensors))
