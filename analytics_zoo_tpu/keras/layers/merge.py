"""Merge layers (ref: zoo/.../keras/layers/Merge.scala -- modes sum/mul/
max/ave/concat/dot/cos; keras functional merge helpers)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import KerasLayer


class _MergeModule(nn.Module):
    mode: str
    concat_axis: int
    dot_axes: int

    @nn.compact
    def __call__(self, xs, train: bool = False):
        if not isinstance(xs, (list, tuple)):
            raise ValueError("Merge expects a list of inputs")
        mode = self.mode
        if mode == "concat":
            return jnp.concatenate(list(xs), axis=self.concat_axis)
        if mode == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if mode == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if mode == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if mode == "ave":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out / len(xs)
        if mode == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=self.dot_axes, keepdims=True)
        if mode == "cos":
            a, b = xs
            na = jnp.linalg.norm(a, axis=self.dot_axes, keepdims=True)
            nb = jnp.linalg.norm(b, axis=self.dot_axes, keepdims=True)
            return (jnp.sum(a * b, axis=self.dot_axes, keepdims=True)
                    / jnp.maximum(na * nb, 1e-7))
        raise ValueError(f"unknown merge mode {mode!r}")


class Merge(KerasLayer):
    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 dot_axes: int = -1, **kwargs):
        super().__init__(**kwargs)
        self.mode = mode
        self.concat_axis = concat_axis
        self.dot_axes = dot_axes

    def _make_module(self):
        return _MergeModule(mode=self.mode, concat_axis=self.concat_axis,
                            dot_axes=self.dot_axes)


def concatenate(tensors: Sequence, axis: int = -1):
    return Merge(mode="concat", concat_axis=axis)(list(tensors))


def add(tensors: Sequence):
    return Merge(mode="sum")(list(tensors))


def multiply(tensors: Sequence):
    return Merge(mode="mul")(list(tensors))


def average(tensors: Sequence):
    return Merge(mode="ave")(list(tensors))


def maximum(tensors: Sequence):
    return Merge(mode="max")(list(tensors))


def dot(tensors: Sequence, axes: int = -1):
    return Merge(mode="dot", dot_axes=axes)(list(tensors))


class _MMModule(nn.Module):
    trans_a: bool
    trans_b: bool

    @nn.compact
    def __call__(self, xs, train: bool = False):
        if not isinstance(xs, (list, tuple)) or len(xs) != 2:
            raise ValueError("MM expects exactly two input tensors")
        a, b = xs
        if a.ndim not in (2, 3) or b.ndim != a.ndim:
            raise ValueError(
                "MM inputs must both be 2D or both be 3D, got "
                f"{a.ndim}D and {b.ndim}D")
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class MM(KerasLayer):
    """Matrix multiply of a two-tensor table, with optional transposes;
    2D inputs multiply directly, 3D inputs batch-multiply
    (ref: zoo/.../keras/layers/InternalMM.scala:37-150 -- there a Table
    module with hand-written backward; here one jnp.matmul, with the
    transposes folded into the same XLA dot)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False,
                 **kwargs):
        super().__init__(**kwargs)
        self.trans_a = trans_a
        self.trans_b = trans_b

    def _make_module(self):
        return _MMModule(trans_a=self.trans_a, trans_b=self.trans_b)


class _SelectTableModule(nn.Module):
    index: int

    @nn.compact
    def __call__(self, xs, train: bool = False):
        if not isinstance(xs, (list, tuple)):
            raise ValueError("SelectTable expects a table (list) input")
        return xs[self.index]


class SelectTable(KerasLayer):
    """Select element ``index`` (0-based) from a table input -- either a
    list of graph tensors or the output of :class:`SplitTensor`
    (ref: zoo/.../keras/layers/SelectTable.scala:42-60)."""

    def __init__(self, index: int, **kwargs):
        super().__init__(**kwargs)
        self.index = index

    def _make_module(self):
        return _SelectTableModule(index=self.index)


class _SplitTensorModule(nn.Module):
    dimension: int
    num: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        if not 0 <= self.dimension < x.ndim - 1:
            raise ValueError(
                f"dimension must be in [0, {x.ndim - 2}] (0-based, "
                f"batch dim excluded), got {self.dimension}")
        axis = self.dimension + 1  # input dims exclude the batch dim
        if x.shape[axis] % self.num:
            raise ValueError(
                f"dimension {self.dimension} (size {x.shape[axis]}) not "
                f"divisible into {self.num} chunks")
        return tuple(jnp.split(x, self.num, axis=axis))


class SplitTensor(KerasLayer):
    """Split a tensor into a ``num``-element table along ``dimension``
    (0-based, batch dim excluded -- the reference's convention,
    ref: zoo/.../keras/layers/SplitTensor.scala:39-58 /
    InternalSplitTensor.scala:27). Pair with :class:`SelectTable` to
    route table elements through a branching graph."""

    def __init__(self, dimension: int, num: int, **kwargs):
        super().__init__(**kwargs)
        self.dimension = dimension
        self.num = num

    def _make_module(self):
        return _SplitTensorModule(dimension=self.dimension, num=self.num)
