"""Recurrent layers (ref: zoo/.../keras/layers/{LSTM,GRU,SimpleRNN,
ConvLSTM2D,Bidirectional,TimeDistributed}.scala).

Implemented over flax's scan-based RNN machinery -- on TPU the recurrence
compiles to a single fused ``lax.scan`` loop (no per-step dispatch, unlike
the reference's per-timestep BigDL module calls)."""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras.layers.base import KerasLayer


class _RNNModule(nn.Module):
    cell_type: str
    units: int
    return_sequences: bool
    reverse: bool = False
    conv_kernel: Optional[Tuple[int, int]] = None

    def _cell(self):
        if self.cell_type == "lstm":
            return nn.OptimizedLSTMCell(self.units)
        if self.cell_type == "gru":
            return nn.GRUCell(self.units)
        if self.cell_type == "simple":
            return nn.SimpleCell(self.units)
        if self.cell_type == "convlstm2d":
            return nn.ConvLSTMCell(self.units, self.conv_kernel)
        raise ValueError(self.cell_type)

    @nn.compact
    def __call__(self, x, train: bool = False):
        seq = nn.RNN(self._cell(), reverse=self.reverse,
                     keep_order=True)(x)
        if self.return_sequences:
            return seq
        return seq[:, -1 if not self.reverse else 0]


class _RecurrentBase(KerasLayer):
    cell_type = "simple"

    def __init__(self, output_dim: int, return_sequences: bool = False,
                 go_backwards: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def _make_module(self):
        return _RNNModule(cell_type=self.cell_type, units=self.output_dim,
                          return_sequences=self.return_sequences,
                          reverse=self.go_backwards)


class SimpleRNN(_RecurrentBase):
    cell_type = "simple"


class LSTM(_RecurrentBase):
    cell_type = "lstm"


class GRU(_RecurrentBase):
    cell_type = "gru"


class ConvLSTM2D(KerasLayer):
    """x: [B, T, H, W, C] (ref: keras/layers/ConvLSTM2D.scala;
    channels-last)."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 return_sequences: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.return_sequences = return_sequences

    def _make_module(self):
        return _RNNModule(cell_type="convlstm2d", units=self.nb_filter,
                          return_sequences=self.return_sequences,
                          conv_kernel=(self.nb_kernel, self.nb_kernel))


class _BidirectionalModule(nn.Module):
    fwd: nn.Module
    bwd: nn.Module
    merge_mode: str

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = self.fwd(x, train=train)
        b = self.bwd(x, train=train)
        if self.merge_mode == "concat":
            return jnp.concatenate([f, b], axis=-1)
        if self.merge_mode == "sum":
            return f + b
        if self.merge_mode == "mul":
            return f * b
        if self.merge_mode == "ave":
            return (f + b) / 2.0
        raise ValueError(self.merge_mode)


class Bidirectional(KerasLayer):
    """(ref: keras/layers/Bidirectional.scala)."""

    def __init__(self, layer: _RecurrentBase, merge_mode: str = "concat",
                 **kwargs):
        super().__init__(**kwargs)
        self.layer = layer
        self.merge_mode = merge_mode

    def _make_module(self):
        fwd = _RNNModule(cell_type=self.layer.cell_type,
                         units=self.layer.output_dim,
                         return_sequences=self.layer.return_sequences,
                         reverse=False)
        bwd = _RNNModule(cell_type=self.layer.cell_type,
                         units=self.layer.output_dim,
                         return_sequences=self.layer.return_sequences,
                         reverse=True)
        return _BidirectionalModule(fwd=fwd, bwd=bwd,
                                    merge_mode=self.merge_mode)


class _TimeDistributedModule(nn.Module):
    inner: nn.Module

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        out = self.inner(flat, train=train)
        return out.reshape((b, t) + out.shape[1:])


class TimeDistributed(KerasLayer):
    """Apply a layer to every timestep with shared weights
    (ref: keras/layers/TimeDistributed.scala)."""

    def __init__(self, layer: KerasLayer, **kwargs):
        super().__init__(**kwargs)
        self.layer = layer

    def _make_module(self):
        return _TimeDistributedModule(inner=self.layer.build())


class ConvLSTM3D(KerasLayer):
    """x: [B, T, D, H, W, C] (ref: keras/layers/ConvLSTM3D.scala;
    channels-last)."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 return_sequences: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.return_sequences = return_sequences

    def _make_module(self):
        k = self.nb_kernel
        return _RNNModule(cell_type="convlstm2d", units=self.nb_filter,
                          return_sequences=self.return_sequences,
                          conv_kernel=(k, k, k))
