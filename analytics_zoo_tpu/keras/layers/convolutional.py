"""Convolution / padding / cropping / upsampling layers.

(ref: zoo/.../keras/layers/{Convolution1D,Convolution2D,Convolution3D,
Deconvolution2D,SeparableConvolution2D,AtrousConvolution1D/2D,
Cropping*,UpSampling*,ZeroPadding*}.scala)

TPU-first deviation: channels-LAST layouts ([B,L,C], [B,H,W,C],
[B,D,H,W,C]) -- the native TPU conv layout -- where BigDL uses NCHW.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.keras import activations
from analytics_zoo_tpu.keras.layers.base import FnModule, KerasLayer


def _tup(v, n):
    if isinstance(v, (tuple, list)):
        if len(v) != n:
            raise ValueError(f"expected {n} values, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _ConvModule(nn.Module):
    features: int
    kernel: Tuple[int, ...]
    strides: Tuple[int, ...]
    padding: str
    dilation: Tuple[int, ...]
    activation: Callable
    use_bias: bool
    transpose: bool = False
    groups: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        cls = nn.ConvTranspose if self.transpose else nn.Conv
        kwargs = {} if self.transpose else {
            "feature_group_count": self.groups}
        y = cls(self.features, self.kernel, strides=self.strides,
                padding=self.padding.upper(),
                kernel_dilation=self.dilation,
                use_bias=self.use_bias, **kwargs)(x)
        return self.activation(y)


class _ConvBase(KerasLayer):
    rank = 2

    def __init__(self, nb_filter: int, kernel, subsample=1,
                 activation=None, border_mode: str = "valid",
                 bias: bool = True, dilation_rate=1, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.kernel = _tup(kernel, self.rank)
        self.subsample = _tup(subsample, self.rank)
        self.dilation = _tup(dilation_rate, self.rank)
        self.activation = activations.get(activation)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid/same, "
                             f"got {border_mode!r}")
        self.border_mode = border_mode
        self.bias = bias

    def _make_module(self):
        return _ConvModule(
            features=self.nb_filter, kernel=self.kernel,
            strides=self.subsample, padding=self.border_mode,
            dilation=self.dilation, activation=self.activation,
            use_bias=self.bias)


class Convolution1D(_ConvBase):
    rank = 1

    def __init__(self, nb_filter, filter_length, subsample_length=1,
                 **kwargs):
        super().__init__(nb_filter, filter_length,
                         subsample=subsample_length, **kwargs)


class Convolution2D(_ConvBase):
    rank = 2

    def __init__(self, nb_filter, nb_row, nb_col=None, subsample=(1, 1),
                 **kwargs):
        kernel = (nb_row, nb_col) if nb_col is not None else nb_row
        super().__init__(nb_filter, kernel, subsample=subsample, **kwargs)


class Convolution3D(_ConvBase):
    rank = 3

    def __init__(self, nb_filter, kernel_dim1, kernel_dim2=None,
                 kernel_dim3=None, subsample=(1, 1, 1), **kwargs):
        kernel = ((kernel_dim1, kernel_dim2, kernel_dim3)
                  if kernel_dim2 is not None else kernel_dim1)
        super().__init__(nb_filter, kernel, subsample=subsample, **kwargs)


class AtrousConvolution1D(Convolution1D):
    def __init__(self, nb_filter, filter_length, atrous_rate=1, **kwargs):
        super().__init__(nb_filter, filter_length,
                         dilation_rate=atrous_rate, **kwargs)


class AtrousConvolution2D(Convolution2D):
    def __init__(self, nb_filter, nb_row, nb_col=None, atrous_rate=(1, 1),
                 **kwargs):
        super().__init__(nb_filter, nb_row, nb_col,
                         dilation_rate=atrous_rate, **kwargs)


class Deconvolution2D(_ConvBase):
    """Transposed conv (ref: keras/layers/Deconvolution2D.scala)."""

    rank = 2

    def __init__(self, nb_filter, nb_row, nb_col=None, subsample=(1, 1),
                 **kwargs):
        kernel = (nb_row, nb_col) if nb_col is not None else nb_row
        super().__init__(nb_filter, kernel, subsample=subsample, **kwargs)

    def _make_module(self):
        return _ConvModule(
            features=self.nb_filter, kernel=self.kernel,
            strides=self.subsample, padding=self.border_mode,
            dilation=self.dilation, activation=self.activation,
            use_bias=self.bias, transpose=True)


class _SeparableConv2DModule(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int]
    padding: str
    depth_multiplier: int
    activation: Callable
    use_bias: bool

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        depth = nn.Conv(in_ch * self.depth_multiplier, self.kernel,
                        strides=self.strides, padding=self.padding.upper(),
                        feature_group_count=in_ch, use_bias=False,
                        name="depthwise")(x)
        point = nn.Conv(self.features, (1,) * len(self.kernel),
                        use_bias=self.use_bias, name="pointwise")(depth)
        return self.activation(point)


class SeparableConvolution2D(KerasLayer):
    """(ref: keras/layers/SeparableConvolution2D.scala)."""

    def __init__(self, nb_filter, nb_row, nb_col=None, subsample=(1, 1),
                 depth_multiplier: int = 1, activation=None,
                 border_mode: str = "valid", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col if nb_col is not None else nb_row)
        self.subsample = _tup(subsample, 2)
        self.depth_multiplier = depth_multiplier
        self.activation = activations.get(activation)
        self.border_mode = border_mode
        self.bias = bias

    def _make_module(self):
        return _SeparableConv2DModule(
            features=self.nb_filter, kernel=self.kernel,
            strides=self.subsample, padding=self.border_mode,
            depth_multiplier=self.depth_multiplier,
            activation=self.activation, use_bias=self.bias)


# -------------------------------------------------- crop / pad / upsample ---


def _crop_layer(rank):
    class _Cropping(KerasLayer):
        def __init__(self, cropping=None, **kwargs):
            super().__init__(**kwargs)
            if cropping is None:
                cropping = ((1, 1),) * rank if rank > 1 else (1, 1)
            if rank == 1:
                cropping = (tuple(cropping),)
            self.cropping = tuple(tuple(c) for c in cropping)

        def _make_module(self):
            crops = self.cropping

            def fn(x):
                slices = [slice(None)]
                for lo, hi in crops:
                    slices.append(slice(lo, x.shape[len(slices)] - hi))
                slices.append(slice(None))
                return x[tuple(slices)]

            return FnModule(fn=fn)

    return _Cropping


Cropping1D = _crop_layer(1)
Cropping1D.__name__ = "Cropping1D"
Cropping2D = _crop_layer(2)
Cropping2D.__name__ = "Cropping2D"
Cropping3D = _crop_layer(3)
Cropping3D.__name__ = "Cropping3D"


def _pad_layer(rank):
    class _ZeroPadding(KerasLayer):
        def __init__(self, padding=1, **kwargs):
            super().__init__(**kwargs)
            if isinstance(padding, int):
                padding = ((padding, padding),) * rank
            elif rank == 1 and isinstance(padding, (tuple, list)) and \
                    len(padding) == 2 and isinstance(padding[0], int):
                padding = (tuple(padding),)
            else:
                padding = tuple(
                    (p, p) if isinstance(p, int) else tuple(p)
                    for p in padding)
            self.padding = padding

        def _make_module(self):
            pads = self.padding

            def fn(x):
                cfg = [(0, 0)] + list(pads) + [(0, 0)]
                return jnp.pad(x, cfg)

            return FnModule(fn=fn)

    return _ZeroPadding


ZeroPadding1D = _pad_layer(1)
ZeroPadding1D.__name__ = "ZeroPadding1D"
ZeroPadding2D = _pad_layer(2)
ZeroPadding2D.__name__ = "ZeroPadding2D"
ZeroPadding3D = _pad_layer(3)
ZeroPadding3D.__name__ = "ZeroPadding3D"


def _upsample_layer(rank):
    class _UpSampling(KerasLayer):
        def __init__(self, size=2, **kwargs):
            super().__init__(**kwargs)
            self.size = _tup(size, rank)

        def _make_module(self):
            size = self.size

            def fn(x):
                for axis, s in enumerate(size):
                    x = jnp.repeat(x, s, axis=axis + 1)
                return x

            return FnModule(fn=fn)

    return _UpSampling


UpSampling1D = _upsample_layer(1)
UpSampling1D.__name__ = "UpSampling1D"
UpSampling2D = _upsample_layer(2)
UpSampling2D.__name__ = "UpSampling2D"
UpSampling3D = _upsample_layer(3)
UpSampling3D.__name__ = "UpSampling3D"


class _LocallyConnectedModule(nn.Module):
    """Unshared convolution: one kernel per output position. Patches are
    extracted statically and contracted with a [positions, patch, out]
    weight in ONE einsum -- MXU-friendly despite no weight sharing."""

    units: int
    kernel: Tuple[int, ...]
    strides: Tuple[int, ...]
    activation: Callable
    use_bias: bool

    @nn.compact
    def __call__(self, x, train: bool = False):
        spatial = x.shape[1:-1]
        c_in = x.shape[-1]
        k = self.kernel
        s = self.strides
        out_sizes = tuple((spatial[i] - k[i]) // s[i] + 1
                          for i in range(len(k)))
        n_pos = 1
        for o in out_sizes:
            n_pos *= o
        patch = c_in
        for kk in k:
            patch *= kk
        if len(k) == 1:
            idx = (jnp.arange(out_sizes[0])[:, None] * s[0]
                   + jnp.arange(k[0])[None, :])          # [O, K]
            patches = x[:, idx]                          # [B, O, K, C]
            patches = patches.reshape(x.shape[0], n_pos, patch)
        else:
            i0 = (jnp.arange(out_sizes[0])[:, None] * s[0]
                  + jnp.arange(k[0])[None, :])           # [Oh, Kh]
            j0 = (jnp.arange(out_sizes[1])[:, None] * s[1]
                  + jnp.arange(k[1])[None, :])           # [Ow, Kw]
            patches = x[:, i0][:, :, :, j0]              # [B,Oh,Kh,Ow,Kw,C]
            patches = patches.transpose(0, 1, 3, 2, 4, 5)
            patches = patches.reshape(x.shape[0], n_pos, patch)
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (n_pos, patch, self.units))
        y = jnp.einsum("bpk,pku->bpu", patches, w)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros,
                           (n_pos, self.units))
            y = y + b
        y = y.reshape((x.shape[0],) + out_sizes + (self.units,))
        return self.activation(y)


class LocallyConnected1D(KerasLayer):
    """Conv1D without weight sharing, 'valid' padding only
    (ref: keras/layers/LocallyConnected1D.scala)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation=None, subsample_length: int = 1,
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activations.get(activation)
        self.subsample_length = subsample_length
        self.bias = bias

    def _make_module(self):
        return _LocallyConnectedModule(
            units=self.nb_filter, kernel=(self.filter_length,),
            strides=(self.subsample_length,), activation=self.activation,
            use_bias=self.bias)


class LocallyConnected2D(KerasLayer):
    """Conv2D without weight sharing, 'valid' padding only
    (ref: keras/layers/LocallyConnected2D.scala; channels-last)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample: Tuple[int, int] = (1, 1),
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activations.get(activation)
        self.subsample = tuple(subsample)
        self.bias = bias

    def _make_module(self):
        return _LocallyConnectedModule(
            units=self.nb_filter, kernel=(self.nb_row, self.nb_col),
            strides=self.subsample, activation=self.activation,
            use_bias=self.bias)


class ResizeBilinear(KerasLayer):
    """Bilinear resize of [B, H, W, C] feature maps
    (ref: keras/layers/ResizeBilinear.scala)."""

    def __init__(self, output_height: int, output_width: int, **kwargs):
        super().__init__(**kwargs)
        self.output_height = output_height
        self.output_width = output_width

    def _make_module(self):
        oh, ow = self.output_height, self.output_width

        def fn(x):
            import jax

            return jax.image.resize(
                x, (x.shape[0], oh, ow, x.shape[-1]), method="bilinear")

        return FnModule(fn=fn)
