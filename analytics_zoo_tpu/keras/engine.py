"""Keras engine: symbolic tensors, Sequential and graph Model topologies.

The analog of ``KerasNet``/``Sequential``/``Model``
(ref: zoo/.../keras/models/Topology.scala:67-988,
pyzoo/zoo/pipeline/api/keras/engine/topology.py:31). Where the reference
compiles a topology into BigDL's DistriOptimizer, here ``compile()``
configures the SPMD Estimator and ``fit`` runs the jitted sharded step.

Graph building mirrors the Keras functional API: ``Input`` creates a
symbolic :class:`KTensor`; calling a layer on KTensors records a
:class:`Node`; ``Model(input, output)`` topologically sorts the DAG into
one flax module. KTensor arithmetic (+, -, *, /) provides the autograd
``Variable`` sugar (ref: zoo/.../pipeline/api/autograd -- math on graph
nodes).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)

_uid = itertools.count()


class Node:
    """One layer invocation in the graph."""

    def __init__(self, layer, inputs: List["KTensor"]):
        self.layer = layer
        self.inputs = inputs
        self.id = next(_uid)


class KTensor:
    """Symbolic tensor: the output of a Node (or a graph input)."""

    def __init__(self, node: Optional[Node], shape: Optional[Tuple] = None,
                 input_index: Optional[int] = None):
        self.node = node
        self.shape = shape  # without batch dim, may be None
        self.input_index = input_index  # set for graph inputs

    # autograd-style arithmetic sugar (ref: api/autograd math.scala)
    def __add__(self, other):
        return _lambda_op("add", self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return _lambda_op("sub", self, other)

    def __rsub__(self, other):
        return _lambda_op("rsub", self, other)

    def __mul__(self, other):
        return _lambda_op("mul", self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _lambda_op("div", self, other)

    def __rtruediv__(self, other):
        return _lambda_op("rdiv", self, other)

    def __neg__(self):
        return _lambda_op("neg", self)


def Input(shape: Tuple, name: Optional[str] = None) -> KTensor:
    """Graph input placeholder; ``shape`` excludes the batch dim
    (ref: keras/engine Input / InputLayer)."""
    return KTensor(node=None, shape=tuple(shape),
                   input_index=next(_uid))


def _lambda_op(op: str, a, b=None) -> KTensor:
    from analytics_zoo_tpu.keras.layers.core import Lambda

    ops: Dict[str, Callable] = {
        "add": lambda x, y: x + y, "sub": lambda x, y: x - y,
        "rsub": lambda x, y: y - x, "mul": lambda x, y: x * y,
        "div": lambda x, y: x / y, "rdiv": lambda x, y: y / x,
        "neg": lambda x: -x,
    }
    fn = ops[op]
    if b is None:
        return Lambda(fn, name=f"lambda_{op}_{next(_uid)}")(a)
    if isinstance(b, KTensor):
        lam = Lambda(lambda xs: fn(xs[0], xs[1]),
                     name=f"lambda_{op}_{next(_uid)}")
        return lam([a, b])
    const = b
    return Lambda(lambda x: fn(x, const),
                  name=f"lambda_{op}_{next(_uid)}")(a)


# ------------------------------------------------------------- modules ---


class _SequentialModule(nn.Module):
    """Applies built layer modules in order with a uniform train flag."""

    modules: Tuple[nn.Module, ...]

    @nn.compact
    def __call__(self, x, train: bool = False):
        for m in self.modules:
            x = m(x, train=train)
        return x


class _GraphModule(nn.Module):
    """Executes a topologically-sorted DAG of layer modules.

    ``steps`` is a tuple of (module, input_slot_ids, output_slot_id);
    slot ids reference graph inputs (negative: -1-index) or prior node
    outputs.
    """

    modules: Tuple[nn.Module, ...]
    input_slots: Tuple[Tuple[int, ...], ...]
    n_inputs: int

    @nn.compact
    def __call__(self, *xs, train: bool = False):
        if len(xs) == 1 and isinstance(xs[0], (tuple, list)):
            xs = tuple(xs[0])
        inputs = list(xs)
        if len(inputs) != self.n_inputs:
            raise ValueError(
                f"model expects {self.n_inputs} inputs, got {len(inputs)}")
        values: List[Any] = list(inputs)
        for m, slots in zip(self.modules, self.input_slots):
            args = [values[s] for s in slots]
            out = m(args if len(args) > 1 else args[0], train=train)
            values.append(out)
        return values[-1]


# ------------------------------------------------------------ topology ---


class KerasNet:
    """compile/fit/evaluate/predict surface shared by Sequential and Model
    (ref: Topology.scala:67-630 KerasNet)."""

    def __init__(self):
        self._module: Optional[nn.Module] = None
        self.estimator = None
        self._loss = None
        self._optimizer = "adam"
        self._metrics: Sequence[Any] = ()
        self._checkpoint_dir = None
        self._checkpoint_trigger = None
        self._log_dir = None

    def _build_module(self) -> nn.Module:
        raise NotImplementedError

    @property
    def module(self) -> nn.Module:
        if self._module is None:
            self._module = self._build_module()
        return self._module

    def compile(self, optimizer="adam", loss=None, metrics=()):
        """(ref: Topology.scala compile). Recompiling preserves trained
        weights (Keras contract)."""
        self._optimizer, self._loss, self._metrics = optimizer, loss, metrics
        from analytics_zoo_tpu.learn.estimator import recompiled

        self.estimator = recompiled(self.estimator, self.module, loss=loss,
                                    optimizer=optimizer, metrics=metrics)
        return self

    def set_checkpoint(self, path: str, over_write: bool = True,
                       trigger=None):
        """(ref: Topology.scala:249 setCheckpoint)."""
        self._checkpoint_dir = path
        self._checkpoint_trigger = trigger
        return self

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo"):
        """(ref: Topology.scala:208 setTensorBoard)."""
        import os

        self._log_dir = os.path.join(log_dir, app_name)
        return self

    def _require_compiled(self):
        if self.estimator is None:
            raise RuntimeError("call compile(optimizer, loss) before "
                               "fit/evaluate")

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, **kwargs):
        """(ref: Topology.scala fit; keras fit signature)."""
        self._require_compiled()
        data = (x, y) if y is not None else x
        return self.estimator.fit(
            data, batch_size=batch_size, epochs=nb_epoch,
            validation_data=validation_data,
            checkpoint_dir=self._checkpoint_dir,
            checkpoint_trigger=self._checkpoint_trigger,
            log_dir=self._log_dir, **kwargs)

    def evaluate(self, x, y=None, batch_size: int = 32):
        self._require_compiled()
        data = (x, y) if y is not None else x
        return self.estimator.evaluate(data, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32):
        if self.estimator is None:
            from analytics_zoo_tpu.learn.estimator import Estimator

            self.estimator = Estimator(self.module)
        return self.estimator.predict(x, batch_size=batch_size)

    def save_weights(self, path: str):
        self._require_compiled()
        self.estimator.save(path)

    def load_weights(self, path: str):
        self._require_compiled()
        self.estimator.load(path)

    def get_train_summary(self, tag: str = "train/loss"):
        """Read back TB scalars (ref: Topology.scala:1390
        getTrainSummary)."""
        from analytics_zoo_tpu.utils.summary import read_events

        if self._log_dir is None:
            raise RuntimeError("set_tensorboard was not called")
        return read_events(self._log_dir).get(tag, [])

    def get_validation_summary(self, metric: str = "loss"):
        """Read back validation scalars (ref: Topology.scala
        getValidationSummary); ``metric`` is the metric name, e.g.
        "accuracy"."""
        return self.get_train_summary(tag=f"validation/{metric}")


class Sequential(KerasNet):
    """(ref: Topology.scala:631+ Sequential, keras Sequential)."""

    def __init__(self, layers: Optional[Sequence] = None):
        super().__init__()
        self.layers: List = []
        for l in layers or []:
            self.add(l)

    def add(self, layer) -> "Sequential":
        if self._module is not None:
            raise RuntimeError("cannot add layers after the model is built")
        self.layers.append(layer)
        return self

    def _build_module(self) -> nn.Module:
        if not self.layers:
            raise ValueError("empty Sequential")
        return _SequentialModule(
            modules=tuple(l.build() for l in self.layers))

    def summary(self) -> str:
        lines = ["Sequential {"]
        for l in self.layers:
            lines.append(f"  {l!r}")
        lines.append("}")
        return "\n".join(lines)


class Model(KerasNet):
    """Functional graph model (ref: Topology.scala Model; keras Model)."""

    def __init__(self, input: Union[KTensor, Sequence[KTensor]],
                 output: KTensor):
        super().__init__()
        self.inputs: List[KTensor] = (list(input)
                                      if isinstance(input, (list, tuple))
                                      else [input])
        self.output = output
        for i, t in enumerate(self.inputs):
            if t.input_index is None:
                raise ValueError(f"input {i} is not an Input() tensor")

    def _build_module(self) -> nn.Module:
        # topo-sort nodes reachable from output
        order: List[Node] = []
        seen: Dict[int, int] = {}  # node id -> slot
        input_slot = {t.input_index: i for i, t in enumerate(self.inputs)}

        def slot_of(t: KTensor) -> int:
            if t.node is None:
                if t.input_index not in input_slot:
                    raise ValueError("graph references an Input that is "
                                     "not among the model inputs")
                return input_slot[t.input_index]
            if t.node.id not in seen:
                visit(t.node)
            return seen[t.node.id]

        def visit(node: Node):
            slots = tuple(slot_of(i) for i in node.inputs)
            node._slots = slots
            seen[node.id] = len(self.inputs) + len(order)
            order.append(node)

        out_slot = slot_of(self.output)
        assert out_slot == len(self.inputs) + len(order) - 1, \
            "output must be the last computed node"
        return _GraphModule(
            modules=tuple(n.layer.build() for n in order),
            input_slots=tuple(n._slots for n in order),
            n_inputs=len(self.inputs))

    def summary(self) -> str:
        return f"Model(inputs={len(self.inputs)})"
