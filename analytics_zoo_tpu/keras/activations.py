"""Activation registry (ref: zoo/.../keras/layers activations via
KerasUtils.getActivation; keras1 activation set)."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp


def linear(x):
    return x


def hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "hard_sigmoid": hard_sigmoid,
    "linear": linear,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "log_softmax": lambda x: jax.nn.log_softmax(x, axis=-1),
}


def get(name: Optional[Union[str, Callable]]) -> Callable:
    if name is None:
        return linear
    if callable(name):
        return name
    key = name.lower()
    if key not in _ACTIVATIONS:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}")
    return _ACTIVATIONS[key]
