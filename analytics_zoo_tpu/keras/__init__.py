"""Keras-style model API.

The analog of the reference's Keras layer library + topology
(ref: zoo/.../pipeline/api/keras -- 120 layer files, Topology.scala
KerasNet/Sequential/Model; pyzoo/zoo/pipeline/api/keras). Layers are
declarative configs that build flax modules; ``Sequential`` and graph
``Model`` compile into the SPMD Estimator (where the reference compiles
into BigDL's DistriOptimizer).

TPU-first deviations from the reference (deliberate):
- channels-last (NHWC) conv layout -- the TPU-native layout -- instead of
  BigDL's NCHW;
- weights are flax pytrees, not BigDL tensors; import/export helpers live
  in ``analytics_zoo_tpu.inference``.
"""

from analytics_zoo_tpu.keras.engine import (  # noqa: F401
    Input,
    KTensor,
    Model,
    Sequential,
)
from analytics_zoo_tpu.keras import layers  # noqa: F401
from analytics_zoo_tpu.keras import activations  # noqa: F401
from analytics_zoo_tpu.learn import objectives  # noqa: F401
