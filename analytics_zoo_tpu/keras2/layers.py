"""Keras-2-arg-name adapters over the keras layer library
(ref: zoo/.../pipeline/api/keras2/layers/*.scala -- Dense.scala maps
``units``, Conv*.scala map ``filters``/``kernel_size``/``strides``/
``padding``, Dropout.scala maps ``rate``, etc.)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

from analytics_zoo_tpu.keras import layers as k1
from analytics_zoo_tpu.keras.layers.convolutional import _tup

# shape-preserving layers keep identical signatures: re-export
Activation = k1.Activation
Flatten = k1.Flatten
GlobalAveragePooling1D = k1.GlobalAveragePooling1D
GlobalAveragePooling2D = k1.GlobalAveragePooling2D
GlobalAveragePooling3D = k1.GlobalAveragePooling3D
GlobalMaxPooling1D = k1.GlobalMaxPooling1D
GlobalMaxPooling2D = k1.GlobalMaxPooling2D
GlobalMaxPooling3D = k1.GlobalMaxPooling3D
Cropping1D = k1.Cropping1D
BatchNormalization = k1.BatchNormalization
Embedding = k1.Embedding


def _pair(v) -> Tuple[int, int]:
    return _tup(v, 2)


class Dense(k1.Dense):
    """keras2 Dense(units=...) (ref: keras2/layers/Dense.scala)."""

    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 **kwargs):
        super().__init__(output_dim=units, activation=activation,
                         bias=use_bias, **kwargs)


class Dropout(k1.Dropout):
    """keras2 Dropout(rate=...) (ref: keras2/layers/Dropout.scala)."""

    def __init__(self, rate: float, **kwargs):
        super().__init__(p=rate, **kwargs)


class Conv1D(k1.Convolution1D):
    """(ref: keras2/layers/Conv1D.scala)."""

    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, **kwargs):
        super().__init__(nb_filter=filters, filter_length=kernel_size,
                         subsample_length=strides, border_mode=padding,
                         activation=activation, bias=use_bias, **kwargs)


class Conv2D(k1.Convolution2D):
    """(ref: keras2/layers/Conv2D.scala)."""

    def __init__(self, filters: int,
                 kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, **kwargs):
        kh, kw = _pair(kernel_size)
        super().__init__(nb_filter=filters, nb_row=kh, nb_col=kw,
                         subsample=_pair(strides), border_mode=padding,
                         activation=activation, bias=use_bias, **kwargs)


class MaxPooling1D(k1.MaxPooling1D):
    def __init__(self, pool_size: int = 2,
                 strides: Optional[int] = None, padding: str = "valid",
                 **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding, **kwargs)


class MaxPooling2D(k1.MaxPooling2D):
    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding, **kwargs)


class AveragePooling1D(k1.AveragePooling1D):
    def __init__(self, pool_size: int = 2,
                 strides: Optional[int] = None, padding: str = "valid",
                 **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding, **kwargs)


class AveragePooling2D(k1.AveragePooling2D):
    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding, **kwargs)


class LSTM(k1.LSTM):
    """keras2 LSTM(units=...)."""

    def __init__(self, units: int, return_sequences: bool = False,
                 go_backwards: bool = False, **kwargs):
        super().__init__(output_dim=units,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards, **kwargs)


class GRU(k1.GRU):
    """keras2 GRU(units=...)."""

    def __init__(self, units: int, return_sequences: bool = False,
                 go_backwards: bool = False, **kwargs):
        super().__init__(output_dim=units,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards, **kwargs)


class LocallyConnected1D(k1.LocallyConnected1D):
    """(ref: keras2/layers/LocallyConnected1D.scala)."""

    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 activation=None, use_bias: bool = True, **kwargs):
        super().__init__(nb_filter=filters, filter_length=kernel_size,
                         subsample_length=strides, activation=activation,
                         bias=use_bias, **kwargs)


class Softmax(k1.Activation):
    """(ref: keras2/layers/Softmax.scala)."""

    def __init__(self, **kwargs):
        super().__init__(activation="softmax", **kwargs)
