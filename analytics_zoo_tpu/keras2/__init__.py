"""keras2: Keras-2-style layer API surface.

The analog of the reference's keras2 package
(ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras2/
-- 21 layer files re-exposing keras layers under Keras-2 argument
names; python surface pyzoo/zoo/pipeline/api/keras2/). Thin adapters:
``units``/``filters``/``kernel_size``/``strides``/``padding``/``rate``
map onto the keras-1-style layer library, and Sequential/Model/Input
re-export unchanged.
"""

from analytics_zoo_tpu.keras import Input, Model, Sequential  # noqa: F401
from analytics_zoo_tpu.keras2.layers import (  # noqa: F401
    Activation, AveragePooling1D, AveragePooling2D, BatchNormalization,
    Conv1D, Conv2D, Cropping1D, Dense, Dropout, Embedding, Flatten,
    GlobalAveragePooling1D, GlobalAveragePooling2D,
    GlobalAveragePooling3D, GlobalMaxPooling1D, GlobalMaxPooling2D,
    GlobalMaxPooling3D, GRU, LocallyConnected1D, LSTM, MaxPooling1D,
    MaxPooling2D, Softmax)
