"""Optimizers and LR schedules.

The analog of the reference's optimizer surface: BigDL OptimMethods exposed
through the Keras API plus zoo's own ``Adam`` and BERT-style
``AdamWeightDecay`` (ref: zoo/.../keras/optimizers/Adam.scala,
AdamWeightDecay.scala) and the ``Optim.Fixed`` LR schedule
(ref: zoo/.../common/Optim.scala:29). Backed by optax; each class is a
thin declarative config whose ``to_optax()`` yields the
GradientTransformation the Estimator chains with clipping.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import optax

ScheduleLike = Union[float, Callable[[Any], Any]]


class LearningRateSchedule:
    def to_optax(self) -> ScheduleLike:
        raise NotImplementedError


class Fixed(LearningRateSchedule):
    """Constant LR (ref: Optim.Fixed, common/Optim.scala:29)."""

    def __init__(self, lr: float):
        self.lr = lr

    def to_optax(self):
        return self.lr


class Poly(LearningRateSchedule):
    """Polynomial decay to zero over ``max_iteration`` steps (BigDL Poly)."""

    def __init__(self, power: float, max_iteration: int, lr: float):
        self.power, self.max_iteration, self.lr = power, max_iteration, lr

    def to_optax(self):
        return optax.polynomial_schedule(
            init_value=self.lr, end_value=0.0, power=self.power,
            transition_steps=self.max_iteration)


class Warmup(LearningRateSchedule):
    """Linear warmup then constant / linear decay (the schedule baked into
    the reference's AdamWeightDecay for BERT, ref: AdamWeightDecay.scala)."""

    def __init__(self, lr: float, warmup_steps: int,
                 total_steps: Optional[int] = None):
        self.lr, self.warmup_steps, self.total_steps = (
            lr, warmup_steps, total_steps)

    def to_optax(self):
        warm = optax.linear_schedule(0.0, self.lr, self.warmup_steps)
        if self.total_steps is None:
            return optax.join_schedules([warm, optax.constant_schedule(
                self.lr)], [self.warmup_steps])
        decay = optax.linear_schedule(
            self.lr, 0.0, max(self.total_steps - self.warmup_steps, 1))
        return optax.join_schedules([warm, decay], [self.warmup_steps])


def _as_schedule(lr) -> ScheduleLike:
    if isinstance(lr, LearningRateSchedule):
        return lr.to_optax()
    return lr


class ZooOptimizer:
    """Base optimizer config."""

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError


class SGD(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.lr, self.momentum = lr, momentum
        self.nesterov, self.weight_decay = nesterov, weight_decay

    def to_optax(self):
        tx = optax.sgd(_as_schedule(self.lr), momentum=self.momentum or None,
                       nesterov=self.nesterov)
        if self.weight_decay:
            tx = optax.chain(optax.add_decayed_weights(self.weight_decay), tx)
        return tx


class Adam(ZooOptimizer):
    """(ref: zoo/.../keras/optimizers/Adam.scala)."""

    def __init__(self, lr: ScheduleLike = 1e-3, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8):
        self.lr, self.beta_1, self.beta_2, self.epsilon = (
            lr, beta_1, beta_2, epsilon)

    def to_optax(self):
        return optax.adam(_as_schedule(self.lr), b1=self.beta_1,
                          b2=self.beta_2, eps=self.epsilon)


class AdamWeightDecay(ZooOptimizer):
    """BERT-style decoupled weight decay excluding LayerNorm/bias params
    (ref: zoo/.../keras/optimizers/AdamWeightDecay.scala)."""

    EXCLUDE = ("layer_norm", "layernorm", "ln", "bias", "scale")

    def __init__(self, lr: ScheduleLike = 1e-4, weight_decay: float = 0.01,
                 beta_1: float = 0.9, beta_2: float = 0.999,
                 epsilon: float = 1e-6,
                 exclude_from_weight_decay: Optional[Sequence[str]] = None):
        self.lr, self.weight_decay = lr, weight_decay
        self.beta_1, self.beta_2, self.epsilon = beta_1, beta_2, epsilon
        self.exclude = tuple(exclude_from_weight_decay
                             if exclude_from_weight_decay is not None
                             else self.EXCLUDE)

    def to_optax(self):
        import jax

        def mask(params):
            def keep(path, _):
                names = [str(getattr(k, "key", getattr(k, "name", k))).lower()
                         for k in path]
                return not any(e in n for n in names for e in self.exclude)

            return jax.tree_util.tree_map_with_path(keep, params)

        return optax.adamw(_as_schedule(self.lr), b1=self.beta_1,
                           b2=self.beta_2, eps=self.epsilon,
                           weight_decay=self.weight_decay, mask=mask)


class RMSprop(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 1e-3, decay_rate: float = 0.9,
                 epsilon: float = 1e-8):
        self.lr, self.decay_rate, self.epsilon = lr, decay_rate, epsilon

    def to_optax(self):
        return optax.rmsprop(_as_schedule(self.lr), decay=self.decay_rate,
                             eps=self.epsilon)


class Adagrad(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 1e-2):
        self.lr = lr

    def to_optax(self):
        return optax.adagrad(_as_schedule(self.lr))


class Adadelta(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 1.0, rho: float = 0.9,
                 epsilon: float = 1e-6):
        self.lr, self.rho, self.epsilon = lr, rho, epsilon

    def to_optax(self):
        return optax.adadelta(_as_schedule(self.lr), rho=self.rho,
                              eps=self.epsilon)


def resolve_optimizer(opt) -> optax.GradientTransformation:
    """Accept a ZooOptimizer, an optax transformation, or a name."""
    if isinstance(opt, ZooOptimizer):
        return opt.to_optax()
    if isinstance(opt, optax.GradientTransformation):
        return opt
    if isinstance(opt, str):
        table = {"sgd": SGD, "adam": Adam, "adamw": AdamWeightDecay,
                 "adamweightdecay": AdamWeightDecay, "rmsprop": RMSprop,
                 "adagrad": Adagrad, "adadelta": Adadelta}
        key = opt.lower()
        if key not in table:
            raise ValueError(f"unknown optimizer {opt!r}")
        return table[key]().to_optax()
    raise TypeError(f"cannot interpret optimizer {opt!r}")
