"""GANEstimator: alternating generator/discriminator training.

The analog of the reference's TFPark GAN path
(ref: pyzoo/zoo/tfpark/gan/gan_estimator.py:28-160 -- alternating
optimization driven through ``GanOptimMethod.scala`` which counts
gen/dis steps inside one BigDL optimizer). TPU-first collapse: ONE
jitted SPMD step runs ``discriminator_steps`` D updates then
``generator_steps`` G updates via ``lax.fori_loop``, so the whole
alternation compiles once and never returns to Python mid-cycle.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.learn.estimator import _as_dataset
from analytics_zoo_tpu.learn.optim import resolve_optimizer

logger = get_logger(__name__)


def generator_loss_nonsaturating(fake_logits):
    """-log D(G(z)) (the standard non-saturating generator loss)."""
    return -jnp.mean(jax.nn.log_sigmoid(fake_logits))


def discriminator_loss_vanilla(real_logits, fake_logits):
    """-log D(x) - log(1 - D(G(z)))."""
    return -(jnp.mean(jax.nn.log_sigmoid(real_logits)) +
             jnp.mean(jax.nn.log_sigmoid(-fake_logits)))


class GANEstimator:
    """Alternating GAN training on a mesh.

    Args:
      generator_fn: flax module mapping noise [B, Z] -> samples.
      discriminator_fn: flax module mapping samples -> logits [B] (or
        [B, 1]).
      generator_loss_fn: fn(fake_logits) -> scalar.
      discriminator_loss_fn: fn(real_logits, fake_logits) -> scalar.
      generator_optimizer / discriminator_optimizer: ZooOptimizer /
        optax transformation / name.
      noise_dim: size of the z vector sampled per step.
      generator_steps / discriminator_steps: updates per alternation
        cycle (ref: gan_estimator.py generator_steps/discriminator_steps).
    """

    def __init__(self, generator_fn, discriminator_fn,
                 generator_loss_fn: Callable = generator_loss_nonsaturating,
                 discriminator_loss_fn: Callable =
                 discriminator_loss_vanilla,
                 generator_optimizer: Any = "adam",
                 discriminator_optimizer: Any = "adam",
                 noise_dim: int = 16, generator_steps: int = 1,
                 discriminator_steps: int = 1, seed: int = 0):
        self.generator = generator_fn
        self.discriminator = discriminator_fn
        self.g_loss_fn = generator_loss_fn
        self.d_loss_fn = discriminator_loss_fn
        self.g_tx = resolve_optimizer(generator_optimizer)
        self.d_tx = resolve_optimizer(discriminator_optimizer)
        self.noise_dim = noise_dim
        self.generator_steps = generator_steps
        self.discriminator_steps = discriminator_steps
        self.g_vars = None
        self.d_vars = None
        self.g_opt = None
        self.d_opt = None
        from analytics_zoo_tpu.learn.estimator import training_prng_key

        self._rng = training_prng_key(seed)
        self._step = None

    # ------------------------------------------------------------ build --
    def _ensure_built(self) -> None:
        if self.g_vars is not None:
            return
        self._rng, gk, dk = jax.random.split(self._rng, 3)
        z = jnp.zeros((1, self.noise_dim), jnp.float32)
        self.g_vars = self.generator.init(gk, z)
        fake = self.generator.apply(self.g_vars, z)
        self.d_vars = self.discriminator.init(dk, fake)
        self.g_opt = self.g_tx.init(self.g_vars["params"])
        self.d_opt = self.d_tx.init(self.d_vars["params"])
        n_g = sum(int(np.prod(l.shape)) for l in
                  jax.tree_util.tree_leaves(self.g_vars))
        n_d = sum(int(np.prod(l.shape)) for l in
                  jax.tree_util.tree_leaves(self.d_vars))
        logger.info("GAN built: G %d params, D %d params", n_g, n_d)

    def _build_step(self):
        if self._step is not None:
            return self._step
        gen, dis = self.generator, self.discriminator
        g_loss_fn, d_loss_fn = self.g_loss_fn, self.d_loss_fn
        g_tx, d_tx = self.g_tx, self.d_tx
        nz = self.noise_dim
        d_steps, g_steps = self.discriminator_steps, self.generator_steps
        import optax

        def d_update(carry, rng, real):
            g_vars, d_vars, g_opt, d_opt = carry
            z = jax.random.normal(rng, (real.shape[0], nz))
            fake = gen.apply(g_vars, z)

            def loss(dp):
                dv = {**d_vars, "params": dp}
                return d_loss_fn(dis.apply(dv, real),
                                 dis.apply(dv, fake))

            l, grads = jax.value_and_grad(loss)(d_vars["params"])
            updates, d_opt = d_tx.update(grads, d_opt, d_vars["params"])
            d_vars = {**d_vars,
                      "params": optax.apply_updates(d_vars["params"],
                                                    updates)}
            return (g_vars, d_vars, g_opt, d_opt), l

        def g_update(carry, rng, real):
            g_vars, d_vars, g_opt, d_opt = carry
            z = jax.random.normal(rng, (real.shape[0], nz))

            def loss(gp):
                gv = {**g_vars, "params": gp}
                return g_loss_fn(dis.apply(d_vars, gen.apply(gv, z)))

            l, grads = jax.value_and_grad(loss)(g_vars["params"])
            updates, g_opt = g_tx.update(grads, g_opt, g_vars["params"])
            g_vars = {**g_vars,
                      "params": optax.apply_updates(g_vars["params"],
                                                    updates)}
            return (g_vars, d_vars, g_opt, d_opt), l

        def step(g_vars, d_vars, g_opt, d_opt, real, rng):
            carry = (g_vars, d_vars, g_opt, d_opt)
            rngs = jax.random.split(rng, d_steps + g_steps)
            d_loss = jnp.zeros(())
            for i in range(d_steps):  # unrolled: steps are static + few
                carry, d_loss = d_update(carry, rngs[i], real)
            g_loss = jnp.zeros(())
            for i in range(g_steps):
                carry, g_loss = g_update(carry, rngs[d_steps + i], real)
            g_vars, d_vars, g_opt, d_opt = carry
            return g_vars, d_vars, g_opt, d_opt, d_loss, g_loss

        self._step = jax.jit(step)
        return self._step

    # -------------------------------------------------------------- fit --
    def fit(self, data, batch_size: int, epochs: int = 1
            ) -> List[Dict[str, float]]:
        dataset = _as_dataset(data, labeled=False)
        if dataset.num_samples < batch_size:
            raise ValueError(
                f"dataset ({dataset.num_samples} samples) is smaller "
                f"than batch_size {batch_size}")
        self._ensure_built()
        step = self._build_step()
        history: List[Dict[str, float]] = []
        for epoch in range(epochs):
            t0 = time.time()
            d_sum = g_sum = jnp.zeros(())
            n = 0
            for x, _ in dataset.device_iterator(batch_size,
                                                shuffle=True,
                                                epoch=epoch):
                self._rng, k = jax.random.split(self._rng)
                (self.g_vars, self.d_vars, self.g_opt, self.d_opt,
                 d_loss, g_loss) = step(self.g_vars, self.d_vars,
                                        self.g_opt, self.d_opt, x, k)
                d_sum = d_sum + d_loss
                g_sum = g_sum + g_loss
                n += 1
            entry = {"epoch": epoch + 1,
                     "d_loss": float(d_sum) / max(n, 1),
                     "g_loss": float(g_sum) / max(n, 1),
                     "seconds": time.time() - t0}
            history.append(entry)
            logger.info("GAN epoch %d: %s", epoch + 1, entry)
        return history

    # ---------------------------------------------------------- generate --
    def generate(self, n: int, rng: Optional[jax.Array] = None
                 ) -> np.ndarray:
        """Sample n outputs from the current generator."""
        if self.g_vars is None:
            raise ValueError("fit (or build) before generate")
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        z = jax.random.normal(rng, (n, self.noise_dim))
        return np.asarray(self.generator.apply(self.g_vars, z))
