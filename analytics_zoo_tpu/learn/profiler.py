"""Training-side profiling.

Round-1 gap (VERDICT row 29): the reference profiles serving via
``Timer`` and training via BigDL ``Metrics`` counters + Ray runners'
``profile=True`` per-epoch time stats
(ref: zoo/.../serving/engine/Timer.scala:24-90,
pyzoo/zoo/orca/learn/pytorch/pytorch_ray_estimator.py:150-190,
torch_runner.py:308-316). Here training profiling has two layers:

- ``TrainingProfiler``: host-side stage timers (data wait vs step
  dispatch vs epoch wall time) with the same count/avg/max/min summary
  shape as the serving Timer -- answers "am I input-bound?". Since
  ISSUE-2 every stage duration also lands in the process-wide obs
  registry (``zoo_learn_stage_duration_seconds{stage=...}``), so
  training and serving share one scrape vocabulary.
- XLA device tracing: ``jax.profiler`` traces written to a TensorBoard
  -loadable directory when ``trace_dir`` is set -- answers "what is the
  chip doing?" (the reference has no analog; BigDL had no device
  profiler).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

from analytics_zoo_tpu.common.log import Timer
from analytics_zoo_tpu.obs.metrics import get_registry

_M_LEARN_STAGE = get_registry().histogram(
    "zoo_learn_stage_duration_seconds",
    "Training stage latency (data_wait, train_step, epoch, ...)",
    labelnames=("stage",))


class TrainingProfiler:
    """Stage timers + optional jax.profiler trace for one fit() run."""

    def __init__(self, trace_dir: Optional[str] = None):
        self.timer = Timer(mirror=_M_LEARN_STAGE)
        self.trace_dir = trace_dir
        self._tracing = False

    # ------------------------------------------------------ stage timing --
    @contextlib.contextmanager
    def timing(self, stage: str):
        """Host timer for the stage; while a device trace is active the
        stage also appears as a named region on the trace timeline."""
        with self.timer.timing(stage):
            if self._tracing:
                with self.step_annotation(stage):
                    yield
            else:
                yield

    # ------------------------------------------------------- device trace --
    def start_trace(self) -> None:
        if self.trace_dir and not self._tracing:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def stop_trace(self) -> None:
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False

    @contextlib.contextmanager
    def step_annotation(self, name: str):
        """Named region visible in the device trace timeline."""
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield

    # ----------------------------------------------------------- results --
    def summary(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for name, stat in self.timer.stats().items():
            out[name] = {"count": stat.count,
                         "total_s": round(stat.total, 6),
                         "avg_s": round(stat.avg, 6),
                         "max_s": round(stat.max, 6),
                         "min_s": round(stat.min if stat.count else 0.0,
                                        6)}
        return out

    @property
    def input_bound_fraction(self) -> Optional[float]:
        """Fraction of loop time spent waiting on data -- > ~0.3 means
        the input pipeline, not the chip, sets throughput."""
        stats = self.timer.stats()
        data = stats.get("data_wait")
        step = stats.get("train_step")
        if not data or not step or (data.total + step.total) == 0:
            return None
        return data.total / (data.total + step.total)
