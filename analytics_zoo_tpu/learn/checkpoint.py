"""Checkpoint save/restore.

The analog of BigDL-format snapshots ``model.<iter>`` +
``optimMethod-<name>.<iter>`` written into timestamped dirs on a
checkpoint trigger (ref: zoo/.../keras/models/Topology.scala:1246-1252,
NNEstimator.scala:464-470) and of ``TFOptimizer.load_checkpoint``
(ref: pyzoo/zoo/tfpark/tf_optimizer.py:398-411).

Format: ``<dir>/model.<step>`` and ``<dir>/optim.<step>`` are flax
msgpack-serialized pytrees; ``<dir>/meta.<step>.json`` carries counters;
``<dir>/latest`` names the newest step. Multi-process runs write from
process 0 only and barrier afterwards.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
from flax import serialization

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.parallel import sharding as sharding_lib
from analytics_zoo_tpu.utils import fileio

logger = get_logger(__name__)


def save_checkpoint(ckpt_dir: str, variables: Any, opt_state: Any,
                    step: int, epoch: int,
                    extra_meta: Optional[Dict] = None) -> str:
    """Write a snapshot; returns the checkpoint path prefix."""
    # with cross-host parameter sharding (param_spec_fn) arrays are not
    # fully addressable on process 0, so gather collectively first --
    # every process must participate, hence outside the index-0 branch
    host_vars = sharding_lib.gather_to_host(variables)
    host_opt = sharding_lib.gather_to_host(opt_state)
    if jax.process_index() == 0:
        fileio.makedirs(ckpt_dir, exist_ok=True)
        _atomic_write(fileio.join(ckpt_dir, f"model.{step}"),
                      serialization.to_bytes(host_vars))
        _atomic_write(fileio.join(ckpt_dir, f"optim.{step}"),
                      serialization.to_bytes(host_opt))
        meta = {"step": int(step), "epoch": int(epoch)}
        if extra_meta:
            meta.update(extra_meta)
        _atomic_write(fileio.join(ckpt_dir, f"meta.{step}.json"),
                      json.dumps(meta).encode())
        _atomic_write(fileio.join(ckpt_dir, "latest"), str(step).encode())
        logger.info("checkpoint saved: %s step=%d", ckpt_dir, step)
    _barrier()
    return fileio.join(ckpt_dir, f"model.{step}")


def latest_step(ckpt_dir: str) -> Optional[int]:
    path = fileio.join(ckpt_dir, "latest")
    if not fileio.exists(path):
        return None
    return int(fileio.read_bytes(path).decode().strip())


def load_checkpoint(ckpt_dir: str, variables_template: Any,
                    opt_state_template: Any,
                    step: Optional[int] = None
                    ) -> Tuple[Any, Any, Dict]:
    """Restore (variables, opt_state, meta); templates supply the pytree
    structure (flax msgpack is structure-less on disk). A None
    ``variables_template`` restores the raw dict tree (model variables
    are plain nested dicts, so no template is needed)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_dir!r}")
    data = fileio.read_bytes(fileio.join(ckpt_dir, f"model.{step}"))
    if variables_template is None:
        variables = serialization.msgpack_restore(data)
    else:
        variables = serialization.from_bytes(
            jax.device_get(variables_template), data)
    if opt_state_template is None:
        opt_state = None  # caller only wants model variables
    else:
        raw = fileio.read_bytes(fileio.join(ckpt_dir, f"optim.{step}"))
        try:
            opt_state = serialization.from_bytes(
                jax.device_get(opt_state_template), raw)
        except ValueError as e:
            raise ValueError(
                "optimizer state in the checkpoint does not match this "
                "Estimator's optimizer config (optimizer type and "
                "clip_norm/clip_value must match the run that saved "
                f"it): {e}") from e
    meta = json.loads(fileio.read_bytes(
        fileio.join(ckpt_dir, f"meta.{step}.json")).decode())
    logger.info("checkpoint restored: %s step=%d", ckpt_dir, step)
    return variables, opt_state, meta


def _atomic_write(path: str, data: bytes) -> None:
    if fileio.is_remote(path):
        # object-store writes are already all-or-nothing at commit
        # (no partially-visible object), which is the property the
        # local tmp+rename dance buys
        fileio.write_bytes(path, data)
        return
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        # fsync BEFORE the rename: without it a SIGKILL/power-cut can
        # leave the rename durable but the data not, i.e. `latest`
        # pointing at a truncated checkpoint -- the one artifact a
        # crash must never corrupt
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        # and the directory entry itself, so the rename survives too
        dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError as e:
        # some filesystems refuse directory fsync; the data fsync
        # above already bounds the damage to "old checkpoint visible"
        logger.debug("directory fsync after %s skipped: %s", path, e)


def _barrier() -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("zoo_checkpoint")
