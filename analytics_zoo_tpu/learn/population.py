"""PopulationEstimator: N models as ONE XLA program.

The TPU-native inversion of the reference's one-trial-per-Ray-worker
AutoML shape (ref: pyzoo/zoo/automl/search/ray_tune_search_engine.py):
instead of N processes each fitting one model, N parameter trees are
stacked along a leading *member* axis and trained by a single jitted
``jax.vmap`` step. Hyperparameters that only scale the update --
learning rate and (decoupled) weight decay -- ride as traced per-lane
scalars, so one compiled executable covers every member's setting.

Member *masking* keeps shapes fixed across a search: a culled lane
trains at zero effective lr with its parameters/optimizer state frozen
by a select, rather than being removed from the stack -- ASHA rung
promotion never changes array shapes, so it never recompiles.

Per-member training replays the exact per-member semantics of
:class:`~analytics_zoo_tpu.learn.estimator.Estimator`'s per-step fit
path (same PRNG stream: one split at init, one split per step; same
epoch-seeded host-side shuffle; same Adam update), so a lane's
trajectory matches what a solo ``Estimator(seed=s)`` run of the same
config produces -- the property the vectorized AutoML executor's
parity gate (`tests/test_vectorized_search.py`) enforces.

All data arguments carry the member axis: ``x`` is ``[N, B, ...]``
(use :meth:`PopulationEstimator.stack_data` to broadcast shared data).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.learn.estimator import (
    _SOW_COLLECTIONS, FlaxModelAdapter, _is_flax_module)
from analytics_zoo_tpu.learn.objectives import resolve_loss
from analytics_zoo_tpu.obs.events import instrument_compiles
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

_M_PSTEPS = get_registry().counter(
    "zoo_population_steps_total",
    "Vectorized population train steps (one step updates every lane)")
_M_PMEMBERS = get_registry().gauge(
    "zoo_population_members_items",
    "Member lanes in the most recently built population")
_M_PMASKED = get_registry().gauge(
    "zoo_population_masked_items",
    "Masked (frozen) lanes in the most recently used population")


def _shuffle_order(seed: int, epoch: int, n: int) -> np.ndarray:
    """The Estimator fit path's epoch permutation, verbatim
    (ZooDataset.batches): parity depends on byte-identical batch
    order, so the constant is shared by construction, not by copy."""
    rng = np.random.RandomState((seed * 100003 + epoch) & 0x7FFFFFFF)
    return rng.permutation(n)


class PopulationEstimator:
    """Train/eval N stacked models with one compiled vmapped step.

    Args:
      model: a flax module (shared architecture for every member) or a
        prebuilt adapter with ``init``/``apply``.
      n_members: lane count N (inferred from ``lr``/``seeds`` arrays).
      loss: loss name or ``fn(preds, labels) -> scalar``.
      lr: scalar or ``[N]`` per-lane learning rates (traced, not
        compiled in: changing a lane's lr never recompiles).
      weight_decay: scalar or ``[N]`` decoupled weight decay lanes.
      beta_1 / beta_2 / epsilon: Adam moments config (matches
        ``learn.optim.Adam`` defaults so a lane reproduces
        ``Estimator(optimizer=Adam(lr))`` exactly).
      seeds: ``[N]`` per-member init/dropout seeds (vmapped seeded
        init). Default: every lane seed 0 -- the Estimator default, so
        AutoML lanes that differ only in lr share the solo path's init.
      aux_loss_collections: sown collections summed into the training
        objective per step (same contract as Estimator).
    """

    def __init__(self, model, n_members: Optional[int] = None,
                 loss: Any = "mse", lr: Any = 1e-3,
                 weight_decay: Any = 0.0, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8,
                 seeds: Optional[Sequence[int]] = None,
                 aux_loss_collections: Sequence[str] = ("losses",)):
        self.adapter = (model if hasattr(model, "apply")
                        and hasattr(model, "init")
                        and not _is_flax_module(model)
                        else FlaxModelAdapter(model))
        self.loss_fn = resolve_loss(loss)
        lr_arr = np.atleast_1d(np.asarray(lr, np.float32))
        wd_arr = np.atleast_1d(np.asarray(weight_decay, np.float32))
        n = n_members or max(len(lr_arr), len(wd_arr),
                             len(seeds) if seeds is not None else 1)
        cap = int(get_config().get("zoo.population.max_members", 1024))
        if n < 1 or n > cap:
            raise ValueError(
                f"population needs 1..{cap} members, got {n} "
                "(raise zoo.population.max_members to go bigger)")
        self.n_members = n
        self.lr = jnp.broadcast_to(jnp.asarray(lr_arr), (n,))
        self.weight_decay = jnp.broadcast_to(jnp.asarray(wd_arr), (n,))
        self.beta_1, self.beta_2, self.epsilon = beta_1, beta_2, epsilon
        self.seeds = (list(seeds) if seeds is not None else [0] * n)
        if len(self.seeds) != n:
            raise ValueError(f"seeds must have {n} entries")
        self.aux_loss_collections = tuple(aux_loss_collections)
        # shuffle stream seed -- Estimator's ``seed`` ctor arg; lanes
        # share one epoch permutation (solo runs all use seed=0 too)
        self.shuffle_seed = 0
        self.mask = jnp.ones((n,), jnp.float32)
        self.epoch = 0
        self.variables = None   # stacked: every leaf is [N, ...]
        self.opt_state = None
        self._rngs = None       # [N] per-lane training PRNG keys
        self._train_step = None
        self._predict_fn = None
        import optax

        self._core = optax.scale_by_adam(
            b1=beta_1, b2=beta_2, eps=epsilon)
        _M_PMEMBERS.set(float(n))

    # ------------------------------------------------------------ data --
    @staticmethod
    def stack_data(x, n: int):
        """Broadcast shared (memberless) data to the ``[N, ...]``
        layout every fit/predict argument uses."""
        return jax.tree_util.tree_map(
            lambda a: np.broadcast_to(
                np.asarray(a)[None], (n,) + np.asarray(a).shape), x)

    # ----------------------------------------------------------- build --
    def _ensure_built(self, example_x) -> None:
        if self.variables is not None:
            return
        # per-lane stream: PRNGKey(seed) then ONE split -- row 0 carries
        # on as the training stream, row 1 initializes (the exact
        # Estimator._ensure_built sequence, per lane)
        keys0 = jnp.stack([jax.random.PRNGKey(int(s))
                           for s in self.seeds])
        both = jax.vmap(jax.random.split)(keys0)
        self._rngs, init_rngs = both[:, 0], both[:, 1]
        small = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[:, :1], example_x)
        self.variables = jax.vmap(
            lambda k, xs: self.adapter.init(k, xs))(init_rngs, small)
        self.opt_state = jax.vmap(self._core.init)(
            self.variables.get("params", {}))
        n_params = sum(int(np.prod(l.shape)) for l in
                       jax.tree_util.tree_leaves(
                           self.variables.get("params", {})))
        logger.info("population built: %d members, %d stacked params",
                    self.n_members, n_params)

    # ------------------------------------------------------ train step --
    def _member_step(self, variables, opt_state, x, y, rng, lr, wd,
                     mask):
        """One member's SGD update -- Estimator._step_math with the lr
        applied per-lane (the optimizer core is lr-free scale_by_adam;
        ``optax.adam(lr)`` is exactly that core followed by a -lr
        scale, so a lane reproduces the solo Adam trajectory)."""
        import optax

        adapter, loss_fn = self.adapter, self.loss_fn
        aux_colls = self.aux_loss_collections
        new_rng, step_rng = jax.random.split(rng)
        params = variables.get("params", {})
        extra = {k: v for k, v in variables.items() if k != "params"}

        def compute_loss(p, xb, yb, srng):
            preds, new_extra = adapter.apply(
                {"params": p, **extra}, xb, training=True, rng=srng)
            loss = loss_fn(preds, yb)
            for coll in aux_colls:
                if coll in new_extra:
                    for leaf in jax.tree_util.tree_leaves(
                            new_extra[coll]):
                        loss = loss + jnp.sum(leaf)
            new_extra = {k: v for k, v in new_extra.items()
                         if k not in aux_colls
                         and k not in _SOW_COLLECTIONS}
            return loss, new_extra

        (loss, new_extra), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params, x, y, step_rng)
        updates, new_opt = self._core.update(grads, opt_state, params)
        lr_eff = lr * mask
        updates = jax.tree_util.tree_map(
            lambda u, p: -lr_eff * (u + wd * p), updates, params)
        new_params = optax.apply_updates(params, updates)
        # a masked lane is FROZEN, not merely zero-stepped: optimizer
        # moments and mutable collections hold too, so unmasking (or
        # exporting) later sees exactly the state at mask time
        keep = mask > 0

        def sel(new, old):
            return jnp.where(keep, new, old)

        new_vars = {"params": jax.tree_util.tree_map(
            lambda n_, o: sel(n_, o), new_params, params)}
        for k, v in new_extra.items():
            new_vars[k] = jax.tree_util.tree_map(
                lambda n_, o: sel(n_, o), v, extra[k])
        for k, v in extra.items():
            new_vars.setdefault(k, v)
        new_opt = jax.tree_util.tree_map(
            lambda n_, o: sel(n_, o), new_opt, opt_state)
        return new_vars, new_opt, loss, new_rng

    def _build_train_step(self):
        if self._train_step is not None:
            return self._train_step
        donate = get_config().get("zoo.train.donate_buffers")
        stepv = jax.vmap(self._member_step)

        def step(variables, opt_state, x, y, rngs, lr, wd, mask):
            return stepv(variables, opt_state, x, y, rngs, lr, wd,
                         mask)

        self._train_step = instrument_compiles(
            jax.jit(step, donate_argnums=(0, 1) if donate else ()),
            "population.train_step", subsystem="learn")
        return self._train_step

    # ------------------------------------------------------------- fit --
    def fit(self, x, y, batch_size: int, epochs: int,
            budgets: Optional[Sequence[int]] = None) -> List[np.ndarray]:
        """Train every unmasked lane from ``self.epoch`` up to
        ``epochs`` (absolute, the Estimator.fit convention). ``x``/``y``
        are member-stacked ``[N, B, ...]`` arrays; every lane sees the
        same epoch permutation (shared shuffle seed) over its own data
        lane. ``budgets`` gives per-lane absolute epoch targets: a lane
        freezes once ``epoch >= budget`` (fixed-shape ASHA masking --
        heterogeneous epoch budgets train lockstep without reshaping).
        Returns per-epoch mean-loss vectors ``[N]``."""
        x = np.asarray(x)
        y = np.asarray(y)
        n = self.n_members
        if x.shape[0] != n or y.shape[0] != n:
            raise ValueError(
                f"x/y must be member-stacked [N={n}, B, ...]; got "
                f"{x.shape} / {y.shape}")
        n_samples = x.shape[1]
        batch_size = max(1, min(int(batch_size), n_samples))
        self._ensure_built(x)
        step = self._build_train_step()
        budget_arr = (np.asarray(budgets, np.int32)
                      if budgets is not None else None)
        history: List[np.ndarray] = []
        steps_per_epoch = n_samples // batch_size
        while self.epoch < epochs:
            mask = self.mask
            if budget_arr is not None:
                mask = mask * jnp.asarray(
                    (budget_arr > self.epoch).astype(np.float32))
            _M_PMASKED.set(float(n - int(jnp.sum(mask > 0))))
            order = _shuffle_order(self.shuffle_seed, self.epoch,
                                   n_samples)
            losses = np.zeros((n,), np.float32)
            for b in range(steps_per_epoch):
                idx = order[b * batch_size:(b + 1) * batch_size]
                xb, yb = x[:, idx], y[:, idx]
                (self.variables, self.opt_state, loss,
                 self._rngs) = step(self.variables, self.opt_state,
                                    xb, yb, self._rngs, self.lr,
                                    self.weight_decay, mask)
                _M_PSTEPS.inc()
                losses = losses + np.asarray(loss)
            history.append(losses / max(steps_per_epoch, 1))
            self.epoch += 1
        return history

    # ----------------------------------------------------- eval / mask --
    def predict(self, x) -> np.ndarray:
        """Vmapped inference apply: ``[N, B, ...]`` -> stacked member
        predictions (one dispatch for the whole population)."""
        self._ensure_built(x)
        if self._predict_fn is None:
            adapter = self.adapter

            def pred(variables, xb):
                out, _ = adapter.apply(variables, xb, training=False)
                return out

            self._predict_fn = instrument_compiles(
                jax.jit(jax.vmap(pred)), "population.predict",
                subsystem="learn")
        return np.asarray(self._predict_fn(
            self.variables, jnp.asarray(np.asarray(x))))

    def ensemble_predict(self, x):
        """Shared-input ensemble: every member answers the SAME batch;
        returns ``(mean, variance)`` over the member axis -- the
        population variance is the confidence signal the reference
        model zoo's anomaly-detection scenario thresholds on."""
        stacked = self.stack_data(np.asarray(x), self.n_members)
        preds = self.predict(stacked)
        return preds.mean(axis=0), preds.var(axis=0)

    def set_mask(self, mask) -> None:
        """``[N]`` 0/1 lane mask; 0 freezes a lane (zero effective lr
        AND held optimizer/mutable state). Shapes never change, so
        re-masking never recompiles."""
        mask = np.asarray(mask, np.float32).reshape(self.n_members)
        self.mask = jnp.asarray(mask)
        _M_PMASKED.set(float(np.sum(mask <= 0)))

    # ---------------------------------------------------------- export --
    def export_member(self, i: int) -> Dict[str, Any]:
        """Member ``i`` as a plain (unstacked) variables tree --
        drop-in for ``Estimator.variables`` / flax serialization."""
        if self.variables is None:
            raise RuntimeError("population not built; fit() first")
        if not 0 <= i < self.n_members:
            raise IndexError(f"member {i} out of range")
        return jax.device_get(jax.tree_util.tree_map(
            lambda a: a[i], self.variables))

    def export_member_bytes(self, i: int) -> bytes:
        """Member ``i`` serialized exactly like
        ``TimeSequenceModel.state_bytes`` (flax ``to_bytes`` of the
        variables tree), so vectorized trial outputs rebuild through
        the same ``load_state_bytes`` path as pool-trial outputs."""
        from flax.serialization import to_bytes

        return to_bytes(self.export_member(i))
