"""Loss functions (objectives).

The analog of the reference's 15 objectives
(ref: zoo/.../pipeline/api/keras/objectives/ -- SparseCategoricalCrossEntropy,
CategoricalCrossEntropy, BinaryCrossEntropy, MeanSquaredError,
MeanAbsoluteError, MeanAbsolutePercentageError, MeanSquaredLogarithmicError,
Hinge, SquaredHinge, Poisson, CosineProximity, KullbackLeiblerDivergence,
RankHinge). Every loss is ``fn(preds, labels) -> scalar batch mean``;
computed on globally-sharded batches under jit, so the mean is the global
batch mean (matching BigDL's global-batch loss semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


def sparse_categorical_crossentropy(preds, labels, from_logits: bool = True):
    labels = jnp.asarray(labels).reshape(-1).astype(jnp.int32)
    if from_logits:
        logp = jax.nn.log_softmax(preds, -1)
    else:
        logp = jnp.log(jnp.clip(preds, _EPS, 1.0))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)
    return jnp.mean(nll)


def categorical_crossentropy(preds, labels, from_logits: bool = True):
    labels = jnp.asarray(labels, jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(preds, -1)
    else:
        logp = jnp.log(jnp.clip(preds, _EPS, 1.0))
    return -jnp.mean(jnp.sum(labels * logp, -1))


def binary_crossentropy(preds, labels, from_logits: bool = False):
    y = jnp.asarray(labels, jnp.float32).reshape(preds.shape)
    if from_logits:
        return jnp.mean(
            jnp.maximum(preds, 0) - preds * y +
            jnp.log1p(jnp.exp(-jnp.abs(preds))))
    p = jnp.clip(preds, _EPS, 1 - _EPS)
    return -jnp.mean(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))


def mean_squared_error(preds, labels):
    return jnp.mean(jnp.square(preds - jnp.asarray(
        labels, preds.dtype).reshape(preds.shape)))


def mean_absolute_error(preds, labels):
    return jnp.mean(jnp.abs(preds - jnp.asarray(
        labels, preds.dtype).reshape(preds.shape)))


def mean_absolute_percentage_error(preds, labels):
    y = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    return 100.0 * jnp.mean(jnp.abs((y - preds) /
                                    jnp.clip(jnp.abs(y), _EPS)))


def mean_squared_logarithmic_error(preds, labels):
    y = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    return jnp.mean(jnp.square(jnp.log1p(jnp.clip(y, 0)) -
                               jnp.log1p(jnp.clip(preds, 0))))


def hinge(preds, labels):
    y = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    y = jnp.where(y > 0, 1.0, -1.0)
    return jnp.mean(jnp.maximum(1.0 - y * preds, 0.0))


def squared_hinge(preds, labels):
    y = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    y = jnp.where(y > 0, 1.0, -1.0)
    return jnp.mean(jnp.square(jnp.maximum(1.0 - y * preds, 0.0)))


def poisson(preds, labels):
    y = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    return jnp.mean(preds - y * jnp.log(preds + _EPS))


def cosine_proximity(preds, labels):
    y = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    p = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True),
                            _EPS)
    y = y / jnp.maximum(jnp.linalg.norm(y, axis=-1, keepdims=True), _EPS)
    return -jnp.mean(jnp.sum(p * y, -1))


def kullback_leibler_divergence(preds, labels):
    y = jnp.clip(jnp.asarray(labels, preds.dtype).reshape(preds.shape),
                 _EPS, 1.0)
    p = jnp.clip(preds, _EPS, 1.0)
    return jnp.mean(jnp.sum(y * jnp.log(y / p), -1))


def rank_hinge(preds, labels, margin: float = 1.0):
    """Pairwise ranking hinge over interleaved (pos, neg) pairs: preds
    [B,2] rows of (pos, neg), or flat [2B] laid out
    pos0,neg0,pos1,neg1,... (ref: objectives/RankHinge.scala used by
    KNRM text matching)."""
    flat = preds.reshape(-1)
    pos, neg = flat[0::2], flat[1::2]
    return jnp.mean(jnp.maximum(margin - pos + neg, 0.0))


_REGISTRY = {
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mse": mean_squared_error, "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error, "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "hinge": hinge, "squared_hinge": squared_hinge, "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "rank_hinge": rank_hinge,
}


def resolve_loss(loss):
    if callable(loss):
        return loss
    if isinstance(loss, str):
        key = loss.lower()
        if key in _REGISTRY:
            return _REGISTRY[key]
        raise ValueError(f"unknown loss {loss!r}")
    raise TypeError(f"cannot interpret loss {loss!r}")
