"""Estimator: distributed fit / evaluate / predict.

The single training engine replacing the reference's whole L4
(SURVEY.md section 1): ``InternalDistriOptimizer`` (BigDL two-Spark-jobs-
per-iteration allreduce, ref: zoo/.../keras/models/Topology.scala:1145-1548),
the zoo ``Estimator`` facade (ref: zoo/.../pipeline/estimator/Estimator.scala:37-230),
and the per-framework Ray runners (ref: pyzoo/zoo/orca/learn/*).

Where the reference runs "model forward-backward" as Spark job 1 and
"parameter synchronization" as Spark job 2 every iteration, here one jitted
SPMD step does both: the batch is sharded over the mesh's data axis, the
loss is the global-batch mean, and XLA inserts the gradient allreduce
(psum over ICI/DCN) during compilation. The retry-from-checkpoint loop
mirrors InternalDistriOptimizer.train (ref: Topology.scala:1255-1332).
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.common.triggers import (
    EveryEpoch, Trigger, TriggerState)
from analytics_zoo_tpu.data.dataset import ZooDataset
from analytics_zoo_tpu.learn import checkpoint as ckpt_lib
from analytics_zoo_tpu.learn.metrics import Metric, resolve_metric
from analytics_zoo_tpu.learn.objectives import resolve_loss
from analytics_zoo_tpu.learn.optim import resolve_optimizer
from analytics_zoo_tpu.obs.events import emit, instrument_compiles
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.parallel import sharding
from analytics_zoo_tpu.parallel.mesh import default_mesh
from analytics_zoo_tpu.parallel.sharding import replicated

logger = get_logger(__name__)

# training progress in the unified registry (the BigDL ``Metrics``
# counter role): scraping /metrics on a co-located serving frontend --
# or reading Reporter rollups -- shows training and serving side by side
_REG = get_registry()
_M_STEPS = _REG.counter(
    "zoo_learn_steps_total", "Optimization steps completed")
_M_EPOCHS = _REG.counter(
    "zoo_learn_epochs_total", "Training epochs completed")


def training_prng_key(seed: int):
    """PRNG key for the training stream (dropout masks, on-device epoch
    shuffles), with the implementation chosen by ``zoo.train.prng_impl``.
    "auto" picks the hardware RBG generator on TPU: threefry2x32 dropout
    mask generation costs ~23 ms/step on BERT-base (b32, L384, v5e)
    where RBG is near-free; elsewhere auto keeps the default threefry
    stream so CPU runs stay bit-reproducible across jax versions."""
    impl = get_config().get("zoo.train.prng_impl")
    if impl == "auto":
        try:
            on_tpu = jax.devices()[0].platform == "tpu"
        except Exception:
            on_tpu = False
        impl = "rbg" if on_tpu else "threefry2x32"
    if impl in (None, "", "threefry2x32", "default"):
        return jax.random.PRNGKey(seed)
    return jax.random.key(seed, impl=impl)


def _as_dataset(data, labeled: bool = True) -> ZooDataset:
    """Coerce to ZooDataset. ``labeled=True`` splits a 2-tuple into
    (features, labels); predict paths pass ``labeled=False`` so a tuple is
    a multi-input feature pytree."""
    if isinstance(data, ZooDataset):
        return data
    from analytics_zoo_tpu.data.shard import XShards

    if isinstance(data, XShards):
        return ZooDataset.from_xshards(data)
    if labeled and isinstance(data, tuple) and len(data) == 2:
        return ZooDataset.from_ndarrays(data[0], data[1])
    return ZooDataset.from_ndarrays(data)


def _call_args(x) -> tuple:
    """Feature pytree -> positional args for the model (tuple splats)."""
    if isinstance(x, tuple):
        return x
    return (x,)


def _stage(profiler, name: str):
    """Profiler stage context (nullcontext when profiling is off)."""
    if profiler is not None:
        return profiler.timing(name)
    return contextlib.nullcontext()


# sow-style collections: written fresh per apply, never carried as
# state (persisting them would grow the tuples every step)
_SOW_COLLECTIONS = ("losses", "intermediates")


class FlaxModelAdapter:
    """Adapts a flax ``nn.Module`` (or compatible object) to the uniform
    (init, apply) the Estimator drives. Detects a ``train``/``deterministic``
    flag on ``__call__`` and non-param variable collections (batch_stats).

    Sow collections (``losses``/``intermediates``) are stripped from the
    stored variables and requested mutable on every training apply, so
    modules that ``sow`` auxiliary losses (e.g. the MoE load-balance
    loss) surface them per step without accumulating state."""

    def __init__(self, module):
        self.module = module
        try:
            sig = inspect.signature(type(module).__call__)
            params = set(sig.parameters)
        except (TypeError, ValueError):
            params = set()
        self._train_kw = ("train" if "train" in params else
                          "deterministic" if "deterministic" in params
                          else None)

    def _mode_kwargs(self, training: bool) -> Dict[str, Any]:
        if self._train_kw == "train":
            return {"train": training}
        if self._train_kw == "deterministic":
            return {"deterministic": not training}
        return {}

    def init(self, rng, x) -> Dict[str, Any]:
        variables = self.module.init({"params": rng, "dropout": rng},
                                     *_call_args(x),
                                     **self._mode_kwargs(False))
        return {k: v for k, v in variables.items()
                if k not in _SOW_COLLECTIONS}

    def apply(self, variables, x, training: bool, rng=None,
              want_sown: bool = False):
        """Returns (preds, new_extra_collections). ``want_sown``
        surfaces the sow collections on an inference apply too (how
        evaluate() folds MoE aux losses into val_loss)."""
        variables = {k: v for k, v in variables.items()
                     if k not in _SOW_COLLECTIONS}
        mutable = [k for k in variables if k != "params"]
        kwargs = self._mode_kwargs(training)
        rngs = {"dropout": rng} if (training and rng is not None) else None
        if training or want_sown:
            preds, new_extra = self.module.apply(
                variables, *_call_args(x), rngs=rngs,
                mutable=mutable + list(_SOW_COLLECTIONS), **kwargs)
            return preds, dict(new_extra)
        preds = self.module.apply(variables, *_call_args(x), rngs=rngs,
                                  **kwargs)
        return preds, {k: variables[k] for k in mutable}


class Estimator:
    """fit/evaluate/predict over a sharded mesh.

    Args:
      model: a flax ``nn.Module`` (or any object with compatible
        init/apply), or an adapter instance.
      loss: loss name or ``fn(preds, labels) -> scalar``.
      optimizer: ZooOptimizer / optax transformation / name.
      metrics: list of Metric / names, tracked during evaluate and
        validation.
      mesh: defaults to the context mesh (data-parallel over all devices).
      clip_norm: global-L2 gradient clip (ref: tf_optimizer.py:392-396).
      clip_value: symmetric constant clip (-v, v).
      variables: pre-initialized variables (skip lazy init).
      aux_loss_collections: variable collections whose sown scalars are
        SUMMED INTO the training objective each step -- how MoE
        load-balance losses (``moe_aux_loss`` in ``losses``) reach the
        optimizer. Default: ("losses",).
    """

    def __init__(self, model, loss=None, optimizer="adam",
                 metrics: Sequence[Any] = (), mesh=None,
                 clip_norm: Optional[float] = None,
                 clip_value: Optional[float] = None,
                 variables: Optional[Dict[str, Any]] = None,
                 param_spec_fn: Optional[Callable] = None,
                 aux_loss_collections: Sequence[str] = ("losses",),
                 grad_accum_steps: int = 1,
                 seed: int = 0):
        self.adapter = (model if hasattr(model, "apply")
                        and hasattr(model, "init")
                        and not _is_flax_module(model)
                        else FlaxModelAdapter(model))
        self.loss_fn = resolve_loss(loss) if loss is not None else None
        self.tx = self._with_clipping(resolve_optimizer(optimizer),
                                      clip_norm, clip_value)
        self.metrics: List[Metric] = [resolve_metric(m) for m in metrics]
        self.mesh = mesh or default_mesh()
        self.aux_loss_collections = tuple(aux_loss_collections)
        self.param_spec_fn = param_spec_fn
        if int(grad_accum_steps) < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        # k > 1 splits each fit batch into k microbatches inside the
        # jitted step (lax.scan), averaging grads before ONE optimizer
        # update: the effective batch grows k-fold at constant
        # activation memory, and the optimizer's HBM traffic (params +
        # moments read/write) amortizes over k microbatches.
        # Exact-parity caveat: batch-COUPLED layers (BatchNorm and
        # friends) see B/k rows per microbatch, so their statistics --
        # and hence the trajectory -- differ from the k=1 run; the
        # exact-parity guarantee holds for per-sample models only
        self.grad_accum_steps = int(grad_accum_steps)
        self.seed = seed
        self.variables = variables
        self.opt_state = None
        self.global_step = 0
        self.epoch = 0
        self._train_step = None
        self._eval_step = None
        self._epoch_fns: Dict[Any, Callable] = {}
        self._predict_fns: Dict[Any, Callable] = {}
        self.last_profile = None  # set by fit(profile=True)
        self._rng = training_prng_key(seed)
        from analytics_zoo_tpu.common.context import (
            enable_compilation_cache)

        enable_compilation_cache()

    # ------------------------------------------------------------- setup --
    @staticmethod
    def _with_clipping(tx, clip_norm, clip_value):
        import optax

        chain = []
        if clip_value is not None:
            chain.append(optax.clip(clip_value))
        if clip_norm is not None:
            chain.append(optax.clip_by_global_norm(clip_norm))
        chain.append(tx)
        return optax.chain(*chain) if len(chain) > 1 else tx

    def _probe_example(self, dataset: ZooDataset, batch_size: int):
        if dataset.num_samples == 0:
            raise ValueError("dataset is empty")
        x, *_ = next(dataset.batches(batch_size, shuffle=False,
                                     mesh=self.mesh, drop_remainder=False))
        return x

    def _ensure_built(self, example_x) -> None:
        newly_placed = False
        if self.variables is None:
            self._rng, init_rng = jax.random.split(self._rng)
            small = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[:1], example_x)
            self.variables = self.adapter.init(init_rng, small)
            n_params = sum(np.prod(l.shape) for l in
                           jax.tree_util.tree_leaves(
                               self.variables.get("params", {})))
            logger.info("model built: %d parameters", int(n_params))
            newly_placed = True
        if self.opt_state is None:
            self.opt_state = self.tx.init(self.variables.get("params", {}))
            newly_placed = True
        if newly_placed:
            self._place_state()

    def _place_state(self) -> None:
        # default: replicate model + optimizer state over the mesh (the
        # data axis shards only the batch -- the reference's replicated
        # model-per-executor layout, Topology.scala:1145+). With
        # param_spec_fn, parameters AND optimizer moments follow the
        # given PartitionSpecs (tensor parallelism / sharded embeddings).
        if self.param_spec_fn is None:
            rep = replicated(self.mesh)
            self.variables = jax.device_put(self.variables, rep)
            self.opt_state = jax.device_put(self.opt_state, rep)
        else:
            from analytics_zoo_tpu.parallel.sharding import shard_pytree

            self.variables = shard_pytree(self.variables, self.mesh,
                                          self.param_spec_fn)
            self.opt_state = shard_pytree(self.opt_state, self.mesh,
                                          self.param_spec_fn)

    # -------------------------------------------------------- train step --
    def _step_math(self, variables, opt_state, x, y, rng):
        """One SGD update; shared by the per-step and the device-cached
        whole-epoch paths. With ``grad_accum_steps`` k > 1 the batch is
        split into k microbatches scanned inside this one update."""
        import optax

        adapter, loss_fn, tx = self.adapter, self.loss_fn, self.tx
        aux_colls = self.aux_loss_collections
        params = variables.get("params", {})
        extra = {k: v for k, v in variables.items() if k != "params"}

        def compute_loss(p, xb, yb, step_rng):
            preds, new_extra = adapter.apply(
                {"params": p, **extra}, xb, training=True,
                rng=step_rng)
            loss = loss_fn(preds, yb)
            for coll in aux_colls:
                if coll in new_extra:
                    for leaf in jax.tree_util.tree_leaves(
                            new_extra[coll]):
                        loss = loss + jnp.sum(leaf)
            # sown collections are per-step scalars, not model state
            new_extra = {k: v for k, v in new_extra.items()
                         if k not in aux_colls
                         and k not in _SOW_COLLECTIONS}
            return loss, new_extra

        k = self.grad_accum_steps
        if k <= 1:
            (loss, new_extra), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params, x, y, rng)
        else:
            loss, new_extra, grads = self._accum_grads(
                compute_loss, params, x, y, rng, k)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return {"params": params, **new_extra}, opt_state, loss

    @staticmethod
    def _accum_grads(compute_loss, params, x, y, rng, k: int):
        """Microbatch scan: mean of per-microbatch grads == the full-
        batch gradient (losses are batch means), at 1/k the activation
        memory and one optimizer update per k microbatches. Holds
        exactly for per-sample models; batch-coupled layers (e.g.
        BatchNorm) compute statistics over B/k rows instead of B, so
        their trajectory legitimately differs from the k=1 run.

        Mutable-collection caveat: every microbatch's forward reads the
        SAME pre-step collections (``params`` is the scan's only
        threaded state), so each microbatch's mutable update -- e.g.
        the BatchNorm EMA -- is computed independently from the
        pre-step statistics, and only the LAST microbatch's update is
        kept. This is NOT equivalent to a sequential k-step loop, which
        would compound k EMA updates (each folding into the previous
        step's stats) and advance the EMA roughly k times faster."""

        def split(a):
            if a.shape[0] % k:
                raise ValueError(
                    f"grad_accum_steps={k} must divide the batch "
                    f"dim, got {a.shape[0]}")
            return a.reshape(k, a.shape[0] // k, *a.shape[1:])

        xs = jax.tree_util.tree_map(split, x)
        ys = (jax.tree_util.tree_map(split, y)
              if y is not None else None)

        def body(carry, inp):
            g_acc, loss_acc = carry
            j, xj, yj = inp
            (loss, new_extra), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(
                params, xj, yj, jax.random.fold_in(rng, j))
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
            return (g_acc, loss_acc + loss), new_extra

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g_sum, loss_sum), extras = jax.lax.scan(
            body, (zeros, jnp.zeros((), jnp.float32)),
            (jnp.arange(k), xs, ys))
        grads = jax.tree_util.tree_map(lambda g: g / k, g_sum)
        # mutable state (e.g. batch stats): each microbatch updated from
        # the same PRE-STEP collections, so taking [-1] keeps one
        # single-microbatch update -- NOT the compounded k updates a
        # sequential k-step loop would produce (see docstring caveat)
        new_extra = jax.tree_util.tree_map(lambda a: a[-1], extras)
        return loss_sum / k, new_extra, grads

    def _build_train_step(self):
        if self._train_step is not None:
            return self._train_step
        if self.loss_fn is None:
            raise ValueError("Estimator needs a loss to train")
        donate = get_config().get("zoo.train.donate_buffers")

        def step(variables, opt_state, loss_sum, x, y, rng):
            variables, opt_state, loss = self._step_math(
                variables, opt_state, x, y, rng)
            # the epoch loss accumulates ON DEVICE: pulling per-step
            # scalars to host costs a full round-trip each (catastrophic
            # over remote dispatch links); the epoch mean is one
            # transfer of this resident scalar
            return variables, opt_state, loss_sum + loss, loss

        # compile-boundary instrumentation (obs.events): the first call
        # per input signature is a trace+compile -- its wall time and
        # abstract shapes land in the event log and feed the
        # recompile-storm detector (a fit() whose batches keep changing
        # shape recompiles every step and warns instead of crawling)
        self._train_step = instrument_compiles(
            jax.jit(step, donate_argnums=(0, 1, 2) if donate else ()),
            "estimator.train_step", subsystem="learn")
        return self._train_step

    def _build_epoch_fn(self, batch_size: int, n_steps: int,
                        n_samples: int):
        """Whole-epoch train function for device-resident datasets: ONE
        dispatch runs ``n_steps`` updates via ``lax.fori_loop``, gathering
        each shuffled batch on device. Where the reference runs two Spark
        jobs per ITERATION (Topology.scala:1193+), this runs one XLA
        program per EPOCH -- no host round-trips inside. The shuffle
        permutation is drawn ON DEVICE too: only an rng key crosses the
        host boundary per epoch (a host-built permutation of a
        MovieLens-scale epoch is ~17 MB of transfer)."""
        from jax.sharding import NamedSharding

        mesh = self.mesh

        def epoch(variables, opt_state, x_all, y_all, rng0):
            perm_rng, step_rng0 = jax.random.split(rng0)
            perm = jax.random.permutation(perm_rng, n_samples)

            def body(i, carry):
                variables, opt_state, loss_sum = carry
                idx = jax.lax.dynamic_slice_in_dim(
                    perm, i * batch_size, batch_size)

                def take(a):
                    b = jnp.take(a, idx, axis=0)
                    return jax.lax.with_sharding_constraint(
                        b, NamedSharding(
                            mesh, sharding.data_parallel_spec(b)))

                x = jax.tree_util.tree_map(take, x_all)
                y = (jax.tree_util.tree_map(take, y_all)
                     if y_all is not None else None)
                rng = jax.random.fold_in(step_rng0, i)
                variables, opt_state, loss = self._step_math(
                    variables, opt_state, x, y, rng)
                return variables, opt_state, loss_sum + loss

            init = (variables, opt_state, jnp.zeros((), jnp.float32))
            variables, opt_state, loss_sum = jax.lax.fori_loop(
                0, n_steps, body, init)
            return variables, opt_state, loss_sum / n_steps

        donate = get_config().get("zoo.train.donate_buffers")
        return instrument_compiles(
            jax.jit(epoch, donate_argnums=(0, 1) if donate else ()),
            "estimator.epoch", subsystem="learn")

    def _eval_metrics(self) -> List[Metric]:
        """The tracked metrics plus a Loss metric when a loss is set."""
        out = list(self.metrics)
        if self.loss_fn is not None:
            from analytics_zoo_tpu.learn.metrics import Loss

            out.append(Loss(self.loss_fn))
        return out

    def _build_eval_step(self):
        if self._eval_step is not None:
            return self._eval_step
        adapter = self.adapter
        metrics = self._eval_metrics()
        aux_colls = self.aux_loss_collections
        # only the flax adapter can surface sown aux losses; other
        # adapters (GraphModel, custom) have none to surface
        want_sown = bool(aux_colls) and isinstance(adapter,
                                                   FlaxModelAdapter)

        from analytics_zoo_tpu.learn.metrics import Loss

        def step(variables, x, y, w, states):
            if want_sown:
                preds, extra = adapter.apply(variables, x,
                                             training=False,
                                             want_sown=True)
                aux = jnp.zeros((), jnp.float32)
                for coll in aux_colls:
                    for leaf in jax.tree_util.tree_leaves(
                            extra.get(coll, {})):
                        aux = aux + jnp.sum(leaf)
            else:
                preds, _ = adapter.apply(variables, x, training=False)
                aux = None
            out = []
            for m, s in zip(metrics, states):
                s = m.update(s, preds, y, weights=w)
                if aux is not None and isinstance(m, Loss):
                    # the aux term applies once per sample so the
                    # streaming mean matches the training objective
                    # (keras semantics: regularizers count in val_loss)
                    wsum = (jnp.sum(jnp.asarray(w, jnp.float32))
                            if w is not None else
                            jnp.asarray(_batch_size_of(preds),
                                        jnp.float32))
                    s = {**s, "total": s["total"] + aux * wsum}
                out.append(s)
            return out

        self._eval_step = instrument_compiles(
            jax.jit(step), "estimator.eval_step", subsystem="learn")
        return self._eval_step

    # --------------------------------------------------------------- fit --
    def fit(self, data, batch_size: int, epochs: int = 1,
            validation_data=None, validation_trigger: Optional[Trigger] = None,
            checkpoint_dir: Optional[str] = None,
            checkpoint_trigger: Optional[Trigger] = None,
            log_dir: Optional[str] = None,
            resume: bool = False,
            device_cache: bool = False,
            profile: bool = False,
            trace_dir: Optional[str] = None) -> List[Dict[str, float]]:
        """Train; returns per-epoch history.

        Failure semantics mirror InternalDistriOptimizer.train
        (ref: Topology.scala:1255-1332): on an exception mid-epoch, if a
        checkpoint exists and fewer than ``zoo.train.failure.retry_times``
        failures occurred within ``zoo.train.failure.retry_interval_s``,
        restore the latest snapshot and continue.

        ``device_cache=True`` places the whole dataset in device memory
        once and compiles each epoch into a single XLA program (shuffled
        batches gathered on device) -- the fast path for datasets that
        fit in HBM. Triggers/validation/checkpoints then run at epoch
        granularity, and single-process only.

        ``profile=True`` records data-wait vs step-dispatch stage timers
        into ``self.last_profile`` (a ``TrainingProfiler``; the Ray
        runners' profile=True analog, ref: pytorch_ray_estimator.py:
        150-190); ``trace_dir`` additionally captures a jax.profiler
        device trace viewable in TensorBoard.
        """
        cfg = get_config()
        dataset = _as_dataset(data)
        val_dataset = (_as_dataset(validation_data)
                       if validation_data is not None else None)
        validation_trigger = validation_trigger or EveryEpoch()
        checkpoint_trigger = checkpoint_trigger or EveryEpoch()
        self._ensure_built(self._probe_example(dataset, batch_size))
        if resume and checkpoint_dir and \
                ckpt_lib.latest_step(checkpoint_dir) is not None:
            self._restore(checkpoint_dir)
        profiler = None
        if profile or trace_dir:
            from analytics_zoo_tpu.learn.profiler import TrainingProfiler

            profiler = TrainingProfiler(trace_dir=trace_dir)
            self.last_profile = profiler
            profiler.start_trace()
        emit("train_start", "learn", epochs=epochs,
             batch_size=batch_size, device_cache=bool(device_cache))
        try:
            if device_cache:
                if jax.process_count() > 1:
                    raise ValueError("device_cache supports "
                                     "single-process runs only")
                return self._fit_device_cached(
                    dataset, val_dataset, batch_size, epochs,
                    validation_trigger, checkpoint_trigger,
                    checkpoint_dir, log_dir, profiler)

            train_step = self._build_train_step()
            writer = self._make_writer(log_dir)
            log_every = cfg.get("zoo.train.log_every_n_steps")
            retry_times = cfg.get("zoo.train.failure.retry_times")
            retry_interval = cfg.get("zoo.train.failure.retry_interval_s")
            failures: List[float] = []
            history: List[Dict[str, float]] = []
            state = TriggerState(epoch=self.epoch,
                                 iteration=self.global_step)
            steps_per_epoch = dataset.steps_per_epoch(batch_size)
            try:
                return self._fit_loop(
                    dataset, val_dataset, batch_size, epochs, train_step,
                    writer, log_every, retry_times, retry_interval,
                    validation_trigger, checkpoint_trigger,
                    checkpoint_dir, failures, history, state,
                    steps_per_epoch, profiler)
            finally:
                if writer:
                    writer.close()
        finally:
            emit("train_stop", "learn", epochs_run=self.epoch,
                 global_step=self.global_step)
            if profiler is not None:
                profiler.stop_trace()
                logger.info("training profile: %s", profiler.summary())

    def _fit_loop(self, dataset, val_dataset, batch_size, epochs,
                  train_step, writer, log_every, retry_times,
                  retry_interval, validation_trigger, checkpoint_trigger,
                  checkpoint_dir, failures, history, state,
                  steps_per_epoch, profiler=None
                  ) -> List[Dict[str, float]]:
        stage = functools.partial(_stage, profiler)

        while self.epoch < epochs:
            epoch_start = time.time()
            loss_sum = jnp.zeros((), jnp.float32)
            n_steps = 0
            last_val: Optional[Dict[str, float]] = None
            try:
                batches = iter(dataset.device_iterator(
                    batch_size, mesh=self.mesh, shuffle=True,
                    seed=self.seed, epoch=self.epoch))
                for step_in_epoch in range(steps_per_epoch):
                    with stage("data_wait"):
                        try:
                            x, y = next(batches)
                        except StopIteration:
                            break
                    self._rng, step_rng = jax.random.split(self._rng)
                    with stage("train_step"):
                        (self.variables, self.opt_state, loss_sum,
                         loss) = train_step(self.variables,
                                            self.opt_state, loss_sum,
                                            x, y, step_rng)
                    self.global_step += 1
                    n_steps += 1
                    _M_STEPS.inc()
                    if (self.global_step % log_every == 0 or
                            self.global_step == 1):
                        lf = float(loss)
                        # loss reaches triggers at log cadence only: a
                        # per-step float() would force a host sync every
                        # step and kill async dispatch
                        state.loss = lf
                        logger.info("epoch %d step %d loss %.5f",
                                    self.epoch, self.global_step, lf)
                        if writer:
                            writer.add_scalar("train/loss", lf,
                                              self.global_step)
                    # triggers see every optimization step (the contract of
                    # triggers.py; makes SeveralIteration/MinLoss live).
                    # epoch boundaries count steps *within* this epoch, so
                    # they stay correct after a mid-epoch restore shifts
                    # global_step off the modulo grid.
                    finishing = step_in_epoch == steps_per_epoch - 1
                    state.iteration = self.global_step
                    state.epoch = self.epoch + (1 if finishing else 0)
                    state.epoch_finished = finishing
                    state.wall_time = time.time()
                    if val_dataset is not None and validation_trigger(state):
                        last_val = self.evaluate(val_dataset, batch_size)
                        state.score = next(iter(last_val.values()), None)
                        if writer:
                            for k, v in last_val.items():
                                writer.add_scalar(f"validation/{k}", v,
                                                  self.global_step)
                    if checkpoint_dir is not None and \
                            checkpoint_trigger(state):
                        ckpt_lib.save_checkpoint(
                            checkpoint_dir, self.variables, self.opt_state,
                            self.global_step, state.epoch)
                # epoch completed; ONE host sync for the whole epoch
                self.epoch += 1
                _M_EPOCHS.inc()
                state.epoch = self.epoch
                entry: Dict[str, float] = {
                    "epoch": self.epoch,
                    "loss": (float(loss_sum) / n_steps if n_steps
                             else float("nan")),
                    "seconds": time.time() - epoch_start,
                }
                if last_val is not None:
                    entry.update({f"val_{k}": v for k, v in last_val.items()})
                history.append(entry)
                logger.info("epoch %d done: %s", self.epoch, entry)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if not self._handle_training_failure(
                        e, failures, retry_times, retry_interval,
                        checkpoint_dir, state):
                    raise
        return history

    def _handle_training_failure(self, e, failures, retry_times,
                                 retry_interval, checkpoint_dir,
                                 state) -> bool:
        """Shared retry-from-checkpoint contract for both fit loops
        (ref: Topology.scala:1255-1332): prune the failure window, and
        if a checkpoint exists within the retry budget, reset stale
        trigger state and restore. Returns whether training continues
        (False -> caller re-raises)."""
        now = time.time()
        failures[:] = [t for t in failures
                       if now - t < retry_interval] + [now]
        can_retry = (checkpoint_dir is not None and
                     ckpt_lib.latest_step(checkpoint_dir) is not None
                     and len(failures) <= retry_times)
        logger.exception("training failure %d/%d in window: %s",
                         len(failures), retry_times, e)
        emit("train_failure", "learn", error=repr(e),
             failures=len(failures), retrying=can_retry)
        if not can_retry:
            return False
        # the restored model's loss/score are unknown until the next
        # log step / validation; stale pre-crash values would misfire
        # MinLoss/MaxScore
        state.loss = None
        state.score = None
        self._restore(checkpoint_dir)
        return True

    @staticmethod
    def _make_writer(log_dir: Optional[str]):
        if log_dir is None:
            return None
        from analytics_zoo_tpu.utils.summary import SummaryWriter

        return SummaryWriter(log_dir)

    @staticmethod
    def _fired_in_range(trigger: Trigger, state: TriggerState,
                        start_step: int, end_step: int) -> bool:
        """Whether ``trigger`` would have fired at ANY step in
        (start_step, end_step] -- the cached path checks triggers once
        per epoch, so step-granular triggers (SeveralIteration) must
        scan the epoch's step range instead of testing only the final
        step (which is always a multiple of steps-per-epoch)."""
        saved = state.iteration
        try:
            for it in range(start_step + 1, end_step + 1):
                state.iteration = it
                if trigger(state):
                    return True
            return False
        finally:
            state.iteration = saved

    def _fit_device_cached(self, dataset, val_dataset, batch_size,
                           epochs, validation_trigger, checkpoint_trigger,
                           checkpoint_dir, log_dir, profiler=None
                           ) -> List[Dict[str, float]]:
        from jax.sharding import NamedSharding, PartitionSpec as P

        stage = functools.partial(_stage, profiler)

        cfg = get_config()
        n = dataset.num_samples
        n_steps = n // batch_size
        if n_steps == 0:
            raise ValueError(f"dataset ({n} samples) smaller than "
                             f"batch_size {batch_size}")
        rep = NamedSharding(self.mesh, P())
        x_all = jax.device_put(
            jax.tree_util.tree_map(np.asarray, dataset.features), rep)
        y_all = (jax.device_put(
            jax.tree_util.tree_map(np.asarray, dataset.labels), rep)
            if dataset.labels is not None else None)
        key = (batch_size, n_steps, n)
        epoch_fn = self._epoch_fns.get(key)
        if epoch_fn is None:
            epoch_fn = self._build_epoch_fn(batch_size, n_steps, n)
            self._epoch_fns[key] = epoch_fn
        writer = self._make_writer(log_dir)
        history: List[Dict[str, float]] = []
        state = TriggerState(epoch=self.epoch, iteration=self.global_step)
        retry_times = cfg.get("zoo.train.failure.retry_times")
        retry_interval = cfg.get("zoo.train.failure.retry_interval_s")
        failures: List[float] = []
        try:
            while self.epoch < epochs:
                t0 = time.time()
                step_before = self.global_step
                try:
                    self._rng, erng = jax.random.split(self._rng)
                    with stage("train_step"):
                        (self.variables, self.opt_state,
                         mean_loss) = epoch_fn(
                            self.variables, self.opt_state, x_all,
                            y_all, erng)
                        lf = float(mean_loss)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    if not self._handle_training_failure(
                            e, failures, retry_times, retry_interval,
                            checkpoint_dir, state):
                        raise
                    continue
                self.epoch += 1
                self.global_step += n_steps
                _M_EPOCHS.inc()
                _M_STEPS.inc(n_steps)
                entry: Dict[str, float] = {
                    "epoch": self.epoch, "loss": lf,
                    "seconds": time.time() - t0}
                state.epoch = self.epoch
                state.iteration = self.global_step
                state.loss = lf
                state.epoch_finished = True
                state.wall_time = time.time()
                if writer:
                    writer.add_scalar("train/loss", lf, self.global_step)
                if val_dataset is not None and self._fired_in_range(
                        validation_trigger, state, step_before,
                        self.global_step):
                    val = self.evaluate(val_dataset, batch_size)
                    state.score = next(iter(val.values()), None)
                    entry.update({f"val_{k}": v for k, v in val.items()})
                    if writer:
                        for k, v in val.items():
                            writer.add_scalar(f"validation/{k}", v,
                                              self.global_step)
                if checkpoint_dir is not None and self._fired_in_range(
                        checkpoint_trigger, state, step_before,
                        self.global_step):
                    ckpt_lib.save_checkpoint(
                        checkpoint_dir, self.variables, self.opt_state,
                        self.global_step, self.epoch)
                history.append(entry)
                logger.info("epoch %d done (device-cached): %s",
                            self.epoch, entry)
        finally:
            if writer:
                writer.close()
        return history

    def _restore(self, checkpoint_dir: str) -> None:
        # templates carry structure + shape/dtype only: live arrays may
        # already be invalid (donated buffers after a mid-step failure)
        def to_struct(a):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return a

        var_t = jax.tree_util.tree_map(to_struct, self.variables)
        opt_t = jax.tree_util.tree_map(to_struct, self.opt_state)
        self.variables, self.opt_state, meta = ckpt_lib.load_checkpoint(
            checkpoint_dir, var_t, opt_t)
        self.global_step = meta["step"]
        self.epoch = meta["epoch"]
        self._place_state()
        logger.info("restored from checkpoint: step=%d epoch=%d",
                    self.global_step, self.epoch)

    # ---------------------------------------------------------- evaluate --
    def evaluate(self, data, batch_size: int) -> Dict[str, float]:
        """Metrics over the full dataset -- the short final batch is
        included via padding + masking, so no tail samples are dropped."""
        dataset = _as_dataset(data)
        self._ensure_built(self._probe_example(dataset, batch_size))
        eval_step = self._build_eval_step()
        metrics = self._eval_metrics()
        states: List[Any] = [m.empty() for m in metrics]
        for x, y, w in dataset.device_iterator(
                batch_size, mesh=self.mesh, shuffle=False,
                drop_remainder=False, with_mask=True):
            states = eval_step(self.variables, x, y, w, states)
        return {m.name: float(m.result(s))
                for m, s in zip(metrics, states)}

    # ----------------------------------------------------------- predict --
    def predict(self, data, batch_size: int = 32) -> Any:
        dataset = _as_dataset(data, labeled=False)
        self._ensure_built(self._probe_example(dataset, batch_size))
        adapter = self.adapter

        if "predict" not in self._predict_fns:
            self._predict_fns["predict"] = instrument_compiles(
                jax.jit(lambda variables, x: adapter.apply(
                    variables, x, training=False)[0]),
                "estimator.predict", subsystem="learn")
        fn = self._predict_fns["predict"]

        # globally-sharded outputs are not fully addressable per host;
        # gather_to_host all-gathers them (batch order is preserved
        # because batches() hands each process its contiguous block)
        outs: List[Any] = []
        for x, _ in dataset.device_iterator(batch_size, mesh=self.mesh,
                                            shuffle=False,
                                            drop_remainder=False):
            outs.append(sharding.gather_to_host(fn(self.variables, x)))
        result = jax.tree_util.tree_map(
            lambda *parts: np.concatenate(parts)[:dataset.num_samples],
            *outs)
        return result

    # ------------------------------------------------------- persistence --
    def save(self, ckpt_dir: str) -> None:
        self._ensure_opt_for_save()
        ckpt_lib.save_checkpoint(ckpt_dir, self.variables, self.opt_state,
                                 self.global_step, self.epoch)

    def _ensure_opt_for_save(self):
        if self.variables is None:
            raise ValueError("nothing to save: model not built")
        if self.opt_state is None:
            self.opt_state = self.tx.init(self.variables.get("params", {}))

    def load(self, ckpt_dir: str) -> None:
        """Restore weights; works on an un-built Estimator (the model
        variables restore template-free, then the optimizer state restores
        against a fresh tx.init template)."""
        if self.variables is None:
            self.variables, _, _ = ckpt_lib.load_checkpoint(ckpt_dir, None,
                                                            None)
        self._ensure_opt_for_save()
        self._restore(ckpt_dir)


def recompiled(old: Optional["Estimator"], model, **kwargs) -> "Estimator":
    """Build a fresh Estimator carrying over trained weights + counters
    from ``old`` (the Keras compile() contract: recompiling changes the
    training config, not the model)."""
    est = Estimator(model,
                    variables=old.variables if old is not None else None,
                    **kwargs)
    if old is not None:
        est.global_step = old.global_step
        est.epoch = old.epoch
    return est


def _batch_size_of(preds) -> int:
    leaf = jax.tree_util.tree_leaves(preds)[0]
    return leaf.shape[0]


def _is_flax_module(obj) -> bool:
    try:
        import flax.linen as nn

        return isinstance(obj, nn.Module)
    except ImportError:  # pragma: no cover
        return False
