"""Training/inference engine: the unified Estimator.

Replaces the reference's L4/L5 training surface (SURVEY.md):
InternalDistriOptimizer, zoo Estimator, TFPark TFOptimizer, and the Orca
Estimators over five backends -- with one SPMD Estimator.
"""

from analytics_zoo_tpu.learn.estimator import Estimator  # noqa: F401
from analytics_zoo_tpu.learn.gan import GANEstimator  # noqa: F401
from analytics_zoo_tpu.learn.population import (  # noqa: F401
    PopulationEstimator,
)
from analytics_zoo_tpu.learn.profiler import TrainingProfiler  # noqa: F401
from analytics_zoo_tpu.learn import metrics  # noqa: F401
from analytics_zoo_tpu.learn import objectives  # noqa: F401
from analytics_zoo_tpu.learn.optim import (  # noqa: F401
    SGD,
    Adam,
    AdamWeightDecay,
    RMSprop,
    Adagrad,
    Adadelta,
    Fixed,
    Poly,
    Warmup,
)
