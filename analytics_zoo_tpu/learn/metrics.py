"""Validation metrics, usable inside jitted eval steps.

The analog of BigDL ``ValidationMethod``s surfaced by the reference
(Accuracy/Top1/Top5/AUC/MAE/MSE/Loss -- ref: zoo/.../keras/metrics/,
pyzoo/zoo/orca/learn/metrics.py, and the TF-tensor-backed
``TFValidationMethod``/``StatelessMetric`` of tf_optimizer.py:45-66).

Each metric is a pure state machine: ``empty()`` -> state pytree,
``update(state, preds, labels)`` -> state (jit-safe), ``result(state)``
-> scalar. The Estimator merges states across batches; cross-device
reduction is free because updates run on globally-sharded arrays under jit.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


class Metric:
    name: str = "metric"
    # True if larger is better (used to pick "best" checkpoints and by
    # MaxScore triggers)
    greater_is_better: bool = True

    def empty(self) -> Any:
        raise NotImplementedError

    def update(self, state: Any, preds, labels, weights=None) -> Any:
        """``weights`` is an optional [B] 0/1 mask excluding padded
        samples (short final batches are padded for static shapes)."""
        raise NotImplementedError

    def result(self, state: Any):
        raise NotImplementedError


def _ones_like_batch(preds):
    n = jax.tree_util.tree_leaves(preds)[0].shape[0]
    return jnp.ones((n,), jnp.float32)


class _MeanMetric(Metric):
    """Streaming weighted mean of a per-sample statistic."""

    def empty(self):
        return {"total": jnp.zeros((), jnp.float32),
                "count": jnp.zeros((), jnp.float32)}

    def _per_sample(self, preds, labels):
        """Return a [B] float statistic, one value per sample."""
        raise NotImplementedError

    def update(self, state, preds, labels, weights=None):
        stat = self._per_sample(preds, labels)
        w = (_ones_like_batch(preds) if weights is None
             else jnp.asarray(weights, jnp.float32))
        return {"total": state["total"] + jnp.sum(stat * w),
                "count": state["count"] + jnp.sum(w)}

    def result(self, state):
        return state["total"] / jnp.maximum(state["count"], 1.0)


class Accuracy(_MeanMetric):
    """Sparse top-1 accuracy; handles [B,C] logits/probs, [B] binary
    scores, or hard predictions (ref: keras/metrics/Accuracy)."""

    name = "accuracy"

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def _per_sample(self, preds, labels):
        labels = jnp.asarray(labels)
        if labels.ndim >= 2 and labels.ndim == preds.ndim and \
                labels.shape[-1] > 1:
            labels = jnp.argmax(labels, -1)  # one-hot -> sparse
        labels = labels.reshape(labels.shape[0], -1)[:, 0]
        if preds.ndim > 1 and preds.shape[-1] > 1:
            hard = jnp.argmax(preds, -1).reshape(preds.shape[0], -1)[:, 0]
        else:
            flat = preds.reshape(preds.shape[0], -1)[:, 0]
            hard = (flat > self.threshold).astype(jnp.int32)
        return (hard == labels.astype(hard.dtype)).astype(jnp.float32)


Top1Accuracy = Accuracy


class TopKAccuracy(_MeanMetric):
    def __init__(self, k: int = 5):
        self.k = k
        self.name = f"top{k}_accuracy"

    def _per_sample(self, preds, labels):
        labels = jnp.asarray(labels).reshape(-1)
        topk = jnp.argsort(preds, -1)[:, -self.k:]
        return jnp.any(topk == labels[:, None], axis=-1).astype(jnp.float32)


def Top5Accuracy():
    return TopKAccuracy(5)


class MAE(_MeanMetric):
    name = "mae"
    greater_is_better = False

    def _per_sample(self, preds, labels):
        preds = preds.reshape(preds.shape[0], -1)
        labels = jnp.asarray(labels).reshape(labels.shape[0], -1)
        return jnp.mean(jnp.abs(preds - labels), axis=-1)


class MSE(_MeanMetric):
    name = "mse"
    greater_is_better = False

    def _per_sample(self, preds, labels):
        preds = preds.reshape(preds.shape[0], -1)
        labels = jnp.asarray(labels).reshape(labels.shape[0], -1)
        return jnp.mean(jnp.square(preds - labels), axis=-1)


class RMSE(MSE):
    name = "rmse"

    def result(self, state):
        return jnp.sqrt(super().result(state))


class Loss(_MeanMetric):
    """Mean of a loss function over the eval set. The loss fn returns a
    batch mean, so per-sample values come from vmapping over singleton
    batches (keeps padding-masked eval exact)."""

    name = "loss"
    greater_is_better = False

    def __init__(self, loss_fn):
        self.loss_fn = loss_fn

    def _per_sample(self, preds, labels):
        def one(p, t):
            return self.loss_fn(
                jax.tree_util.tree_map(lambda a: a[None], p),
                jax.tree_util.tree_map(lambda a: a[None], t))

        return jax.vmap(one)(preds, labels)


class AUC(Metric):
    """Streaming ROC-AUC via fixed-threshold TP/FP histograms, the same
    binned estimator TF/Keras uses (ref: keras/metrics AUC).

    The thresholds span [0, 1], so raw logits need squashing.
    ``from_logits=True`` (the default) always applies sigmoid -- ROC is
    invariant under monotone maps, so probabilities passed through
    sigmoid keep their AUC (the binned estimator just spends its
    thresholds on a narrower band), while raw logits would silently
    degenerate (round-1 review finding). The transform is the SAME for
    every batch, keeping the streaming histograms on one score scale.
    Pass False for pre-squashed scores at full bin resolution.
    """

    name = "auc"

    def __init__(self, num_thresholds: int = 200,
                 from_logits: bool = True):
        self.num_thresholds = num_thresholds
        self.from_logits = from_logits

    def empty(self):
        z = jnp.zeros((self.num_thresholds,), jnp.float32)
        return {"tp": z, "fp": z, "tn": z, "fn": z}

    def update(self, state, preds, labels, weights=None):
        scores = jnp.asarray(preds).reshape(-1)
        if self.from_logits:  # batch-independent squash
            scores = jax.nn.sigmoid(scores)
        y = jnp.asarray(labels).reshape(-1).astype(jnp.float32)
        w = (jnp.ones_like(scores) if weights is None
             else jnp.asarray(weights, jnp.float32).reshape(-1))
        eps = 1e-7
        th = jnp.linspace(0.0 - eps, 1.0 + eps, self.num_thresholds)
        pred_pos = (scores[None, :] > th[:, None]).astype(jnp.float32)
        pos = (y[None, :] > 0.5).astype(jnp.float32)
        return {
            "tp": state["tp"] + jnp.sum(w * pred_pos * pos, -1),
            "fp": state["fp"] + jnp.sum(w * pred_pos * (1 - pos), -1),
            "fn": state["fn"] + jnp.sum(w * (1 - pred_pos) * pos, -1),
            "tn": state["tn"] + jnp.sum(w * (1 - pred_pos) * (1 - pos), -1),
        }

    def result(self, state):
        tpr = state["tp"] / jnp.maximum(state["tp"] + state["fn"], 1e-7)
        fpr = state["fp"] / jnp.maximum(state["fp"] + state["tn"], 1e-7)
        # thresholds ascend -> fpr/tpr descend; integrate with trapezoid
        return jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)


class BinaryCrossEntropy(_MeanMetric):
    name = "binary_crossentropy"
    greater_is_better = False

    def _per_sample(self, preds, labels):
        p = jnp.clip(preds.reshape(preds.shape[0], -1), 1e-7, 1 - 1e-7)
        y = jnp.asarray(labels).reshape(p.shape).astype(jnp.float32)
        ll = y * jnp.log(p) + (1 - y) * jnp.log(1 - p)
        return -jnp.mean(ll, axis=-1)


_REGISTRY = {
    "accuracy": Accuracy, "acc": Accuracy, "top1": Accuracy,
    "top5": Top5Accuracy, "top5accuracy": Top5Accuracy,
    "mae": MAE, "mse": MSE, "rmse": RMSE, "auc": AUC,
    "binary_crossentropy": BinaryCrossEntropy,
}


def resolve_metric(m) -> Metric:
    if isinstance(m, Metric):
        return m
    if isinstance(m, str):
        key = m.lower().replace("_accuracy", "") if m.lower() in (
            "top5_accuracy",) else m.lower()
        if key in _REGISTRY:
            return _REGISTRY[key]()
        raise ValueError(f"unknown metric {m!r}")
    if callable(m):
        # assume a loss-like callable
        metric = Loss(m)
        metric.name = getattr(m, "__name__", "loss")
        return metric
    raise TypeError(f"cannot interpret metric {m!r}")
