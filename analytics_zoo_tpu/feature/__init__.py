"""Feature layer: text and image preprocessing pipelines.

The analog of the reference's feature package
(ref: zoo/src/main/scala/com/intel/analytics/zoo/feature/ -- the
``TextSet``/``TextFeature`` text chain, the OpenCV-backed ``ImageSet``
op library, and ``Relations`` QA ranking pairs; python surface
pyzoo/zoo/feature/). Host-side numpy/PIL preprocessing feeding
``ZooDataset``; the accelerator never sees variable shapes.
"""

from analytics_zoo_tpu.feature.text import (
    Normalizer, Relation, Relations, SequenceShaper, TextFeature,
    TextFeatureToSample, TextSet, Tokenizer, WordIndexer)
from analytics_zoo_tpu.feature.image import (
    ImageBrightness, ImageCenterCrop, ImageChannelNormalize,
    ImageChannelOrder, ImageHFlip, ImageHue, ImageMatToTensor,
    ImagePixelNormalizer, ImageRandomCrop, ImageRandomPreprocessing,
    ImageResize, ImageSaturation, ImageSet, ImageSetToSample)
from analytics_zoo_tpu.feature.image3d import (
    AffineTransform3D, CenterCrop3D, Crop3D, RandomCrop3D, Rotate3D)

__all__ = [
    "TextFeature", "TextSet", "Tokenizer", "Normalizer", "WordIndexer",
    "SequenceShaper", "TextFeatureToSample", "Relation", "Relations",
    "ImageSet", "ImageResize", "ImageCenterCrop", "ImageRandomCrop",
    "ImageHFlip", "ImageBrightness", "ImageHue", "ImageSaturation",
    "ImageChannelNormalize", "ImagePixelNormalizer", "ImageChannelOrder",
    "ImageMatToTensor", "ImageSetToSample", "ImageRandomPreprocessing",
    "Crop3D", "CenterCrop3D", "RandomCrop3D", "Rotate3D",
    "AffineTransform3D",
]
