"""ImageSet + composable image preprocessing ops.

The analog of the reference's OpenCV-backed image op library
(ref: zoo/src/main/scala/com/intel/analytics/zoo/feature/image/ --
ImageSet.scala, ImageResize.scala, ImageCenterCrop.scala,
ImageRandomCrop.scala, ImageHFlip.scala, ImageBrightness.scala,
ImageHue.scala, ImageSaturation.scala, ImageChannelNormalize.scala,
ImagePixelNormalizer.scala, ImageChannelOrder.scala,
ImageMatToTensor.scala, ImageSetToSample.scala,
ImageRandomPreprocessing.scala).

Host-side PIL/numpy instead of OpenCV JNI; images travel as float32
HWC arrays (NHWC is the TPU-friendly layout XLA convolutions prefer --
``ImageMatToTensor(format='NCHW')`` exists for torch-import parity).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class ImageFeature:
    """One image record (ref: ImageFeature keys image/label/uri;
    ``bboxes``/``bbox_labels`` mirror the detection keys the reference's
    RoiImageFeature carries through its augmentation chain)."""

    def __init__(self, image: np.ndarray, label: Optional[int] = None,
                 uri: Optional[str] = None,
                 bboxes: Optional[np.ndarray] = None,
                 bbox_labels: Optional[np.ndarray] = None):
        self.image = np.asarray(image, np.float32)
        self.label = label
        self.uri = uri
        self.bboxes = (None if bboxes is None
                       else np.asarray(bboxes, np.float32).reshape(-1, 4))
        self.bbox_labels = (None if bbox_labels is None
                            else np.asarray(bbox_labels, np.int32))
        self.sample: Optional[np.ndarray] = None


class ImageProcessing:
    """Per-image op; compose via ImageSet.transform chains
    (ref: ImageProcessing.scala)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        feature.image = self.apply_image(feature.image)
        return feature

    def apply_image(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        return self.transform(feature)


class ImageResize(ImageProcessing):
    """Bilinear resize to (h, w); bboxes scale along
    (ref: ImageResize.scala)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.resize_h, self.resize_w = resize_h, resize_w

    def transform(self, feature: ImageFeature) -> ImageFeature:
        h, w = feature.image.shape[:2]
        feature.image = self.apply_image(feature.image)
        if feature.bboxes is not None:
            b = feature.bboxes.copy()
            b[:, 0::2] *= self.resize_w / w
            b[:, 1::2] *= self.resize_h / h
            feature.bboxes = b
        return feature

    def apply_image(self, img):
        from PIL import Image

        # per-channel float ('F' mode) resize: no 0-255 clip/quantize, so
        # resizing after normalization keeps the data intact
        size = (self.resize_w, self.resize_h)
        chans = [np.asarray(
            Image.fromarray(np.ascontiguousarray(img[..., c]), mode="F")
            .resize(size, Image.Resampling.BILINEAR), np.float32)
            for c in range(img.shape[-1])]
        return np.stack(chans, axis=-1)


def _crop_bboxes(feature: "ImageFeature", top: int, left: int,
                 crop_h: int, crop_w: int) -> None:
    """Shift bboxes into the crop frame, clip to it, and drop boxes
    (plus their labels) that fell entirely outside -- cropping with
    stale pre-crop coordinates would silently corrupt detection
    targets."""
    if feature.bboxes is None:
        return
    b = feature.bboxes.copy()
    b[:, 0::2] = np.clip(b[:, 0::2] - left, 0, crop_w)
    b[:, 1::2] = np.clip(b[:, 1::2] - top, 0, crop_h)
    keep = (b[:, 2] > b[:, 0]) & (b[:, 3] > b[:, 1])
    feature.bboxes = b[keep]
    if feature.bbox_labels is not None:
        feature.bbox_labels = feature.bbox_labels[keep]


class ImageCenterCrop(ImageProcessing):
    """Crop (crop_h, crop_w) from the center; bboxes shift/clip/drop
    with the crop (ref: ImageCenterCrop.scala)."""

    def __init__(self, crop_h: int, crop_w: int):
        self.crop_h, self.crop_w = crop_h, crop_w

    def _offsets(self, img) -> Tuple[int, int]:
        h, w = img.shape[:2]
        return (max(0, (h - self.crop_h) // 2),
                max(0, (w - self.crop_w) // 2))

    def transform(self, feature: ImageFeature) -> ImageFeature:
        top, left = self._offsets(feature.image)
        feature.image = feature.image[top:top + self.crop_h,
                                      left:left + self.crop_w]
        _crop_bboxes(feature, top, left, self.crop_h, self.crop_w)
        return feature

    def apply_image(self, img):
        top, left = self._offsets(img)
        return img[top:top + self.crop_h, left:left + self.crop_w]


class ImageRandomCrop(ImageProcessing):
    """Crop (crop_h, crop_w) at a uniform random offset; bboxes
    shift/clip/drop with the crop (ref: ImageRandomCrop.scala)."""

    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.crop_h, self.crop_w = crop_h, crop_w
        self._rng = np.random.RandomState(seed)

    def _offsets(self, img) -> Tuple[int, int]:
        h, w = img.shape[:2]
        return (self._rng.randint(0, max(1, h - self.crop_h + 1)),
                self._rng.randint(0, max(1, w - self.crop_w + 1)))

    def transform(self, feature: ImageFeature) -> ImageFeature:
        top, left = self._offsets(feature.image)
        feature.image = feature.image[top:top + self.crop_h,
                                      left:left + self.crop_w]
        _crop_bboxes(feature, top, left, self.crop_h, self.crop_w)
        return feature

    def apply_image(self, img):
        top, left = self._offsets(img)
        return img[top:top + self.crop_h, left:left + self.crop_w]


class ImageHFlip(ImageProcessing):
    """Horizontal mirror; bboxes mirror with it (ref: ImageHFlip.scala)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        w = feature.image.shape[1]
        feature.image = self.apply_image(feature.image)
        if feature.bboxes is not None:
            b = feature.bboxes.copy()
            b[:, 0], b[:, 2] = w - feature.bboxes[:, 2], \
                w - feature.bboxes[:, 0]
            feature.bboxes = b
        return feature

    def apply_image(self, img):
        return img[:, ::-1]


class ImageBrightness(ImageProcessing):
    """Add a uniform random delta in [delta_low, delta_high]
    (ref: ImageBrightness.scala)."""

    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.delta_low, self.delta_high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def apply_image(self, img):
        delta = self._rng.uniform(self.delta_low, self.delta_high)
        return np.clip(img + delta, 0.0, 255.0)


def _rgb_to_hsv(img):
    import colorsys  # noqa: F401  (documenting the formula source)

    r, g, b = img[..., 0] / 255.0, img[..., 1] / 255.0, img[..., 2] / 255.0
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    rc, gc, bc = (maxc - r) / dz, (maxc - g) / dz, (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta == 0, 0.0, (h / 6.0) % 1.0)
    return np.stack([h, s, v], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r = np.select(conds, [v, q, p, p, t, v])
    g = np.select(conds, [t, v, v, q, p, p])
    b = np.select(conds, [p, p, t, v, v, q])
    return np.stack([r, g, b], -1) * 255.0


class ImageHue(ImageProcessing):
    """Rotate hue by a random delta in degrees (ref: ImageHue.scala)."""

    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.delta_low, self.delta_high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def apply_image(self, img):
        hsv = _rgb_to_hsv(img)
        delta = self._rng.uniform(self.delta_low, self.delta_high) / 360.0
        hsv[..., 0] = (hsv[..., 0] + delta) % 1.0
        return np.clip(_hsv_to_rgb(hsv), 0.0, 255.0)


class ImageSaturation(ImageProcessing):
    """Scale saturation by a random factor (ref: ImageSaturation.scala)."""

    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.delta_low, self.delta_high = delta_low, delta_high
        self._rng = np.random.RandomState(seed)

    def apply_image(self, img):
        hsv = _rgb_to_hsv(img)
        hsv[..., 1] = np.clip(
            hsv[..., 1] * self._rng.uniform(self.delta_low,
                                            self.delta_high), 0.0, 1.0)
        return np.clip(_hsv_to_rgb(hsv), 0.0, 255.0)


class ImageChannelNormalize(ImageProcessing):
    """(x - mean) / std per channel (ref: ImageChannelNormalize.scala)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0,
                 std_b: float = 1.0):
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.std = np.asarray([std_r, std_g, std_b], np.float32)

    def apply_image(self, img):
        return (img - self.mean) / self.std


class ImagePixelNormalizer(ImageProcessing):
    """Subtract a per-pixel mean image (ref: ImagePixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply_image(self, img):
        return img - self.means


class ImageChannelOrder(ImageProcessing):
    """RGB <-> BGR channel swap (ref: ImageChannelOrder.scala)."""

    def apply_image(self, img):
        return img[..., ::-1]


class ImageMatToTensor(ImageProcessing):
    """Fix the final layout: 'NHWC' (TPU-native) or 'NCHW'
    (torch-import parity) (ref: ImageMatToTensor.scala format arg)."""

    def __init__(self, format: str = "NHWC"):  # noqa: A002
        if format not in ("NHWC", "NCHW"):
            raise ValueError("format must be NHWC or NCHW")
        self.format = format

    def apply_image(self, img):
        if self.format == "NCHW":
            return np.transpose(img, (2, 0, 1))
        return img


class ImageSetToSample(ImageProcessing):
    """Terminal stage: freeze the current image as the sample array
    (ref: ImageSetToSample.scala)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        feature.sample = np.asarray(feature.image, np.float32)
        return feature

    def apply_image(self, img):
        return img


class ImageRandomPreprocessing(ImageProcessing):
    """Apply an op with probability p (ref: ImageRandomPreprocessing.scala)."""

    def __init__(self, op: ImageProcessing, prob: float,
                 seed: Optional[int] = None):
        self.op = op
        self.prob = prob
        self._rng = np.random.RandomState(seed)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        if self._rng.uniform() < self.prob:
            return self.op.transform(feature)
        return feature

    def apply_image(self, img):
        if self._rng.uniform() < self.prob:
            return self.op.apply_image(img)
        return img


class ImageExpand(ImageProcessing):
    """Zoom-out augmentation: place the image on a mean-filled canvas
    expanded by a random ratio in [1, max_expand_ratio], shifting any
    bboxes with it (ref: zoo/.../feature/image/ImageExpand -> BigDL
    Expand op -- the SSD small-object augmentation)."""

    def __init__(self, max_expand_ratio: float = 4.0,
                 means: Sequence[float] = (123.0, 117.0, 104.0),
                 seed: Optional[int] = None):
        self.max_expand_ratio = max_expand_ratio
        self.means = np.asarray(means, np.float32)
        self._rng = np.random.RandomState(seed)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        img = feature.image
        h, w = img.shape[:2]
        ratio = self._rng.uniform(1.0, self.max_expand_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        top = self._rng.randint(0, nh - h + 1)
        left = self._rng.randint(0, nw - w + 1)
        canvas = np.broadcast_to(
            self.means[:img.shape[-1]],
            (nh, nw, img.shape[-1])).astype(np.float32).copy()
        canvas[top:top + h, left:left + w] = img
        feature.image = canvas
        if feature.bboxes is not None:
            b = feature.bboxes.copy()
            b[:, 0::2] += left
            b[:, 1::2] += top
            feature.bboxes = b
        return feature

    def apply_image(self, img):
        return self.transform(ImageFeature(img)).image


class ImageFiller(ImageProcessing):
    """Fill a normalized-coordinate region with a constant value
    (ref: zoo/.../feature/image/ImageFiller -> BigDL Filler -- used to
    black out regions, e.g. license plates)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        self.start_x, self.start_y = start_x, start_y
        self.end_x, self.end_y = end_x, end_y
        self.value = value

    def apply_image(self, img):
        h, w = img.shape[:2]
        out = img.copy()
        x1 = int(np.clip(self.start_x * w, 0, w))
        x2 = int(np.clip(self.end_x * w, 0, w))
        y1 = int(np.clip(self.start_y * h, 0, h))
        y2 = int(np.clip(self.end_y * h, 0, h))
        out[y1:y2, x1:x2] = self.value
        return out


class ImageAspectScale(ImageProcessing):
    """Aspect-preserving resize: shorter side to ``min_size``, longer
    side capped at ``max_size``, optionally rounded to a multiple
    (ref: zoo/.../feature/image/ImageAspectScale -> BigDL AspectScale,
    the Faster-RCNN input scaling); bboxes scale along."""

    def __init__(self, min_size: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.min_size = min_size
        self.max_size = max_size
        self.scale_multiple_of = scale_multiple_of

    def _scale_for(self, h: int, w: int) -> float:
        short, long = min(h, w), max(h, w)
        scale = self.min_size / short
        if scale * long > self.max_size:
            scale = self.max_size / long
        return scale

    def transform(self, feature: ImageFeature) -> ImageFeature:
        h, w = feature.image.shape[:2]
        scale = self._scale_for(h, w)
        nh, nw = int(round(h * scale)), int(round(w * scale))
        m = self.scale_multiple_of
        if m > 1:
            nh, nw = -(-nh // m) * m, -(-nw // m) * m
        # delegate: ImageResize owns the image+bbox rescale logic
        return ImageResize(nh, nw).transform(feature)

    def apply_image(self, img):
        return self.transform(ImageFeature(img)).image


class ImageRandomAspectScale(ImageProcessing):
    """AspectScale with the short-side target drawn from ``min_sizes``
    (ref: zoo/.../feature/image/ImageRandomAspectScale)."""

    def __init__(self, min_sizes: Sequence[int], max_size: int = 1000,
                 scale_multiple_of: int = 1, seed: Optional[int] = None):
        self.min_sizes = list(min_sizes)
        self.max_size = max_size
        self.scale_multiple_of = scale_multiple_of
        self._rng = np.random.RandomState(seed)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        size = self.min_sizes[self._rng.randint(len(self.min_sizes))]
        return ImageAspectScale(size, self.max_size,
                                self.scale_multiple_of).transform(feature)

    def apply_image(self, img):
        return self.transform(ImageFeature(img)).image


class ImageColorJitter(ImageProcessing):
    """Random brightness/contrast/saturation in random order
    (ref: zoo/.../feature/image/ImageColorJitter -> BigDL ColorJitter,
    the SSD photometric-distortion chain)."""

    def __init__(self, brightness_delta: float = 32.0,
                 contrast_range: Tuple[float, float] = (0.5, 1.5),
                 saturation_range: Tuple[float, float] = (0.5, 1.5),
                 seed: Optional[int] = None):
        self.brightness_delta = brightness_delta
        self.contrast_range = contrast_range
        self.saturation_range = saturation_range
        self._rng = np.random.RandomState(seed)

    def apply_image(self, img):
        ops = [self._brightness, self._contrast, self._saturation]
        for i in self._rng.permutation(len(ops)):
            img = ops[i](img)
        return img

    def _brightness(self, img):
        delta = self._rng.uniform(-self.brightness_delta,
                                  self.brightness_delta)
        return np.clip(img + delta, 0.0, 255.0)

    def _contrast(self, img):
        f = self._rng.uniform(*self.contrast_range)
        mean = img.mean()
        return np.clip((img - mean) * f + mean, 0.0, 255.0)

    def _saturation(self, img):
        if img.shape[-1] != 3:
            return img
        f = self._rng.uniform(*self.saturation_range)
        gray = img.mean(axis=-1, keepdims=True)
        return np.clip((img - gray) * f + gray, 0.0, 255.0)


# the reference wraps ops in RandomTransformer(op, prob); identical
# semantics to ImageRandomPreprocessing (ref: RandomTransformer.scala)
ImageRandomTransformer = ImageRandomPreprocessing


class ChainedImageProcessing(ImageProcessing):
    """Left-to-right composition (``a >> b`` on ops would shadow
    Preprocessing; ImageSet.transform chains instead)."""

    def __init__(self, ops: Sequence[ImageProcessing]):
        self.ops = list(ops)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        for op in self.ops:
            feature = op.transform(feature)
        return feature

    def apply_image(self, img):
        f = ImageFeature(img)
        return self.transform(f).image


class ImageSet:
    """A collection of images flowing through the op chain
    (ref: ImageSet.scala; python pyzoo/zoo/feature/image/imageset.py)."""

    def __init__(self, features: Sequence[ImageFeature]):
        self.features: List[ImageFeature] = list(features)

    @classmethod
    def from_arrays(cls, images: np.ndarray,
                    labels: Optional[Sequence[int]] = None) -> "ImageSet":
        labels = labels if labels is not None else [None] * len(images)
        return cls([ImageFeature(im, la) for im, la in zip(images, labels)])

    @classmethod
    def read(cls, folder: str) -> "ImageSet":
        """Read a class-per-subfolder image directory
        (ref: ImageSet.read; NNImageReader). A flat folder of images
        reads with ``label=None``."""
        from PIL import Image

        from analytics_zoo_tpu.feature._io import walk_class_folders

        feats = []
        for path, label in walk_class_folders(folder):
            img = np.asarray(Image.open(path).convert("RGB"),
                             np.float32)
            feats.append(ImageFeature(img, label, uri=path))
        return cls(feats)

    def transform(self, *ops: ImageProcessing) -> "ImageSet":
        chain = ChainedImageProcessing(ops) if len(ops) > 1 else ops[0]
        for f in self.features:
            chain.transform(f)
        return self

    def get_images(self) -> List[np.ndarray]:
        return [f.image for f in self.features]

    def get_labels(self) -> List[Optional[int]]:
        return [f.label for f in self.features]

    def to_arrays(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        samples = [f.sample if f.sample is not None else f.image
                   for f in self.features]
        x = np.stack(samples)
        labels = self.get_labels()
        y = (np.asarray(labels, np.int32)
             if all(l is not None for l in labels) else None)
        return x, y

    def to_dataset(self):
        from analytics_zoo_tpu.data.dataset import ZooDataset

        x, y = self.to_arrays()
        return ZooDataset.from_ndarrays(x, y)

    def __len__(self) -> int:
        return len(self.features)
