"""Shared corpus-directory walking for ImageSet.read / TextSet.read."""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple


def walk_class_folders(path: str
                       ) -> Iterator[Tuple[str, Optional[int]]]:
    """Yield (file_path, label) over a class-per-subfolder dataset dir
    (label = 0-based sorted-subfolder index). A flat folder of files
    yields them with label None."""
    classes = sorted(d for d in os.listdir(path)
                     if os.path.isdir(os.path.join(path, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    for c in classes or [""]:
        sub = os.path.join(path, c) if c else path
        for name in sorted(os.listdir(sub)):
            fpath = os.path.join(sub, name)
            if os.path.isfile(fpath):
                yield fpath, label_of.get(c)
