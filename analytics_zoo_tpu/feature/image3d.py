"""3-D image preprocessing ops (volumetric / medical imaging).

The analog of the reference's image3d family
(ref: zoo/src/main/scala/com/intel/analytics/zoo/feature/image3d/ --
Cropper.scala (Crop3D / RandomCrop3D / CenterCrop3D), Rotation.scala
(Rotate3D around an axis by trilinear resampling), Affine.scala
(AffineTransform3D matrix warp)). Volumes travel as float32 [D, H, W]
or [D, H, W, C] arrays; ops compose through the same ``ImageSet`` /
``ImageProcessing`` chain as the 2-D library.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.feature.image import ImageProcessing


def _spatial(img: np.ndarray):
    """(depth, height, width) regardless of a trailing channel dim."""
    return img.shape[:3]


class Crop3D(ImageProcessing):
    """Crop a [depth, height, width] box at ``start`` (z, y, x)
    (ref: image3d/Cropper.scala Crop3D). The box must fit -- a silent
    short slice would only crash later at batch-stacking time."""

    def __init__(self, start: Sequence[int], patch: Sequence[int]):
        self.start = tuple(int(v) for v in start)
        self.patch = tuple(int(v) for v in patch)
        if any(v < 0 for v in self.start) or \
                any(v <= 0 for v in self.patch):
            raise ValueError(f"invalid crop start={self.start} "
                             f"patch={self.patch}")

    def apply_image(self, img):
        dims = _spatial(img)
        for i in range(3):
            if self.start[i] + self.patch[i] > dims[i]:
                raise ValueError(
                    f"crop box start={self.start} patch={self.patch} "
                    f"does not fit volume {dims}")
        z, y, x = self.start
        d, h, w = self.patch
        return img[z:z + d, y:y + h, x:x + w]


class CenterCrop3D(ImageProcessing):
    """(ref: Cropper.scala CenterCrop3D)."""

    def __init__(self, patch: Sequence[int]):
        self.patch = tuple(int(v) for v in patch)

    def apply_image(self, img):
        dims = _spatial(img)
        start = [max(0, (dims[i] - self.patch[i]) // 2) for i in range(3)]
        return Crop3D(start, self.patch).apply_image(img)


class RandomCrop3D(ImageProcessing):
    """(ref: Cropper.scala RandomCrop3D)."""

    def __init__(self, patch: Sequence[int], seed: Optional[int] = None):
        self.patch = tuple(int(v) for v in patch)
        self._rng = np.random.RandomState(seed)

    def apply_image(self, img):
        dims = _spatial(img)
        start = [self._rng.randint(0, max(1, dims[i] - self.patch[i] + 1))
                 for i in range(3)]
        return Crop3D(start, self.patch).apply_image(img)


def _trilinear_sample(img: np.ndarray, coords: np.ndarray) -> np.ndarray:
    """Sample ``img`` [D, H, W] or [D, H, W, C] at fractional coords
    [3, N] with trilinear interpolation (indices/weights computed once;
    gathers broadcast over a trailing channel axis); out-of-bounds
    reads clamp to the edge. Returns [N] or [N, C]."""
    d, h, w = img.shape[:3]
    z, y, x = coords
    z0 = np.clip(np.floor(z).astype(np.int64), 0, d - 1)
    y0 = np.clip(np.floor(y).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(x).astype(np.int64), 0, w - 1)
    z1 = np.minimum(z0 + 1, d - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    expand = img.ndim == 4

    def frac(v, v0):
        f = np.clip(v - v0, 0.0, 1.0)
        return f[:, None] if expand else f

    fz, fy, fx = frac(z, z0), frac(y, y0), frac(x, x0)

    def at(zi, yi, xi):
        return img[zi, yi, xi]

    c000, c001 = at(z0, y0, x0), at(z0, y0, x1)
    c010, c011 = at(z0, y1, x0), at(z0, y1, x1)
    c100, c101 = at(z1, y0, x0), at(z1, y0, x1)
    c110, c111 = at(z1, y1, x0), at(z1, y1, x1)
    c00 = c000 * (1 - fx) + c001 * fx
    c01 = c010 * (1 - fx) + c011 * fx
    c10 = c100 * (1 - fx) + c101 * fx
    c11 = c110 * (1 - fx) + c111 * fx
    c0 = c00 * (1 - fy) + c01 * fy
    c1 = c10 * (1 - fy) + c11 * fy
    return c0 * (1 - fz) + c1 * fz


class AffineTransform3D(ImageProcessing):
    """Warp a volume by a 3x3 matrix + translation about its center
    (ref: image3d/Affine.scala AffineTransform3D): output voxel p maps
    to input ``mat @ (p - c) + c + translation``."""

    def __init__(self, mat: np.ndarray,
                 translation: Optional[Sequence[float]] = None):
        self.mat = np.asarray(mat, np.float64).reshape(3, 3)
        self.translation = np.asarray(translation if translation
                                      is not None else (0, 0, 0),
                                      np.float64)

    def apply_image(self, img):
        img = np.asarray(img, np.float32)
        dims = _spatial(img)
        grid = np.stack(np.meshgrid(
            np.arange(dims[0]), np.arange(dims[1]), np.arange(dims[2]),
            indexing="ij"), 0).reshape(3, -1).astype(np.float64)
        center = (np.asarray(dims, np.float64) - 1)[:, None] / 2
        src = (self.mat @ (grid - center) + center
               + self.translation[:, None])
        out = _trilinear_sample(img, src)
        return out.reshape(img.shape).astype(np.float32)


class Rotate3D(AffineTransform3D):
    """Rotate about one axis ('z' = depth, 'y', or 'x') by ``angle``
    radians (ref: image3d/Rotation.scala)."""

    def __init__(self, angle: float, axis: str = "z"):
        c, s = float(np.cos(angle)), float(np.sin(angle))
        if axis == "z":        # rotate in the (h, w) plane
            mat = [[1, 0, 0], [0, c, -s], [0, s, c]]
        elif axis == "y":      # (d, w) plane
            mat = [[c, 0, -s], [0, 1, 0], [s, 0, c]]
        elif axis == "x":      # (d, h) plane
            mat = [[c, -s, 0], [s, c, 0], [0, 0, 1]]
        else:
            raise ValueError("axis must be one of z/y/x")
        super().__init__(np.asarray(mat))
