"""TextSet / TextFeature preprocessing chain + Relations.

The analog of the reference's text feature pipeline
(ref: zoo/src/main/scala/com/intel/analytics/zoo/feature/text/ --
TextSet.scala, TextFeature.scala, Tokenizer.scala, Normalizer.scala,
WordIndexer.scala, SequenceShaper.scala, TextFeatureToSample.scala;
python surface pyzoo/zoo/feature/text/text_set.py) and of the QA
ranking ``Relations`` (ref: zoo/.../feature/common/Relations.scala,
pyzoo/zoo/feature/common.py:30-93).

Local in-process lists instead of RDDs: the Spark local/distributed
split dissolves -- multi-host runs shard the *resulting arrays* through
``ZooDataset``, not the preprocessing itself.
"""

from __future__ import annotations

import csv
import json
import re
import string
from collections import Counter
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np


class TextFeature:
    """One text record with its evolving pipeline state
    (ref: TextFeature.scala keys text/label/tokens/indexedTokens/sample)."""

    def __init__(self, text: str, label: Optional[int] = None,
                 uri: Optional[str] = None):
        self.text = text
        self.label = label
        self.uri = uri
        self.tokens: Optional[List[str]] = None
        self.indices: Optional[np.ndarray] = None
        self.sample: Optional[np.ndarray] = None

    def get_tokens(self) -> Optional[List[str]]:
        return self.tokens

    def get_sample(self) -> Optional[np.ndarray]:
        return self.sample


class TextTransformer:
    """Per-feature transform; compose via TextSet.transform chains
    (ref: text/TextTransformer.scala)."""

    def transform(self, feature: TextFeature) -> TextFeature:
        raise NotImplementedError

    def __call__(self, feature: TextFeature) -> TextFeature:
        return self.transform(feature)


class Tokenizer(TextTransformer):
    """Whitespace tokenization (ref: Tokenizer.scala)."""

    def transform(self, feature: TextFeature) -> TextFeature:
        feature.tokens = feature.text.split()
        return feature


class Normalizer(TextTransformer):
    """Lower-case tokens and strip non-alphanumeric characters
    (ref: Normalizer.scala)."""

    _PUNCT = re.compile(f"[{re.escape(string.punctuation)}]")

    def transform(self, feature: TextFeature) -> TextFeature:
        if feature.tokens is None:
            raise ValueError("Normalizer requires tokens: tokenize first")
        toks = [self._PUNCT.sub("", t.lower()) for t in feature.tokens]
        feature.tokens = [t for t in toks if t]
        return feature


class WordIndexer(TextTransformer):
    """Map tokens to 1-based indices via a vocabulary
    (ref: WordIndexer.scala; unknown words are dropped, matching the
    reference's behavior of skipping out-of-vocab tokens)."""

    def __init__(self, word_index: Dict[str, int]):
        self.word_index = word_index

    def transform(self, feature: TextFeature) -> TextFeature:
        if feature.tokens is None:
            raise ValueError("WordIndexer requires tokens: tokenize first")
        feature.indices = np.asarray(
            [self.word_index[t] for t in feature.tokens
             if t in self.word_index], np.int32)
        return feature


class SequenceShaper(TextTransformer):
    """Pad/truncate index sequences to a fixed length
    (ref: SequenceShaper.scala; ``trunc_mode`` 'pre' keeps the tail,
    'post' keeps the head -- matching text_set.py:273-285)."""

    def __init__(self, len: int, trunc_mode: str = "pre",  # noqa: A002
                 pad_element: int = 0):
        if trunc_mode not in ("pre", "post"):
            raise ValueError("trunc_mode must be 'pre' or 'post'")
        self.target_len = len
        self.trunc_mode = trunc_mode
        self.pad_element = pad_element

    def transform(self, feature: TextFeature) -> TextFeature:
        if feature.indices is None:
            raise ValueError("SequenceShaper requires indices: word2idx "
                             "first")
        idx = feature.indices
        n = self.target_len
        if len(idx) > n:
            idx = idx[-n:] if self.trunc_mode == "pre" else idx[:n]
        elif len(idx) < n:
            pad = np.full(n - len(idx), self.pad_element, np.int32)
            idx = np.concatenate([idx, pad])
        feature.indices = idx
        return feature


class TextFeatureToSample(TextTransformer):
    """Terminal stage: indices become the trainable sample array
    (ref: TextFeatureToSample.scala)."""

    def transform(self, feature: TextFeature) -> TextFeature:
        if feature.indices is None:
            raise ValueError("TextFeatureToSample requires indices")
        feature.sample = np.asarray(feature.indices, np.int32)
        return feature


class TextSet:
    """A corpus flowing through the text pipeline
    (ref: TextSet.scala; python text_set.py:23-455). The
    tokenize/normalize/word2idx/shape_sequence/generate_sample chain
    mirrors the reference's fluent API."""

    def __init__(self, features: Sequence[TextFeature]):
        self.features: List[TextFeature] = list(features)
        self._word_index: Optional[Dict[str, int]] = None

    # ------------------------------------------------------ construction --
    @classmethod
    def from_texts(cls, texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        labels = labels if labels is not None else [None] * len(texts)
        return cls([TextFeature(t, l) for t, l in zip(texts, labels)])

    @classmethod
    def read(cls, path: str) -> "TextSet":
        """Read a category-per-subfolder corpus (the news20 layout the
        reference's TextClassification example uses; ref:
        TextSet.read, text_set.py:302-331): each subfolder is a class,
        each file one text; labels are 0-based in sorted-folder order.
        A flat folder of files reads with ``label=None``."""
        from analytics_zoo_tpu.feature._io import walk_class_folders

        feats = []
        for fpath, label in walk_class_folders(path):
            with open(fpath, encoding="utf-8", errors="replace") as f:
                feats.append(TextFeature(f.read(), label, uri=fpath))
        return cls(feats)

    @classmethod
    def read_csv(cls, path: str) -> "TextSet":
        """CSV rows of (uri/id, text) (ref: text_set.py:332-353)."""
        feats = []
        with open(path, newline="") as f:
            for row in csv.reader(f):
                if len(row) < 2:
                    continue
                feats.append(TextFeature(row[1], uri=row[0]))
        return cls(feats)

    # -------------------------------------------------------- transforms --
    def transform(self, transformer: TextTransformer) -> "TextSet":
        for f in self.features:
            transformer.transform(f)
        return self

    def tokenize(self) -> "TextSet":
        return self.transform(Tokenizer())

    def normalize(self) -> "TextSet":
        return self.transform(Normalizer())

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict[str, int]] = None
                 ) -> "TextSet":
        """Build the vocabulary and index every feature
        (ref: text_set.py:224-272): words ranked by frequency, the
        ``remove_topN`` most frequent dropped, capped at
        ``max_words_num``, indices starting at 1 (+ existing_map
        extension)."""
        counts = Counter()
        for f in self.features:
            if f.tokens is None:
                raise ValueError("word2idx requires tokens: tokenize "
                                 "first")
            counts.update(f.tokens)
        ranked = [w for w, c in counts.most_common() if c >= min_freq]
        ranked = ranked[remove_topN:]
        if max_words_num > 0:
            ranked = ranked[:max_words_num]
        vocab: Dict[str, int] = dict(existing_map or {})
        next_idx = max(vocab.values(), default=0) + 1
        for w in ranked:
            if w not in vocab:
                vocab[w] = next_idx
                next_idx += 1
        self._word_index = vocab
        return self.transform(WordIndexer(vocab))

    def shape_sequence(self, len: int, trunc_mode: str = "pre",  # noqa: A002
                       pad_element: int = 0) -> "TextSet":
        return self.transform(SequenceShaper(len, trunc_mode, pad_element))

    def generate_sample(self) -> "TextSet":
        return self.transform(TextFeatureToSample())

    # ----------------------------------------------------------- access --
    def get_word_index(self) -> Optional[Dict[str, int]]:
        return self._word_index

    def set_word_index(self, vocab: Dict[str, int]) -> "TextSet":
        self._word_index = vocab
        return self

    def save_word_index(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self._word_index, f)

    def load_word_index(self, path: str) -> "TextSet":
        with open(path) as f:
            self._word_index = json.load(f)
        return self

    def get_texts(self) -> List[str]:
        return [f.text for f in self.features]

    def get_labels(self) -> List[Optional[int]]:
        return [f.label for f in self.features]

    def get_samples(self) -> List[Optional[np.ndarray]]:
        return [f.sample for f in self.features]

    def random_split(self, fraction: float, seed: int = 0):
        idx = np.random.RandomState(seed).permutation(len(self.features))
        cut = int(len(idx) * fraction)
        first = TextSet([self.features[i] for i in idx[:cut]])
        second = TextSet([self.features[i] for i in idx[cut:]])
        first._word_index = second._word_index = self._word_index
        return first, second

    def __len__(self) -> int:
        return len(self.features)

    # --------------------------------------------------------- to arrays --
    def to_arrays(self):
        """(x [N, L] int32, y [N] int32 or None) for Estimator/zoo
        models."""
        samples = self.get_samples()
        if any(s is None for s in samples):
            raise ValueError("generate_sample() must run before "
                             "to_arrays()")
        x = np.stack(samples)
        labels = self.get_labels()
        y = (np.asarray(labels, np.int32)
             if all(l is not None for l in labels) else None)
        return x, y

    def to_dataset(self):
        from analytics_zoo_tpu.data.dataset import ZooDataset

        x, y = self.to_arrays()
        return ZooDataset.from_ndarrays(x, y)


class Relation:
    """(id1, id2, label) QA ranking relation
    (ref: pyzoo/zoo/feature/common.py:30-51)."""

    def __init__(self, id1: str, id2: str, label: int):
        self.id1, self.id2, self.label = id1, id2, int(label)

    def __repr__(self):
        return f"Relation({self.id1}, {self.id2}, {self.label})"


class Relations:
    """Read relations from csv/parquet-style files
    (ref: common.py:52-93)."""

    @staticmethod
    def read(path: str) -> List[Relation]:
        rels = []
        with open(path, newline="") as f:
            for row in csv.reader(f):
                if len(row) != 3 or row[0] == "id1":
                    continue
                rels.append(Relation(row[0], row[1], int(row[2])))
        return rels


def from_relation_pairs(relations: Iterable[Relation], corpus1: TextSet,
                        corpus2: TextSet, seed: int = 0):
    """Positive/negative pairs for pairwise ranking training
    (ref: TextSet.fromRelationPairs, TextSet.scala; text_set.py:369-400):
    for each positive relation, sample one negative with the same id1;
    returns ([P, 2, L1+L2] int32) interleaved (pos, neg) pair arrays.
    Corpora must be indexed+shaped (samples present), keyed by uri."""
    c1 = {f.uri: f.sample for f in corpus1.features}
    c2 = {f.uri: f.sample for f in corpus2.features}
    by_id1: Dict[str, Dict[int, List[str]]] = {}
    for r in relations:
        # graded relevance collapses to binary: label > 0 is a positive
        by_id1.setdefault(r.id1, {0: [], 1: []})[
            1 if r.label > 0 else 0].append(r.id2)
    rng = np.random.RandomState(seed)
    pairs = []
    for id1, groups in by_id1.items():
        negs = groups[0]
        if not negs:
            continue
        for pos_id in groups[1]:
            neg_id = negs[rng.randint(len(negs))]
            pos = np.concatenate([c1[id1], c2[pos_id]])
            neg = np.concatenate([c1[id1], c2[neg_id]])
            pairs.append(np.stack([pos, neg]))
    return np.stack(pairs).astype(np.int32)


def from_relation_lists(relations: Iterable[Relation], corpus1: TextSet,
                        corpus2: TextSet):
    """Per-query candidate lists for ranking evaluation
    (ref: TextSet.fromRelationLists; text_set.py:401-434): returns a
    list of ([K, L1+L2] int32 x, [K] int32 y) per id1."""
    c1 = {f.uri: f.sample for f in corpus1.features}
    c2 = {f.uri: f.sample for f in corpus2.features}
    grouped: Dict[str, List[Relation]] = {}
    for r in relations:
        grouped.setdefault(r.id1, []).append(r)
    out = []
    for id1, rels in grouped.items():
        x = np.stack([np.concatenate([c1[id1], c2[r.id2]]) for r in rels])
        y = np.asarray([r.label for r in rels], np.int32)
        out.append((x.astype(np.int32), y))
    return out
