"""Weight-only int8 quantization.

The analog of the reference's int8 paths (OpenVINO VNNI models,
``doLoadOpenVINOInt8`` -- ref: InferenceModel.scala int8 loaders,
examples/vnni): per-output-channel symmetric int8 weights with float
scales; matmul-heavy layers dequantize on the fly (XLA fuses the
rescale into the matmul epilogue on TPU).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def quantize_params(params: Any, min_size: int = 1024
                    ) -> Tuple[Any, List[Optional[np.ndarray]]]:
    """Returns (quantized_tree, scales). Arrays with >=2 dims and >=
    ``min_size`` elements become int8 with per-last-axis scales; others
    pass through (scale None). ``scales`` aligns with the tree's flattened
    leaf order."""

    def q(x):
        x = np.asarray(x)
        if x.ndim < 2 or x.size < min_size or \
                not np.issubdtype(x.dtype, np.floating):
            return x, None
        amax = np.max(np.abs(x), axis=tuple(range(x.ndim - 1)),
                      keepdims=True)
        scale = np.maximum(amax, 1e-12) / 127.0
        qx = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
        return qx, scale.astype(np.float32)

    flat, tree = jax.tree_util.tree_flatten(params)
    pairs = [q(l) for l in flat]
    q_tree = jax.tree_util.tree_unflatten(tree, [p[0] for p in pairs])
    return q_tree, [p[1] for p in pairs]


def dequantize_params(q_tree: Any, scales: List[Optional[np.ndarray]],
                      dtype=jnp.float32) -> Any:
    flat, tree = jax.tree_util.tree_flatten(q_tree)
    out = []
    for x, scale in zip(flat, scales):
        if scale is None:
            out.append(jnp.asarray(x))
        else:
            out.append((jnp.asarray(x, jnp.float32)
                        * scale).astype(dtype))
    return jax.tree_util.tree_unflatten(tree, out)
