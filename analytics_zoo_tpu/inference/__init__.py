"""Multi-format inference runtime.

The analog of ``InferenceModel`` (ref: zoo/.../pipeline/inference/
InferenceModel.scala:28-608 and the Java AbstractInferenceModel) --
re-designed TPU-first: where the reference keeps a blocking queue of
``concurrentNum`` model copies for thread-safe prediction, XLA executables
are thread-safe, so one AOT-compiled executable per batch-shape bucket
serves all threads (SURVEY.md section 7 step 7).
"""

from analytics_zoo_tpu.inference.inference_model import (  # noqa: F401
    InferenceModel,
)
from analytics_zoo_tpu.inference.population import (  # noqa: F401
    PopulationInferenceModel,
)
from analytics_zoo_tpu.inference.kv_cache import (  # noqa: F401
    CacheOverflow,
    PagedKVCache,
)
from analytics_zoo_tpu.inference.sharded import (  # noqa: F401
    ShardPlan,
    resolve_shard_plan,
)
from analytics_zoo_tpu.inference.quantize import (  # noqa: F401
    dequantize_params,
    quantize_params,
)
from analytics_zoo_tpu.inference.encrypt import (  # noqa: F401
    decrypt_bytes,
    encrypt_bytes,
)
from analytics_zoo_tpu.inference.graph_executor import (  # noqa: F401
    GraphFunction,
    load_onnx_model,
    load_tf_frozen_graph,
)
from analytics_zoo_tpu.inference.graph_model import (  # noqa: F401
    GraphModel,
)
from analytics_zoo_tpu.inference.importers import (  # noqa: F401
    import_caffe,
    import_onnx,
    import_tf_frozen_graph,
    import_tf_saved_model,
    import_torch_state_dict,
)
