"""Paged KV cache for autoregressive generation serving (ISSUE-10).

The device-memory budget of token streaming is the KV cache: every
attention layer keeps one (key, value) pair per generated position, and
a naive per-request [max_len] allocation wastes HBM on every short
request (the motivation behind vLLM's PagedAttention and the TPU
serving stacks in PAPERS.md). This module is the TPU-native take:

- **One page pool per engine.** All cached K/V live in a single device
  array shaped ``[layers, 2, num_pages, page_size, heads, head_dim]``
  (2 = key/value planes). Fixed shape, allocated once -- the decode
  step's XLA program never changes because a request joined or left.
- **Slot table.** A fixed number of decode *slots* (the continuous
  batcher's admission unit, ``zoo.generation.slots``); each slot owns a
  *block table* row mapping its logical pages to physical pool pages.
  Physical page 0 is the **trash page**: inactive slots' block tables
  point at it, so the fixed-shape decode step's masked-lane writes land
  somewhere harmless instead of corrupting a neighbour's context.
- **Reservation-based admission, lazy assignment.** ``admit`` succeeds
  only when the pool can cover the request's *worst case*
  (``prompt_len + max_new_tokens``), so a stream can never die
  mid-decode from cache exhaustion -- refusal happens exactly once, at
  admission, as a structured ``generation_overflow`` 503 the client can
  retry. Physical pages are assigned lazily as the sequence crosses
  page boundaries (``ensure_length``), and released pages go straight
  back on the free list for the next admission (block reuse).

The allocator is host-side (admission happens at step boundaries on the
host); only the pool itself lives in device memory. Device-side writes
and gathers against the pool are the engine's business
(:mod:`analytics_zoo_tpu.serving.generation.engine`) -- this module
owns *accounting*, and its numbers are exact: ``utilization()`` is
assigned-pages / usable-pages, the gauge the capacity dashboard wants.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np


class CacheOverflow(Exception):
    """Admission refused: the pool cannot cover the request's worst
    case. The serving layer maps this to the structured
    ``generation_overflow`` error (HTTP 503 + Retry-After)."""


class PagedKVCache:
    """Page-pool allocator + device K/V store for one decode engine.

    Args:
      num_layers / num_heads / head_dim: attention geometry of the
        served model (the pool holds one K and one V plane per layer).
      page_size: tokens per page (``zoo.generation.page_size``).
      num_slots: decode slot-table size (``zoo.generation.slots``).
      num_pages: physical pages *excluding* the trash page; 0 = auto:
        enough for every slot to reach ``max_len`` simultaneously
        (``zoo.generation.num_pages``).
      max_len: per-slot length ceiling (prompt + generated,
        ``zoo.generation.max_len``); fixes the block-table width.
      dtype: pool dtype (f32 on the CPU rig; bf16 on TPU halves HBM).

    Thread-safety: the allocator is lock-guarded (admission runs on the
    worker loop, stats() on metric scrapes); the pool array itself is
    only touched by the engine's jitted functions.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 page_size: int = 16, num_slots: int = 8,
                 num_pages: int = 0, max_len: int = 256,
                 dtype: Any = None):
        if page_size < 1 or num_slots < 1 or max_len < 2:
            raise ValueError("page_size/num_slots >= 1, max_len >= 2")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        # block-table width: the most pages one slot can ever need
        self.pages_per_slot = self.pages_for(self.max_len)
        if num_pages <= 0:
            num_pages = self.num_slots * self.pages_per_slot
        self.num_pages = int(num_pages)
        import jax.numpy as jnp

        if dtype is None:
            dtype = jnp.float32
        # physical page 0 is the trash page -> pool holds num_pages + 1
        self.kv = jnp.zeros(
            (self.num_layers, 2, self.num_pages + 1, self.page_size,
             self.num_heads, self.head_dim), dtype=dtype)
        self._lock = threading.Lock()
        self._free_pages: List[int] = list(range(1, self.num_pages + 1))
        self._free_slots: List[int] = list(range(self.num_slots))
        # per-slot accounting (host side; the engine mirrors block
        # tables/lengths to the device per step)
        self._block = np.zeros((self.num_slots, self.pages_per_slot),
                               np.int32)  # 0 = trash (unassigned)
        self._assigned = np.zeros(self.num_slots, np.int32)  # pages
        self._length = np.zeros(self.num_slots, np.int32)    # tokens
        self._reserve = np.zeros(self.num_slots, np.int32)   # worst case
        # pages promised to admitted slots but not yet popped off the
        # free list -- the quantity that makes admission refusal exact
        self._unassigned_reserved = 0

    # ------------------------------------------------------ geometry --
    def pages_for(self, length: int) -> int:
        """Pages covering ``length`` tokens (ceil division)."""
        return -(-int(length) // self.page_size)

    # ----------------------------------------------------- admission --
    def can_admit(self, total_len: int) -> bool:
        with self._lock:
            return self._can_admit_locked(total_len)

    def _can_admit_locked(self, total_len: int) -> bool:
        if total_len > self.max_len or not self._free_slots:
            return False
        need = self.pages_for(total_len)
        avail = len(self._free_pages) - self._unassigned_reserved
        return need <= avail

    def admit(self, prompt_len: int, max_new_tokens: int) -> int:
        """Claim a slot whose sequence may grow to
        ``prompt_len + max_new_tokens`` tokens; reserves (but does not
        yet assign) the worst-case pages. Raises :class:`CacheOverflow`
        when no slot or not enough free pages -- the one refusal point
        of a generation request's lifetime.

        A successful ``admit`` opens an obligation: every code path
        that can run afterwards must reach :meth:`release` or hand the
        slot to an owner that will (e.g. the worker's stream table).
        zoolint's lifecycle engine proves this per CFG path at review
        time (``leak-on-path``, docs/zoolint.md) -- the static form of
        the PR-10 admit-window capacity leak."""
        total = int(prompt_len) + int(max_new_tokens)
        with self._lock:
            if total > self.max_len:
                raise CacheOverflow(
                    f"sequence of up to {total} tokens exceeds "
                    f"max_len {self.max_len}")
            need = self.pages_for(total)
            avail = len(self._free_pages) - self._unassigned_reserved
            if not self._free_slots or need > avail:
                raise CacheOverflow(
                    f"kv cache exhausted: need {need} pages for a "
                    f"{total}-token stream, {max(0, avail)} free "
                    f"(slots free: {len(self._free_slots)})")
            slot = self._free_slots.pop(0)
            self._reserve[slot] = need
            self._unassigned_reserved += need
            self._assigned[slot] = 0
            self._length[slot] = 0
            self._block[slot, :] = 0
            return slot

    def ensure_length(self, slot: int, length: int) -> None:
        """Assign physical pages so positions ``[0, length)`` are
        backed; called by the engine before writing K/V at a new
        position. Never fails for an admitted slot growing inside its
        reservation (that is the point of reserving at admit)."""
        need = self.pages_for(length)
        with self._lock:
            if length > int(self._reserve[slot]) * self.page_size:
                raise ValueError(
                    f"slot {slot} growing past its reservation "
                    f"({length} tokens > {int(self._reserve[slot])} "
                    "pages)")
            while int(self._assigned[slot]) < need:
                page = self._free_pages.pop(0)
                self._block[slot, int(self._assigned[slot])] = page
                self._assigned[slot] += 1
                self._unassigned_reserved -= 1
            self._length[slot] = max(int(self._length[slot]),
                                     int(length))

    def release(self, slot: int) -> None:
        """Return the slot and every page it held to the free lists
        (block reuse: the next admission hands these same pages out).
        Idempotent -- a double release is a no-op, not corruption."""
        with self._lock:
            if slot in self._free_slots:
                return
            n = int(self._assigned[slot])
            self._free_pages.extend(
                int(p) for p in self._block[slot, :n])
            self._unassigned_reserved -= max(
                0, int(self._reserve[slot]) - n)
            self._block[slot, :] = 0
            self._assigned[slot] = 0
            self._length[slot] = 0
            self._reserve[slot] = 0
            self._free_slots.append(slot)
            self._free_slots.sort()

    # ------------------------------------------------ page handoff --
    # ISSUE-20 (disaggregated prefill/decode pools): a prefill replica
    # serializes a slot's pages and hands the stream to a decode
    # replica on another host; the snapshot is page-aligned (whole
    # pages, including the unused tail of the last page) so the
    # importer writes physical pages verbatim and the decode step
    # resumes bit-identically.

    def export_pages(self, slot: int) -> Dict[str, Any]:
        """Serialize ``slot``'s assigned pages + accounting into a
        host-side snapshot dict (``kv`` [layers, 2, n, page_size,
        heads, head_dim], ``length`` tokens, ``reserve`` worst-case
        pages). The slot itself stays admitted -- callers release it
        (or keep decoding) after the handoff is safely published.

        A successful ``export_pages`` opens an obligation: the
        snapshot must reach :meth:`import_pages` (possibly on another
        cache) or the stream's slot must be released -- an exported
        snapshot abandoned on an error path strands the stream with no
        owner. zoolint's lifecycle engine proves this per CFG path
        (``leak-on-path``, kv-handoff spec)."""
        with self._lock:
            if slot in self._free_slots:
                raise ValueError(f"slot {slot} is not admitted")
            n = int(self._assigned[slot])
            pages = [int(p) for p in self._block[slot, :n]]
            length = int(self._length[slot])
            reserve = int(self._reserve[slot])
        # gather outside the lock: device -> host copy of n pages
        kv = np.asarray(self.kv[:, :, np.asarray(pages, np.int32)]) \
            if pages else np.zeros(
                (self.num_layers, 2, 0, self.page_size,
                 self.num_heads, self.head_dim), np.float32)
        return {"kv": kv, "length": length, "reserve": reserve}

    def import_pages(self, snapshot: Dict[str, Any]) -> int:
        """Re-admit a handed-off stream from an :meth:`export_pages`
        snapshot: claims a slot + its worst-case reservation, assigns
        physical pages for the backed length, and writes the page
        contents verbatim. Returns the (new) slot id. Raises
        :class:`CacheOverflow` when no slot / not enough free pages --
        the importer maps that to the structured ``generation_overflow``
        refusal, same as first admission -- and :class:`ValueError` on
        a snapshot whose geometry does not match this pool."""
        kv = np.asarray(snapshot["kv"])
        length = int(snapshot["length"])
        reserve = int(snapshot["reserve"])
        need = self.pages_for(length)
        expect = (self.num_layers, 2, need, self.page_size,
                  self.num_heads, self.head_dim)
        if kv.shape != expect:
            raise ValueError(
                f"snapshot geometry {kv.shape} does not match pool "
                f"{expect}")
        if reserve < need:
            raise ValueError(
                f"snapshot reserve {reserve} pages < backed {need}")
        with self._lock:
            if reserve * self.page_size > self.max_len:
                raise CacheOverflow(
                    f"snapshot reservation of {reserve} pages exceeds "
                    f"max_len {self.max_len}")
            avail = len(self._free_pages) - self._unassigned_reserved
            if not self._free_slots or reserve > avail:
                raise CacheOverflow(
                    f"kv cache exhausted: need {reserve} pages to "
                    f"import a {length}-token stream, "
                    f"{max(0, avail)} free "
                    f"(slots free: {len(self._free_slots)})")
            slot = self._free_slots.pop(0)
            self._reserve[slot] = reserve
            self._block[slot, :] = 0
            pages = [self._free_pages.pop(0) for _ in range(need)]
            for i, page in enumerate(pages):
                self._block[slot, i] = page
            self._assigned[slot] = need
            self._length[slot] = length
            self._unassigned_reserved += reserve - need
        if pages:
            # scatter outside the lock: host -> device page writes
            idx = np.asarray(pages, np.int32)
            self.kv = self.kv.at[:, :, idx].set(
                kv.astype(self.kv.dtype))
        return slot

    # ---------------------------------------------------- step views --
    def block_tables(self) -> np.ndarray:
        """[num_slots, pages_per_slot] int32 physical-page map (0 =
        trash/unassigned) -- a defensive copy the engine ships to the
        device each step."""
        with self._lock:
            return self._block.copy()

    def lengths(self) -> np.ndarray:
        """[num_slots] int32 backed sequence length per slot."""
        with self._lock:
            return self._length.copy()

    # ----------------------------------------------------- accounting --
    def free_slot_count(self) -> int:
        with self._lock:
            return len(self._free_slots)

    def utilization(self) -> float:
        """Assigned pages / usable pages -- the
        ``zoo_generation_kv_utilization_ratio`` gauge."""
        with self._lock:
            return (self.num_pages - len(self._free_pages)) \
                / max(1, self.num_pages)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            assigned = self.num_pages - len(self._free_pages)
            return {
                "num_pages": self.num_pages,
                "page_size": self.page_size,
                "num_slots": self.num_slots,
                "pages_assigned": assigned,
                "pages_reserved_unassigned": self._unassigned_reserved,
                "slots_free": len(self._free_slots),
                "utilization": assigned / max(1, self.num_pages),
                "bytes": int(np.prod(self.kv.shape))
                * self.kv.dtype.itemsize,
            }
