"""PopulationInferenceModel: one warmed compile serves N models.

The serving face of :class:`~analytics_zoo_tpu.learn.population.
PopulationEstimator` (ISSUE-13): a stacked parameter tree ``[N, ...]``
behind the ``predict_async`` contract the serving worker dispatches
through. Two modes:

- ``"tenant"``: the request's ``__tenant__`` wire key selects which
  member answers. The lane index is a TRACED int32 scalar argument of
  the jitted apply -- ``tree_map(lambda a: a[lane], variables)`` is a
  dynamic slice, not a shape -- so every tenant id dispatches through
  the SAME warmed executable. Thousands of per-tenant fine-tuned
  variants serve from one compile instead of thousands of deployments.
- ``"ensemble"``: every member answers the same batch in one vmapped
  dispatch; the reply carries the population ``mean`` and per-member
  ``var`` (the variance is the confidence signal the reference model
  zoo's anomaly-detection scenario thresholds on).

Batching follows :mod:`inference.inference_model`'s idiom: inputs pad
up to power-of-two buckets, compiled executables cache per bucket
shape, and compiles feed the recompile-storm detector -- a healthy
deployment's compile counter stays flat after ``warm_up`` no matter
how many distinct tenants it answers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import record_compile, warming
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.protocol import INVALID_PREFIX

logger = get_logger(__name__)

_REG = get_registry()
_M_SERVE = _REG.counter(
    "zoo_population_dispatch_total",
    "Population-model serving dispatches, by mode (tenant/ensemble)",
    labelnames=("mode",))


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


class PopulationInferenceModel:
    """Serve a stacked ``[N, ...]`` parameter tree.

    Args:
      apply_fn: ``apply_fn(member_variables, x) -> predictions`` for ONE
        member's (unstacked) variables tree.
      variables: the member-stacked variables pytree (leading axis N on
        every leaf).
      n_members: population size; inferred from the first leaf when
        omitted.
      mode: ``"tenant"`` (lane-selected member answers) or
        ``"ensemble"`` (mean + variance over all members).
      default_lane / strict: tenant-mode behavior for requests naming
        no ``__tenant__`` -- answer from ``default_lane``
        (``zoo.serving.tenant.default_lane``), or refuse with a
        structured 400 when strict (``zoo.serving.tenant.strict``).
    """

    def __init__(self, apply_fn: Callable, variables: Any,
                 n_members: Optional[int] = None, mode: str = "tenant",
                 default_lane: Optional[int] = None,
                 strict: Optional[bool] = None):
        if mode not in ("tenant", "ensemble"):
            raise ValueError("mode must be tenant|ensemble")
        cfg = get_config()
        self._apply_fn = apply_fn
        self.variables = variables
        leaves = jax.tree_util.tree_leaves(variables)
        if not leaves:
            raise ValueError("population variables tree is empty")
        self.n_members = (int(n_members) if n_members is not None
                          else int(leaves[0].shape[0]))
        self.mode = mode
        self.default_lane = int(
            cfg.get("zoo.serving.tenant.default_lane", 0)
            if default_lane is None else default_lane)
        self.strict = bool(
            cfg.get("zoo.serving.tenant.strict", False)
            if strict is None else strict)
        # the serving worker keys its tenant routing off this attribute:
        # set (lane count) = requests may carry __tenant__ and dispatch
        # passes the resolved lane; None = a tenant-carrying request is
        # a 400 (ensemble replies aggregate every member, so a lane
        # selector on one is a client error, not a no-op)
        self.tenant_lanes = (self.n_members if mode == "tenant"
                             else None)
        self._compiled: Dict[Any, Any] = {}

    @classmethod
    def from_estimator(cls, pop, mode: str = "tenant",
                       **kwargs) -> "PopulationInferenceModel":
        """Wrap a trained :class:`PopulationEstimator` without copying
        its stacked parameters."""
        if pop.variables is None:
            raise ValueError("population not built; fit() first")
        adapter = pop.adapter

        def apply_fn(variables, x):
            out, _ = adapter.apply(variables, x, training=False)
            return out

        return cls(apply_fn, pop.variables, n_members=pop.n_members,
                   mode=mode, **kwargs)

    # ------------------------------------------------------- tenanting --
    def resolve_lane(self, tenant: Optional[int]) -> Optional[int]:
        """Map a request's ``__tenant__`` (or None) to a concrete lane.
        Raises ``ValueError`` with the structured ``invalid_request``
        prefix -- the serving worker pushes the message as the reply,
        and the frontend maps it to a 400 -- for an out-of-range lane
        or a missing tenant under strict mode."""
        if self.mode != "tenant":
            return None
        if tenant is None:
            if self.strict:
                raise ValueError(
                    f"{INVALID_PREFIX}: request names no __tenant__ "
                    "and zoo.serving.tenant.strict is on")
            tenant = self.default_lane
        lane = int(tenant)
        if not 0 <= lane < self.n_members:
            raise ValueError(
                f"{INVALID_PREFIX}: tenant lane {lane} out of range "
                f"[0, {self.n_members})")
        return lane

    # --------------------------------------------------------- predict --
    def _fns(self):
        """Build the mode's jitted apply once (lane is a traced
        argument, so ONE executable per input bucket covers every
        tenant)."""
        apply_fn = self._apply_fn
        if self.mode == "tenant":

            def fn(variables, lane, x):
                member = jax.tree_util.tree_map(
                    lambda a: a[lane], variables)
                return apply_fn(member, x)

            return jax.jit(fn)

        def fn(variables, x):
            preds = jax.vmap(lambda v: apply_fn(v, x))(variables)
            return {
                "mean": jax.tree_util.tree_map(
                    lambda a: a.mean(axis=0), preds),
                "var": jax.tree_util.tree_map(
                    lambda a: a.var(axis=0), preds),
            }

        return jax.jit(fn)

    def predict_async(self, x, lane: Optional[int] = None):
        """Dispatch without materializing: returns ``(outputs, n)``
        (the worker's ``predict_async`` contract). ``lane`` is the
        resolved tenant lane (tenant mode; None resolves through
        :meth:`resolve_lane`, honoring default/strict)."""
        def canon(a):
            a = np.asarray(a)
            if a.dtype == np.float64:
                return a.astype(np.float32)
            if a.dtype == np.int64:
                return a.astype(np.int32)
            return a

        x = jax.tree_util.tree_map(canon, x)
        leaves = jax.tree_util.tree_leaves(x)
        n = leaves[0].shape[0]
        bucket = _bucket(n)

        def pad(a):
            if a.shape[0] == bucket:
                return a
            return np.concatenate(
                [a, np.repeat(a[-1:], bucket - a.shape[0], axis=0)])

        padded = jax.tree_util.tree_map(pad, x)
        key = tuple((l.shape, str(l.dtype))
                    for l in jax.tree_util.tree_leaves(padded))
        fn = self._compiled.get(key)
        fresh = fn is None
        if fresh:
            fn = self._fns()
            self._compiled[key] = fn
        _M_SERVE.labels(mode=self.mode).inc()
        if self.mode == "tenant":
            if lane is None:
                lane = self.resolve_lane(None)
            args = (self.variables, jnp.asarray(lane, jnp.int32),
                    padded)
        else:
            args = (self.variables, padded)
        if fresh:
            import time

            t0 = time.perf_counter()
            out = fn(*args)
            record_compile("population.serve", key,
                           time.perf_counter() - t0,
                           subsystem="inference")
            return out, n
        return fn(*args), n

    def predict(self, x, lane: Optional[int] = None):
        out, n = self.predict_async(x, lane=lane)
        return jax.tree_util.tree_map(lambda a: np.asarray(a)[:n], out)

    # ---------------------------------------------------------- warmup --
    def warm_up(self, example_input,
                batch_sizes: Sequence[int] = (1, 8, 32)
                ) -> "PopulationInferenceModel":
        """Pre-compile the request-batch buckets (lane 0 stands in for
        every tenant: the lane is traced, so warming one lane warms
        them all)."""
        example = jax.tree_util.tree_map(
            np.asarray, example_input,
            is_leaf=lambda v: isinstance(v, list))
        done = set()
        with warming():
            for bs in batch_sizes:
                bucket = _bucket(bs)
                if bucket in done:
                    continue
                done.add(bucket)
                batch = jax.tree_util.tree_map(
                    lambda a: np.repeat(a[:1], bucket, axis=0), example)
                lane = 0 if self.mode == "tenant" else None
                self.predict(batch, lane=lane)
        return self
