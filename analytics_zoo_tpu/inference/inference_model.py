"""InferenceModel: the serving-facing prediction engine.

The analog of ``InferenceModel`` (ref: zoo/.../pipeline/inference/
InferenceModel.scala:28-608, pyzoo/zoo/pipeline/inference/
inference_model.py:24-250). Key design inversion for TPU: the reference
maintains a ``LinkedBlockingQueue`` of ``concurrentNum`` model copies
because BigDL modules are stateful; XLA executables are pure + thread-safe,
so ONE AOT-compiled executable per batch-shape bucket serves any number of
threads. Batch inputs are padded up to the nearest bucket (powers of two)
to bound recompilation.

Loaders (mirroring doLoad* -- ref: InferenceModel.scala:76-260):
- ``load_zoo``         a saved ZooModel directory
- ``load_flax``        a flax module (+ variables or checkpoint dir)
- ``load_torch``       torch state_dict imported into a flax module
- ``load_encrypted_*`` AES-encrypted variants (EncryptSupportive analog)
"""

from __future__ import annotations

import io
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import record_compile, warming
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

# inference engine wiring into the unified registry: live XLA compiles
# (should stay flat after warm-up -- a climbing counter means requests
# are paying compile stalls), dispatch volume, and how much of each
# device batch is bucket padding (wasted compute; high ratios mean the
# batcher's caps sit badly against the bucket ladder)
_REG = get_registry()
# compile / pad-ratio series carry (bucket, shard mode) labels so a
# sharded deployment's entries stay distinguishable from single-chip
# ones in /metrics instead of aggregating into one series (mode "off"
# = the unsharded engine; "tp"/"dp"/"tp_q8" = inference/sharded.py)
_M_COMPILES = _REG.counter(
    "zoo_inference_compile_total",
    "XLA shape-bucket compiles by (bucket, shard mode) -- flat after "
    "warm-up in a healthy deployment; climbing means requests pay "
    "compile stalls", labelnames=("bucket", "mode"))
_M_DISPATCH = _REG.counter(
    "zoo_inference_dispatch_total",
    "Prediction batches dispatched, by shard mode",
    labelnames=("mode",))
_M_PAD = _REG.histogram(
    "zoo_inference_batch_pad_ratio",
    "Fraction of each dispatched device batch that is bucket padding, "
    "by (bucket, shard mode)", labelnames=("bucket", "mode"),
    buckets=(0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0))


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def bucket_ladder(max_batch: int) -> List[int]:
    """Every power-of-two batch bucket up to (and including) the one
    covering ``max_batch`` -- the shape ladder ``predict`` pads onto.
    The serving launcher warms these; the adaptive batcher snaps its
    backlog-grown caps to them so batching policy never invents an XLA
    shape."""
    ladder = []
    b = 1
    while b <= _bucket(max_batch):
        ladder.append(b)
        b *= 2
    return ladder


class InferenceModel:
    def __init__(self, concurrent_num: int = 1, dtype=None):
        # concurrent_num kept for API parity (ref: InferenceModel.scala
        # concurrentNum); XLA needs no model copies.
        self.concurrent_num = concurrent_num
        if dtype is None:
            # advisory serving dtype (importers/quantize consult it);
            # the zoo.inference.default_dtype key, bfloat16 on TPU
            from analytics_zoo_tpu.common.config import get_config

            dtype = str(get_config().get("zoo.inference.default_dtype",
                                         "bfloat16"))
        self.dtype = dtype
        from analytics_zoo_tpu.common.context import (
            enable_compilation_cache)

        enable_compilation_cache()  # serving restarts skip recompiles
        self._apply_fn: Optional[Callable] = None
        self.variables: Optional[Dict] = None
        self._compiled: Dict[Any, Callable] = {}
        self._lock = threading.Lock()
        self._quantized = False
        self.example_input = None  # set by load_zoo for warm_up
        # mesh routing (inference/sharded.py): None = single-chip, the
        # pre-mesh engine byte-for-byte (including cache keys)
        self.shard_plan = None

    # ------------------------------------------------------------ loads --
    def load_zoo(self, path: str) -> "InferenceModel":
        """(ref: doLoadBigDL / zoo model load)."""
        from analytics_zoo_tpu.models.common import ZooModel

        model = ZooModel.load_model(path)
        est = model.estimator
        adapter = est.adapter
        self._apply_fn = (
            lambda variables, x: adapter.apply(variables, x,
                                               training=False)[0])
        self.variables = est.variables
        try:  # lets deployments warm_up without knowing the model class
            self.example_input = model._example_input()
        except Exception:
            self.example_input = None
        return self

    def load_flax(self, module, variables=None,
                  checkpoint_dir: Optional[str] = None,
                  example_input=None) -> "InferenceModel":
        from analytics_zoo_tpu.learn.estimator import FlaxModelAdapter

        adapter = FlaxModelAdapter(module)
        if variables is None:
            if checkpoint_dir is None:
                raise ValueError("pass variables or checkpoint_dir")
            from analytics_zoo_tpu.learn import checkpoint as ckpt

            variables, _, _ = ckpt.load_checkpoint(checkpoint_dir, None,
                                                   None)
        self._apply_fn = (
            lambda v, x: adapter.apply(v, x, training=False)[0])
        self.variables = variables
        return self

    def load_torch(self, module, state_dict, key_map=None,
                   wrap: str = "params") -> "InferenceModel":
        """torch state_dict -> flax module weights
        (ref: doLoadPyTorch, net/TorchModel.scala -- except weights are
        imported, not executed via an embedded interpreter)."""
        from analytics_zoo_tpu.inference.importers import (
            import_torch_state_dict)

        params = import_torch_state_dict(state_dict, key_map=key_map)
        return self.load_flax(module, variables={wrap: params})

    def load_graph(self, graph_fn) -> "InferenceModel":
        """Serve an imported executable graph
        (:class:`~analytics_zoo_tpu.inference.graph_executor.GraphFunction`)
        through the bucketed-jit predict path. The execution analog of
        the reference's TFNet/ONNX serving backends
        (ref: InferenceModel.scala doLoadTensorflow -> TFNet session;
        here the graph IS a jax function, so it shares predict/warm_up/
        quantize infrastructure with native models)."""
        # float weight constants ride as "variables" so quantize() can
        # compress them and jit treats them as runtime operands; static
        # operands (shapes/axes -- integer/scalar consts) stay baked
        # into the graph so trace-time ops see concrete values
        import copy

        weights = graph_fn.weight_constants()
        self.variables = {"graph_consts": weights}
        # private copy without the fp weights: quantize() can release
        # the full-precision copies, and the CALLER's GraphFunction
        # stays intact (it must keep working standalone)
        graph_fn = copy.copy(graph_fn)
        graph_fn.constants = {k: v for k, v in graph_fn.constants.items()
                              if k not in weights}
        single = len(graph_fn.input_names) == 1

        def apply_graph(variables, x):
            feed = (x if isinstance(x, dict)
                    else {graph_fn.input_names[0]: x} if single
                    else dict(zip(graph_fn.input_names, x)))
            return graph_fn.execute(feed,
                                    constants=variables["graph_consts"])

        self._apply_fn = apply_graph
        return self

    def load_tf_graph(self, path_or_bytes, inputs=None, outputs=None
                      ) -> "InferenceModel":
        """Frozen TF GraphDef -> executable serving model
        (ref: doLoadTensorflow frozen path, TFNet.scala:56-719)."""
        from analytics_zoo_tpu.inference.graph_executor import (
            load_tf_frozen_graph)

        return self.load_graph(load_tf_frozen_graph(
            path_or_bytes, inputs=inputs, outputs=outputs))

    def load_onnx(self, path_or_bytes) -> "InferenceModel":
        """ONNX model -> executable serving model
        (ref: onnx_loader.py:32-128)."""
        from analytics_zoo_tpu.inference.graph_executor import (
            load_onnx_model)

        return self.load_graph(load_onnx_model(path_or_bytes))

    def load_encrypted_zoo(self, path: str, secret: str,
                           ) -> "InferenceModel":
        """Directory of encrypted files produced by ``save_encrypted``
        (ref: doLoadEncrypted*, EncryptSupportive.scala)."""
        import os
        import tempfile

        from analytics_zoo_tpu.inference.encrypt import decrypt_bytes

        with tempfile.TemporaryDirectory() as tmp:
            for name in os.listdir(path):
                with open(os.path.join(path, name), "rb") as f:
                    blob = f.read()
                with open(os.path.join(tmp, name), "wb") as f:
                    f.write(decrypt_bytes(blob, secret))
            return self.load_zoo(tmp)

    @staticmethod
    def save_encrypted(model_dir: str, out_dir: str, secret: str) -> None:
        """Encrypt every file of a saved model directory."""
        import os

        from analytics_zoo_tpu.inference.encrypt import encrypt_bytes

        os.makedirs(out_dir, exist_ok=True)
        for name in os.listdir(model_dir):
            src = os.path.join(model_dir, name)
            if not os.path.isfile(src):
                continue
            with open(src, "rb") as f:
                blob = encrypt_bytes(f.read(), secret)
            with open(os.path.join(out_dir, name), "wb") as f:
                f.write(blob)

    # --------------------------------------------------------- quantize --
    def quantize(self, min_size: int = 1024) -> "InferenceModel":
        """Weight-only int8 (ref: int8/OpenVINO VNNI path). Weights are
        stored int8; the forward dequantizes (XLA fuses the rescale)."""
        from analytics_zoo_tpu.inference.quantize import (
            dequantize_params, quantize_params)

        if self.variables is None:
            raise RuntimeError("load a model before quantize()")
        if self.shard_plan is not None:
            raise RuntimeError("quantize() before shard(): weight-only "
                               "quantization rebuilds the variable "
                               "tree the plan committed to its mesh")
        q_tree, scales = quantize_params(self.variables, min_size)
        inner = self._apply_fn

        def apply_q(variables, x):
            return inner(dequantize_params(variables, scales), x)

        self._apply_fn = apply_q
        self.variables = q_tree
        self._compiled.clear()
        self._quantized = True
        return self

    # ------------------------------------------------------------ shard --
    def shard(self, plan="config") -> "InferenceModel":
        """Route prediction through a device mesh
        (:mod:`analytics_zoo_tpu.inference.sharded`). ``plan="config"``
        resolves ``zoo.serving.shard.*``; pass a :class:`ShardPlan` to
        pick the mesh explicitly, or None for a no-op. Attach AFTER
        ``quantize()`` (weight-only int8 replaces the variable tree) and
        before ``warm_up`` so the ladder compiles under the active
        mesh. Attaching commits the variables onto the mesh; the bucket
        cache keeps any pre-attach entries -- their keys cannot collide
        with the plan-signed ones."""
        if plan == "config":
            from analytics_zoo_tpu.inference.sharded import (
                resolve_shard_plan)

            plan = resolve_shard_plan(self.variables)
        if plan is None:
            return self
        if self.variables is None:
            raise RuntimeError("load a model before shard()")
        if self.shard_plan is not None:
            raise RuntimeError(
                "a shard plan is already attached; build a fresh "
                "InferenceModel to re-shard (variables are committed "
                "to the previous mesh)")
        self.variables = plan.place_variables(self.variables)
        self.shard_plan = plan
        return self

    def _bucket_for(self, n: int) -> int:
        """The device-batch bucket covering ``n``: the power-of-two
        ladder single-chip; under a batch-splitting shard plan the same
        ladder in units of the mesh size (every bucket divides evenly
        across the devices -- and re-bucketing a bucket is a fixed
        point, so warmed sizes stay warmed)."""
        plan = self.shard_plan
        m = plan.batch_multiple if plan is not None else 1
        if m <= 1:
            return _bucket(n)
        return m * _bucket(-(-n // m))

    # ---------------------------------------------------------- warm-up --
    def warm_up(self, example_input,
                batch_sizes: Sequence[int] = (1, 8, 32)
                ) -> "InferenceModel":
        """Pre-compile the shape buckets a serving deployment will hit
        (SURVEY.md section 7 step 7: AOT-compile per batch-shape), so the
        first real request never pays the XLA compile. ``example_input``
        is a single-sample (or any-size) batch pytree; each requested
        batch size compiles its power-of-two bucket."""
        if self._apply_fn is None:
            raise RuntimeError("no model loaded")
        # lists count as leaves so YAML-sourced examples ({input:
        # [[1,2,3]]}) become proper arrays, not 0-d scalar trees
        example = jax.tree_util.tree_map(
            np.asarray, example_input,
            is_leaf=lambda v: isinstance(v, list))
        done = set()
        # mark these compiles as intentional: warming the whole bucket
        # ladder mints N distinct shapes in seconds, which must not
        # read as a recompile storm. The warming() context is thread-
        # local and reaches EVERY compile boundary the warm trace
        # crosses (this bucket cache AND a graph-backed model's
        # GraphFunction signatures)
        with warming():
            for bs in batch_sizes:
                bucket = self._bucket_for(bs)
                if bucket in done:
                    continue
                done.add(bucket)
                batch = jax.tree_util.tree_map(
                    lambda a: np.repeat(a[:1], bucket, axis=0), example)
                self.predict(batch)
        return self

    # ---------------------------------------------------------- predict --
    def _shape_key(self, x) -> Any:
        return tuple(
            (getattr(l, "shape", None), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(x))

    def predict(self, x, batch_size: Optional[int] = None) -> Any:
        """Thread-safe batched prediction with shape-bucket AOT cache
        (ref: doPredict, InferenceModel.scala:28-62 -- minus the model
        queue)."""
        out, n = self.predict_async(x)
        return jax.tree_util.tree_map(
            lambda a: np.asarray(a)[:n], out)

    def predict_async(self, x) -> Any:
        """Dispatch prediction WITHOUT materializing results: returns
        (device_outputs, n). jax dispatch is asynchronous, so the
        caller can submit the next batch (overlapping its host->device
        transfer with this batch's compute) before fetching these
        outputs with ``np.asarray(...)[:n]``. The serving worker's
        pipelined mode is built on this."""
        if self._apply_fn is None:
            raise RuntimeError("no model loaded")
        # canonicalize 64-bit host inputs (JSON ints/floats) to the
        # 32-bit dtypes jax runs anyway -- otherwise the shape-bucket
        # key differs from warmed buckets and recompiles pointlessly
        def canon(a):
            a = np.asarray(a)
            if a.dtype == np.float64:
                return a.astype(np.float32)
            if a.dtype == np.int64:
                return a.astype(np.int32)
            return a

        x = jax.tree_util.tree_map(canon, x)
        leaves = jax.tree_util.tree_leaves(x)
        n = leaves[0].shape[0]
        plan = self.shard_plan
        bucket = self._bucket_for(n)

        def pad(a):
            if a.shape[0] == bucket:
                return a
            reps = np.concatenate(
                [a, np.repeat(a[-1:], bucket - a.shape[0], axis=0)])
            return reps

        padded = jax.tree_util.tree_map(pad, x)
        # sharding-aware cache key: the plain shape tuple single-chip
        # (EXACTLY the pre-mesh key, so warm persistent caches survive
        # the upgrade) and (shapes, plan signature) under a mesh --
        # single-chip and sharded entries, or two different meshes,
        # can never collide
        key = self._shape_key(padded)
        mode = "off"
        if plan is not None:
            key = (key, plan.signature)
            mode = plan.label
            padded = plan.place_batch(padded)
        with self._lock:
            fn = self._compiled.get(key)
            fresh = fn is None
            if fresh:
                fn = (plan.build_fn(self._apply_fn) if plan is not None
                      else jax.jit(self._apply_fn))
                self._compiled[key] = fn
                _M_COMPILES.labels(bucket=str(bucket), mode=mode).inc()
                logger.info("inference: compiling bucket %s", key)
        _M_DISPATCH.labels(mode=mode).inc()
        _M_PAD.labels(bucket=str(bucket),
                      mode=mode).observe((bucket - n) / bucket)
        if fresh:
            # first dispatch of a new bucket: jax traces + XLA-compiles
            # synchronously inside this call, so its wall time ~= the
            # compile stall requests behind it paid. The event feeds the
            # recompile-storm detector -- a serving deployment whose
            # traffic keeps minting new buckets (bad bucketing, ragged
            # inputs) warns loudly instead of just running slow.
            t0 = time.perf_counter()
            out = fn(self.variables, padded)
            record_compile("inference.predict", key,
                           time.perf_counter() - t0,
                           subsystem="inference")
            return out, n
        return fn(self.variables, padded), n
