"""Mesh-routed serving: the sharding layer behind ``InferenceModel``.

PRs 1-6 made single-chip serving fast, observable and crash-safe;
``parallel/`` ships exact tensor-parallel recipes and the MULTICHIP
dryrun proves out an 8-device mesh -- but every prediction still ran on
one chip. This module routes ``predict_async`` through a
``jax.sharding.Mesh`` per deployment config (the ROADMAP "sharded
multi-chip inference" item; mesh-native TPU serving per the Gemma-on-TPU
study, arXiv:2605.25645):

``zoo.serving.shard.mode``
    - ``off``   (default) -- single-chip, byte-identical to the pre-mesh
      engine, including the exact compile-cache keys (warm persistent
      XLA caches survive the upgrade);
    - ``tp``    -- tensor parallel: parameters sharded over the
      ``zoo.mesh.axis.model`` axis by a ``parallel.recipes`` spec
      (``zoo.serving.shard.recipe``), batch replicated; GSPMD inserts
      the exact collectives (megatron row/column layout). The big-model
      mode: 1/N parameter HBM per chip and N chips on every matmul.
    - ``dp``    -- data parallel: parameters replicated, batch sharded
      over the ``zoo.mesh.axis.data`` axis. The small-model mode: N
      independent replicas behind one dispatch.
    - ``auto``  -- picks ``tp`` when the parameter bytes exceed
      ``zoo.serving.shard.auto_hbm_fraction`` of one chip's HBM
      (``memory_stats()``, overridable via
      ``zoo.serving.shard.auto_hbm_bytes``), else ``dp``.

``zoo.serving.shard.quantized_collectives``
    Opt-in EQuARX-idiom wire compression (arXiv:2506.17615) for the
    ``tp`` mode: parameters stay resident as shards (same 1/N HBM at
    rest) and the engine executes a ``shard_map`` whose body re-assembles
    the tensor-parallel shards through an **int8 all-gather with
    per-shard rescale** (:func:`parallel.collectives.quantized_all_gather`
    -- ~1/4 the cross-chip bytes of f32) and computes each chip's slice
    of the batch locally. Approximate (documented tolerance: the int8
    round-trip bounds relative error at ~1/127 per shard); the exact
    GSPMD path stays the default.

The compile-cache consequence, handled in ``inference_model.py``: a
plan contributes a ``signature`` (mode, axis, recipe, device set) to
the bucket cache key, so single-chip and sharded entries -- or two
different meshes -- can never collide; with ``mode=off`` the key is
exactly the pre-mesh tuple.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

# per-mesh serving visibility (obs): how many chips the active plan
# spans, by mode -- the companion of the bucket/mode labels on the
# zoo_inference_* compile/dispatch series
_M_MESH = get_registry().gauge(
    "zoo_inference_mesh_devices_items",
    "Devices spanned by the active serving shard plan, by mode",
    labelnames=("mode",))
_MESH_LABELS = ("tp", "dp", "tp_q8")


def _set_mesh_gauge(active_label: Optional[str], n: int) -> None:
    """One active mesh at a time: setting a mode zeroes the others, so
    a process that resolved several plans (benches, re-launches, a
    mode=off restart) never scrapes as running multiple meshes."""
    for label in _MESH_LABELS:
        _M_MESH.labels(mode=label).set(
            n if label == active_label else 0)

_MODES = ("off", "tp", "dp", "auto")
_RECIPES = ("transformer_tp", "embedding_tp")
# conservative per-chip HBM guess when the backend exposes no
# memory_stats (CPU meshes, some remote runtimes): one v5e chip
_FALLBACK_HBM_BYTES = 16 << 30


def _shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-compat shard_map (kept as the module's historical name;
    the one implementation lives in ``parallel.mesh.shard_map`` and is
    shared with ``parallel/`` so the whole tree runs on both jax
    lines)."""
    from analytics_zoo_tpu.parallel.mesh import shard_map

    return shard_map(f, mesh, in_specs, out_specs)


def _spec_fn_for(recipe: str, axis: str) -> Callable:
    from analytics_zoo_tpu.parallel import recipes

    if recipe == "embedding_tp":
        return recipes.embedding_tp_spec(axis=axis)
    return recipes.transformer_tp_spec(axis=axis)


def _sharded_dim(spec: P, axis: str) -> Optional[int]:
    """Index of the dimension ``spec`` shards over ``axis`` (None when
    the spec never mentions it; tuple entries count)."""
    for i, entry in enumerate(spec):
        if entry == axis or (isinstance(entry, (tuple, list))
                             and axis in entry):
            return i
    return None


def _leaf_name(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


def _param_bytes(variables: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(variables):
        size = int(np.prod(getattr(leaf, "shape", ()) or (1,)))
        itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        total += size * itemsize
    return total


def _per_chip_bytes(device, cfg_get=None) -> int:
    if cfg_get is None:
        cfg_get = get_config().get
    override = int(cfg_get("zoo.serving.shard.auto_hbm_bytes", 0))
    if override:
        return override
    try:
        stats = device.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception as e:
        logger.debug("shard auto: no memory_stats on %s: %s", device, e)
    return _FALLBACK_HBM_BYTES


class ShardPlan:
    """A resolved serving sharding decision: the mesh, the per-leaf
    parameter specs, how batches place, and the cache-key signature.
    Built by :func:`resolve_shard_plan`; attached to an
    ``InferenceModel`` via ``model.shard(plan)``."""

    def __init__(self, mode: str, mesh: Mesh, axis: str,
                 recipe: Optional[str], quantized: bool,
                 spec_fn: Optional[Callable]):
        self.mode = mode                  # "tp" | "dp" (resolved)
        self.mesh = mesh
        self.axis = axis
        self.recipe = recipe              # None for dp
        self.quantized = quantized and mode == "tp"
        self.spec_fn = spec_fn            # None for dp (replicate)
        self.n_devices = int(np.prod(mesh.devices.shape))
        # batch constraint: modes that split the batch across the mesh
        # need device batches divisible by the axis size; exact tp
        # replicates the batch, so any bucket works
        self.batch_multiple = (self.n_devices
                               if mode == "dp" or self.quantized else 1)
        device_ids = tuple(int(d.id) for d in mesh.devices.flat)
        self.label = mode + ("_q8" if self.quantized else "")
        # the compile-cache key contribution: device set + mode/spec
        # signature, so single-chip and sharded entries (or two
        # different meshes/recipes) never collide
        self.signature: Tuple = ("shard", self.label, axis,
                                 recipe or "", device_ids)
        self._spec_tree = None  # per-leaf P tree, built at placement

    # ------------------------------------------------------ placement --
    def place_variables(self, variables: Any) -> Any:
        """Commit the parameter pytree onto the mesh (sharded per the
        recipe spec for tp, replicated for dp) and remember the spec
        tree the quantized engine's ``shard_map`` needs."""
        if self.spec_fn is None:
            self._spec_tree = jax.tree_util.tree_map(
                lambda _: P(), variables)
        else:
            self._spec_tree = jax.tree_util.tree_map_with_path(
                lambda p, leaf: self.spec_fn(p, leaf), variables)
        shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._spec_tree)
        # placement IS activation (InferenceModel.shard commits here,
        # exactly once per model): the mesh gauge flips to this plan
        # and zeroes whatever mode a previous plan advertised
        _set_mesh_gauge(self.label, self.n_devices)
        return jax.tree_util.tree_map(jax.device_put, variables,
                                      shardings)

    def batch_spec(self) -> P:
        """Input placement: batch-sharded over the mesh axis for the
        batch-splitting modes, replicated for exact tp."""
        return P(self.axis) if self.batch_multiple > 1 else P()

    def place_batch(self, padded: Any) -> Any:
        sharding = NamedSharding(self.mesh, self.batch_spec())
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), padded)

    # ---------------------------------------------------- compilation --
    def build_fn(self, apply_fn: Callable) -> Callable:
        """The callable the bucket cache compiles for this plan: plain
        jit for the exact modes (GSPMD reads the committed shardings),
        or the quantized-gather ``shard_map`` engine."""
        if not self.quantized:
            return jax.jit(apply_fn)
        if self._spec_tree is None:
            raise RuntimeError("place_variables must run before "
                               "build_fn on a quantized plan")
        from analytics_zoo_tpu.parallel.collectives import (
            quantized_all_gather)

        axis = self.axis
        spec_leaves = self._spec_tree

        def body(local_vars, x_local):
            # re-assemble each tensor-parallel shard through the int8
            # gather; replicated leaves (LayerNorms, biases of
            # row-parallel layers) pass through untouched
            def gather(leaf, spec):
                dim = _sharded_dim(spec, axis)
                if dim is None:
                    return leaf
                return quantized_all_gather(leaf, axis, axis=dim)

            full = jax.tree_util.tree_map(gather, local_vars,
                                          spec_leaves)
            return apply_fn(full, x_local)

        fn = _shard_map(body, self.mesh,
                        (self._spec_tree, self.batch_spec()),
                        self.batch_spec())
        return jax.jit(fn)

    # -------------------------------------------------------- surface --
    def describe(self) -> Dict[str, Any]:
        """The protocol-visible shard info (/debug/vars ``serving_shard``
        block, ``worker.metrics()['shard']``)."""
        return {
            "mode": self.mode,
            "quantized_collectives": self.quantized,
            "axis": self.axis,
            "recipe": self.recipe,
            "devices": self.n_devices,
            "platform": self.mesh.devices.flat[0].platform,
            "batch_multiple": self.batch_multiple,
        }


def _validate_tp(variables: Any, spec_fn: Callable, axis: str,
                 n: int) -> List[str]:
    """Names of leaves the recipe shards; raises when a sharded dim
    does not divide by the axis size (a clear error beats jax's)."""
    sharded: List[str] = []
    bad: List[str] = []
    flat = jax.tree_util.tree_flatten_with_path(variables)[0]
    for path, leaf in flat:
        spec = spec_fn(path, leaf)
        dim = _sharded_dim(spec, axis)
        if dim is None:
            continue
        name = _leaf_name(path)
        sharded.append(name)
        shape = getattr(leaf, "shape", ())
        if dim >= len(shape) or shape[dim] % n:
            bad.append(f"{name}{tuple(shape)} dim {dim}")
    if bad:
        raise ValueError(
            f"zoo.serving.shard.mode=tp cannot shard over {n} devices: "
            f"{', '.join(bad[:4])} not divisible by the axis size "
            "(pick a smaller zoo.serving.shard.devices or mode=dp)")
    return sharded


def resolve_shard_plan(variables: Any, devices=None,
                       overrides: Optional[Dict[str, Any]] = None
                       ) -> Optional[ShardPlan]:
    """Read ``zoo.serving.shard.*`` and build the deployment's plan
    (None = mode off / nothing to shard over). ``auto`` resolves by
    parameter bytes vs per-chip HBM; an ``auto`` tp whose recipe cannot
    shard this parameter tree falls back to dp instead of failing the
    launch. ``overrides`` (full ``zoo.serving.shard.*`` key names) win
    over the config layer for THIS resolution only -- the launcher's
    YAML ``shard:`` block rides here instead of mutating the
    process-global config, so a later launch in the same process
    cannot inherit a previous deployment's sharding."""
    cfg = get_config()
    over = overrides or {}

    def _cfg(key, default):
        return over[key] if key in over else cfg.get(key, default)

    mode = str(_cfg("zoo.serving.shard.mode", "off"))
    if mode not in _MODES:
        raise ValueError(f"zoo.serving.shard.mode must be one of "
                         f"{_MODES}, got {mode!r}")
    if mode == "off":
        return None
    devices = list(devices) if devices is not None else jax.devices()
    limit = int(_cfg("zoo.serving.shard.devices", 0))
    if limit:
        devices = devices[:limit]
    if len(devices) < 2:
        logger.warning("shard.mode=%s requested but only %d device(s) "
                       "available; serving single-chip", mode,
                       len(devices))
        return None
    quantized = bool(_cfg(
        "zoo.serving.shard.quantized_collectives", False))
    recipe = str(_cfg("zoo.serving.shard.recipe", "transformer_tp"))
    if recipe not in _RECIPES:
        raise ValueError(f"zoo.serving.shard.recipe must be one of "
                         f"{_RECIPES}, got {recipe!r}")
    auto = mode == "auto"
    if auto:
        pbytes = _param_bytes(variables)
        budget = (float(_cfg("zoo.serving.shard.auto_hbm_fraction",
                             0.6))
                  * _per_chip_bytes(devices[0], _cfg))
        mode = "tp" if pbytes > budget else "dp"
        logger.info("shard.mode=auto: %d param bytes vs %.0f per-chip "
                    "budget -> %s", pbytes, budget, mode)

    from analytics_zoo_tpu.parallel.mesh import config_axis, create_mesh

    if mode == "tp":
        axis = config_axis("model")
        spec_fn = _spec_fn_for(recipe, axis)
        try:
            sharded = _validate_tp(variables, spec_fn, axis,
                                   len(devices))
        except ValueError:
            if not auto:
                raise
            sharded = []
        if not sharded:
            if auto:
                logger.info("shard.mode=auto: recipe %r shards nothing "
                            "on this tree; falling back to dp", recipe)
                mode = "tp_fallback_dp"
            else:
                logger.warning(
                    "shard.mode=tp: recipe %r shards NO parameter of "
                    "this model (suffixes never matched); serving will "
                    "replicate the full tree on every chip", recipe)
        if mode == "tp":
            mesh = create_mesh({axis: len(devices)}, devices=devices)
            plan = ShardPlan("tp", mesh, axis, recipe, quantized,
                             spec_fn)
            return plan
    axis = config_axis("data")
    if quantized:
        # dp has no cross-chip reduction on the predict path -- nothing
        # for the quantized collective to compress
        logger.info("shard.quantized_collectives is a no-op under dp "
                    "(no cross-chip reduction on the predict path)")
    mesh = create_mesh({axis: len(devices)}, devices=devices)
    plan = ShardPlan("dp", mesh, axis, None, False, None)
    return plan


def maybe_shard_from_config(model, devices=None, overrides=None):
    """Launcher hook: resolve the deployment's plan (config layer +
    per-launch ``overrides``) and attach it to the model. A deployment
    that resolves to single-chip (mode off, degraded device count)
    zeroes the mesh gauge -- a relaunch must not keep advertising a
    previous deployment's mesh. Returns the plan (or None)."""
    plan = resolve_shard_plan(model.variables, devices=devices,
                              overrides=overrides)
    if plan is not None:
        model.shard(plan)
        from analytics_zoo_tpu.obs.events import emit as emit_event

        emit_event("shard_attached", "serving", **plan.describe())
        logger.info("serving sharded: %s", plan.describe())
    else:
        _set_mesh_gauge(None, 0)
    return plan
