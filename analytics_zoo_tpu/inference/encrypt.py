"""Encrypted model storage.

The analog of ``EncryptSupportive`` (ref: zoo/.../pipeline/inference/
EncryptSupportive.scala:26-77 -- AES/CBC/PKCS5Padding with a
PBKDF2-derived key): AES-256-CBC + PKCS7, PBKDF2-HMAC-SHA256 key
derivation, random IV + salt prepended to the ciphertext.
"""

from __future__ import annotations

import os

try:  # optional dependency: importing this module must never fail --
    # serving/inference deployments without encrypted models should not
    # need the cryptography wheel (errors surface at call time instead)
    from cryptography.hazmat.primitives import hashes, padding
    from cryptography.hazmat.primitives.ciphers import (
        Cipher, algorithms, modes)
    from cryptography.hazmat.primitives.kdf.pbkdf2 import PBKDF2HMAC
    _CRYPTO_ERR = None
except ImportError as _e:  # pragma: no cover - environment dependent
    _CRYPTO_ERR = _e

_ITERATIONS = 65536  # ref: EncryptSupportive.scala iteration count
_KEY_LEN = 32


def crypto_available() -> bool:
    return _CRYPTO_ERR is None


def _require_crypto() -> None:
    if _CRYPTO_ERR is not None:
        raise RuntimeError(
            "encrypted model support needs the 'cryptography' package "
            f"(import failed: {_CRYPTO_ERR})")


def _derive(secret: str, salt: bytes) -> bytes:
    kdf = PBKDF2HMAC(algorithm=hashes.SHA256(), length=_KEY_LEN,
                     salt=salt, iterations=_ITERATIONS)
    return kdf.derive(secret.encode("utf-8"))


def encrypt_bytes(data: bytes, secret: str) -> bytes:
    _require_crypto()
    salt = os.urandom(16)
    iv = os.urandom(16)
    key = _derive(secret, salt)
    padder = padding.PKCS7(128).padder()
    padded = padder.update(data) + padder.finalize()
    enc = Cipher(algorithms.AES(key), modes.CBC(iv)).encryptor()
    return salt + iv + enc.update(padded) + enc.finalize()


def decrypt_bytes(blob: bytes, secret: str) -> bytes:
    _require_crypto()
    salt, iv, ct = blob[:16], blob[16:32], blob[32:]
    key = _derive(secret, salt)
    dec = Cipher(algorithms.AES(key), modes.CBC(iv)).decryptor()
    padded = dec.update(ct) + dec.finalize()
    unpadder = padding.PKCS7(128).unpadder()
    return unpadder.update(padded) + unpadder.finalize()
