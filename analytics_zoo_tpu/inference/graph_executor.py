"""Graph-executing model import: run frozen TF GraphDefs and ONNX
models as jittable JAX functions.

The reference's headline interop is *executing* arbitrary customer
models: ``TFNet`` wraps any frozen TF graph as a layer over a JNI
session (ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/
net/TFNet.scala:56-719) and the ONNX loader constructs a model by
mapping graph nodes onto layers (ref: pyzoo/zoo/pipeline/api/onnx/
onnx_loader.py:32-128). The TPU-native equivalent is neither a session
bridge nor a layer translation: both formats lower to ONE small op-set
interpreter whose ops are jnp/lax calls, so an imported graph traces
into a single XLA program -- it jits, fuses, shards and AOT-compiles
exactly like a hand-written model (and runs on the MXU, which no JNI
session would).

Both loaders parse the protobuf wire format directly (no tensorflow /
onnx dependency), same stance as ``importers.py``.

API:
- ``load_tf_frozen_graph(path_or_bytes, inputs=None, outputs=None)``
- ``load_onnx_model(path_or_bytes)``
both return a :class:`GraphFunction` -- call it with arrays (or a dict
of input-name -> array); wrap in ``jax.jit`` or hand it to
``InferenceModel`` for the bucketed-jit serving path.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.inference.importers import (
    _iter_fields, _read_varint, _signed)
from analytics_zoo_tpu.obs.events import record_compile

__all__ = ["GraphFunction", "load_tf_frozen_graph", "load_onnx_model",
           "UnsupportedOpError"]


class UnsupportedOpError(ValueError):
    """Graph contains ops outside the interpreter's op set; carries the
    full sorted list so users see every gap at once."""

    def __init__(self, ops, kind: str):
        self.ops = sorted(set(ops))
        super().__init__(
            f"unsupported {kind} op(s): {', '.join(self.ops)} -- the "
            "graph executor covers the standard inference op set; "
            "extend _TF_OPS/_ONNX_OPS or import weights only")


class _Node:
    __slots__ = ("name", "op", "inputs", "attrs", "outputs")

    def __init__(self, name, op, inputs, attrs, outputs=()):
        self.name = name
        self.op = op
        self.inputs = inputs      # list of (producer_name, output_index)
        self.attrs = attrs
        self.outputs = outputs    # ONNX: explicit output tensor names

    def __repr__(self):  # pragma: no cover - debug aid
        return f"_Node({self.name!r}, {self.op})"


class GraphFunction:
    """An imported graph as a callable ``f(*arrays | {name: array})``.

    Executes nodes in topological order through the jnp op registry;
    fully traceable, so ``jax.jit(fn)`` compiles the whole graph into
    one XLA program. ``constants`` maps initializer names to ndarrays
    (exposed so tests/users can inspect or re-shard imported weights).
    """

    def __init__(self, nodes: List[_Node], constants: Dict[str, Any],
                 input_names: List[str], output_names: List[Tuple[str,
                                                                  int]],
                 registry: Dict[str, Callable], kind: str):
        self.nodes = nodes
        self.constants = constants
        self.input_names = list(input_names)
        self._outputs = list(output_names)
        self.output_names = [n for n, _ in self._outputs]
        self._registry = registry
        self.kind = kind
        missing = [n.op for n in nodes if n.op not in registry]
        if missing:
            raise UnsupportedOpError(missing, kind)
        # compile-boundary bookkeeping: the first execute() per feed
        # signature is a trace (eager: the first time XLA sees those
        # op shapes; under jit: literally the trace the compile
        # consumes) -- recorded as a compile event so graph-serving
        # deployments get the same recompile-storm coverage as native
        # models
        self._seen_sigs: set = set()
        self._sig_lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        if len(args) == 1 and isinstance(args[0], dict) and not kwargs:
            feed = dict(args[0])
        elif args:
            if len(args) != len(self.input_names):
                raise ValueError(
                    f"expected {len(self.input_names)} inputs "
                    f"({self.input_names}), got {len(args)}")
            feed = dict(zip(self.input_names, args))
        else:
            feed = kwargs
        return self.execute(feed)

    def weight_constants(self) -> Dict[str, Any]:
        """The floating-point non-scalar constants -- the graph's
        weights. These are safe to pass back into :meth:`execute` as
        traced values (e.g. dequantized under jit); integer/scalar
        constants are static operands (shapes, axes, permutations) and
        must stay concrete, so they are not included."""
        return {n: c for n, c in self.constants.items()
                if getattr(np.asarray(c), "ndim", 0) >= 1
                and np.issubdtype(np.asarray(c).dtype, np.floating)}

    def execute(self, feed: Dict[str, Any],
                constants: Optional[Dict[str, Any]] = None):
        """Run with an explicit feed dict; ``constants`` overrides
        same-named stored constants (how InferenceModel threads
        possibly-quantized weights through as traced values). Static
        operands (axes/shapes/permutations, always integer or scalar
        constants) keep their concrete stored values regardless."""
        import jax.numpy as jnp

        for name in self.input_names:
            if name not in feed:
                raise ValueError(f"missing input {name!r}")
        consts = (self.constants if constants is None
                  else {**self.constants, **constants})
        env: Dict[str, Any] = dict(consts)
        env.update({k: jnp.asarray(v) for k, v in feed.items()})
        sig = tuple(sorted(
            (k, tuple(getattr(v, "shape", ()) or ()),
             str(getattr(v, "dtype", ""))) for k, v in feed.items()))
        with self._sig_lock:
            fresh = sig not in self._seen_sigs
            if fresh:
                self._seen_sigs.add(sig)
        t0 = time.perf_counter() if fresh else 0.0
        for node in self.nodes:
            ins = [None if dep is None else _resolve(env, *dep)
                   for dep in node.inputs]
            out = self._registry[node.op](node, env, *ins)
            if node.outputs:
                outs = out if isinstance(out, tuple) else (out,)
                for oname, val in zip(node.outputs, outs):
                    if oname:
                        env[oname] = val
            else:
                env[node.name] = out
        res = tuple(_resolve(env, n, i) for n, i in self._outputs)
        if fresh:
            record_compile(
                f"graph.{self.kind}",
                tuple((s, dt) for _, s, dt in sig),
                time.perf_counter() - t0, subsystem="inference")
        return res[0] if len(res) == 1 else res

    @property
    def ops_used(self) -> List[str]:
        return sorted({n.op for n in self.nodes})


def _resolve(env, name, idx):
    val = env[name]
    if isinstance(val, tuple):
        return val[idx]
    if idx:
        raise ValueError(f"node {name!r} has one output, asked for "
                         f"output {idx}")
    return val


# ===================================================== TF GraphDef ====
# Wire schema (public tensorflow/core/framework protos):
# GraphDef.node=1; NodeDef: name=1, op=2, input=3, attr=5 (map entry
# key=1/value=2); AttrValue: list=1, s=2, i=3, f=4, b=5, type=6,
# shape=7, tensor=8; TensorProto: dtype=1, tensor_shape=2,
# tensor_content=4, half_val=13, float_val=5, double_val=6, int_val=7,
# string_val=8, int64_val=10, bool_val=11;
# TensorShapeProto: dim=2 (size=1), unknown_rank=3.

_TF_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
              5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
              14: None, 19: np.float16, 22: np.uint32, 23: np.uint64}
# DT_BFLOAT16 (14) resolved lazily via ml_dtypes


def _tf_dtype(enum: int):
    if enum == 14:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if enum not in _TF_DTYPES or _TF_DTYPES[enum] is None:
        raise ValueError(f"unsupported TF dtype enum {enum}")
    return np.dtype(_TF_DTYPES[enum])


def _parse_tf_shape(buf: bytes) -> Optional[List[int]]:
    dims: List[int] = []
    for field, _, val in _iter_fields(buf):
        if field == 2:  # dim
            size = 0
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    size = _signed(v2)
            dims.append(size)
        elif field == 3:  # unknown_rank
            return None
    return dims


def _parse_tf_tensor(buf: bytes) -> np.ndarray:
    dtype_enum = 1
    shape: List[int] = []
    content = None
    vals: List[Any] = []
    strings: List[bytes] = []
    for field, wire, val in _iter_fields(buf):
        if field == 1:
            dtype_enum = val
        elif field == 2:
            shape = _parse_tf_shape(val) or []
        elif field == 4:
            content = val
        elif field == 5:  # float_val
            if wire == 5:
                vals.append(struct.unpack("<f", val)[0])
            else:
                vals.extend(np.frombuffer(val, "<f4").tolist())
        elif field == 6:  # double_val
            if wire == 1:
                vals.append(struct.unpack("<d", val)[0])
            else:
                vals.extend(np.frombuffer(val, "<f8").tolist())
        elif field in (7, 10, 11, 13):  # int/int64/bool/half packed ints
            if wire == 0:
                vals.append(_signed(val))
            else:
                p = 0
                while p < len(val):
                    d, p = _read_varint(val, p)
                    vals.append(_signed(d))
        elif field == 8:
            strings.append(val)
    dt = _tf_dtype(dtype_enum)
    n = int(np.prod(shape)) if shape else 1
    if strings:
        raise ValueError("string tensors are not executable")
    if content is not None:
        arr = np.frombuffer(content, dtype=dt.newbyteorder("<"))
    elif dtype_enum == 13 and vals:  # half stored as ints
        arr = np.asarray(vals, np.uint16).view(np.float16)
    else:
        arr = np.asarray(vals, dtype=dt) if vals else np.zeros(0, dt)
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr.ravel()[0], dt)  # proto scalar fill
    return arr.astype(dt, copy=False).reshape(shape)


def _parse_attr_value(buf: bytes) -> Any:
    for field, wire, val in _iter_fields(buf):
        if field == 2:
            return val.decode("utf-8", "replace")
        if field == 3:
            return _signed(val)
        if field == 4:
            return struct.unpack("<f", val)[0]
        if field == 5:
            return bool(val)
        if field == 6:
            return ("dtype", val)
        if field == 7:
            return ("shape", _parse_tf_shape(val))
        if field == 8:
            return _parse_tf_tensor(val)
        if field == 1:  # list
            out: List[Any] = []
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 2:
                    out.append(v2.decode("utf-8", "replace"))
                elif f2 == 3:  # ints: varint or packed
                    if w2 == 0:
                        out.append(_signed(v2))
                    else:
                        p = 0
                        while p < len(v2):
                            d, p = _read_varint(v2, p)
                            out.append(_signed(d))
                elif f2 == 4:
                    if w2 == 5:
                        out.append(struct.unpack("<f", v2)[0])
                    else:
                        out.extend(np.frombuffer(v2, "<f4").tolist())
                elif f2 == 5:
                    if w2 == 0:
                        out.append(bool(v2))
                    else:
                        out.extend(bool(b) for b in v2)
                elif f2 == 6:
                    if w2 == 0:
                        out.append(("dtype", v2))
                    else:
                        p = 0
                        while p < len(v2):
                            d, p = _read_varint(v2, p)
                            out.append(("dtype", d))
            return out
    return None


def _parse_tf_node(buf: bytes) -> Tuple[str, str, List[str], Dict]:
    name = op = ""
    inputs: List[str] = []
    attrs: Dict[str, Any] = {}
    for field, _, val in _iter_fields(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:
            op = val.decode("utf-8")
        elif field == 3:
            inputs.append(val.decode("utf-8"))
        elif field == 5:  # attr map entry
            key, aval = "", None
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    key = v2.decode("utf-8")
                elif f2 == 2:
                    aval = _parse_attr_value(v2)
            attrs[key] = aval
    return name, op, inputs, attrs


def _split_tf_input(ref: str) -> Tuple[str, int]:
    if ":" in ref:
        base, idx = ref.rsplit(":", 1)
        return base, int(idx)
    return ref, 0


def load_tf_frozen_graph(path_or_bytes,
                         inputs: Optional[Sequence[str]] = None,
                         outputs: Optional[Sequence[str]] = None
                         ) -> GraphFunction:
    """Frozen TF1 GraphDef -> executable :class:`GraphFunction`
    (the execution analog of TFNet.scala:56-719's JNI session; here
    the graph lowers to jnp ops and compiles via XLA).

    ``inputs`` default to the graph's Placeholder nodes; ``outputs``
    default to graph sinks (nodes nobody consumes). Names accept the
    ``name`` or ``name:idx`` forms.
    """
    data = _read_bytes(path_or_bytes)
    raw_nodes = []
    for field, _, val in _iter_fields(data):
        if field == 1:
            raw_nodes.append(_parse_tf_node(val))
    if not raw_nodes:
        raise ValueError("not a GraphDef (no node fields)")

    constants: Dict[str, np.ndarray] = {}
    nodes: List[_Node] = []
    placeholders: List[str] = []
    for name, op, ins, attrs in raw_nodes:
        if op == "Const":
            constants[name] = attrs.get("value")
            if constants[name] is None:
                raise ValueError(f"Const node {name!r} has no value")
            continue
        if op in ("Placeholder", "PlaceholderV2"):
            placeholders.append(name)
            continue
        if op == "NoOp":
            continue
        deps = [_split_tf_input(r) for r in ins
                if not r.startswith("^")]
        nodes.append(_Node(name, op, deps, attrs))

    in_names = list(inputs) if inputs else placeholders
    in_names = [_split_tf_input(n)[0] for n in in_names]
    if outputs:
        out_refs = [_split_tf_input(n) for n in outputs]
    else:
        consumed = {src for n in nodes for src, _ in n.inputs}
        out_refs = [(n.name, 0) for n in nodes if n.name not in consumed]
        if not out_refs:
            raise ValueError("graph has no sink nodes; pass outputs=")
    nodes = _topo_order(nodes, set(constants) | set(in_names))
    return GraphFunction(nodes, constants, in_names, out_refs,
                         _TF_OPS, "TF")


def _read_bytes(path_or_bytes) -> bytes:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return bytes(path_or_bytes)
    from analytics_zoo_tpu.utils.fileio import read_bytes

    return read_bytes(path_or_bytes)


def _topo_order(nodes: List[_Node], ready: set) -> List[_Node]:
    """Dependency-order nodes (graph protos are usually already
    topological, but ONNX only guarantees it per spec -- cheap to be
    safe for both). Iterative DFS: frozen transformer graphs routinely
    have sequential chains past Python's recursion limit."""
    by_out: Dict[str, _Node] = {}
    for n in nodes:
        for o in (n.outputs or (n.name,)):
            if o:
                by_out[o] = n
    done = set(ready)
    order: List[_Node] = []
    seen: set = set()
    on_stack: set = set()
    for root in nodes:
        if id(root) in seen:
            continue
        stack: List[Tuple[_Node, bool]] = [(root, False)]
        while stack:
            n, expanded = stack.pop()
            if expanded:
                on_stack.discard(id(n))
                if id(n) in seen:
                    continue
                seen.add(id(n))
                for o in (n.outputs or (n.name,)):
                    done.add(o)
                order.append(n)
                continue
            if id(n) in seen:
                continue
            if id(n) in on_stack:
                raise ValueError(f"cycle through node {n.name!r}")
            on_stack.add(id(n))
            stack.append((n, True))
            for dep in n.inputs:
                if dep is None:
                    continue
                src = dep[0]
                if src not in done and src in by_out:
                    child = by_out[src]
                    if id(child) not in seen:
                        stack.append((child, False))
    return order


# ------------------------------------------------------ TF op registry

def _np_const(x) -> np.ndarray:
    """Concrete value of a trace-time-static operand (shapes, axes,
    permutations); jit keeps these static because they come from
    Const nodes."""
    return np.asarray(x)


def _tf_conv_padding(attrs, ins_rank=4):
    pad = attrs.get("padding", "SAME")
    if isinstance(pad, bytes):
        pad = pad.decode()
    if pad == "EXPLICIT":
        ep = attrs.get("explicit_paddings") or []
        pairs = [(int(ep[2 * i]), int(ep[2 * i + 1]))
                 for i in range(ins_rank)]
        # spatial dims sit at 1:3 for NHWC but 2:4 for NCHW
        df = attrs.get("data_format", "NHWC") or "NHWC"
        if isinstance(df, bytes):
            df = df.decode()
        return pairs[2:4] if df == "NCHW" else pairs[1:3]
    return pad


def _tf_conv(node, env, x, w):
    import jax.lax as lax

    a = node.attrs
    df = a.get("data_format", "NHWC") or "NHWC"
    strides = a.get("strides") or [1, 1, 1, 1]
    dil = a.get("dilations") or [1, 1, 1, 1]
    if df == "NHWC":
        s, d = strides[1:3], dil[1:3]
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        s, d = strides[2:4], dil[2:4]
        dn = ("NCHW", "HWIO", "NCHW")
    groups = 1
    if node.op == "DepthwiseConv2dNative":
        # TF depthwise kernel [H, W, C, M] -> HWIO [H, W, 1, C*M] with
        # feature_group_count=C
        h, wd, c, m = w.shape
        w = w.reshape(h, wd, 1, c * m)
        groups = c
    return lax.conv_general_dilated(
        x, w, window_strides=s, padding=_tf_conv_padding(node.attrs),
        rhs_dilation=d, dimension_numbers=dn,
        feature_group_count=groups)


def _tf_pool(node, env, x, kind):
    import jax.lax as lax
    import jax.numpy as jnp

    a = node.attrs
    df = a.get("data_format", "NHWC") or "NHWC"
    ks = a.get("ksize") or [1, 1, 1, 1]
    st = a.get("strides") or [1, 1, 1, 1]
    pad = a.get("padding", "VALID")
    if isinstance(pad, bytes):
        pad = pad.decode()
    if df != "NHWC":
        ks, st = [ks[0], ks[2], ks[3], ks[1]], [st[0], st[2], st[3],
                                                st[1]]
        x = jnp.transpose(x, (0, 2, 3, 1))
    if kind == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, ks, st, pad)
    else:
        out = lax.reduce_window(x, 0.0, lax.add, ks, st, pad)
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, ks, st, pad)
        out = out / cnt
    if df != "NHWC":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


def _tf_bias_add(node, env, x, b):
    import jax.numpy as jnp

    if (node.attrs.get("data_format") or "NHWC") == "NCHW" and x.ndim > 2:
        return x + b.reshape((1, -1) + (1,) * (x.ndim - 2))
    return x + b


def _tf_fused_bn(node, env, x, scale, offset, mean, var):
    import jax.numpy as jnp

    eps = _attr(node.attrs, "epsilon", 1e-3)
    df = node.attrs.get("data_format", "NHWC") or "NHWC"
    shape = ((1, -1) + (1,) * (x.ndim - 2)) if df == "NCHW" \
        else ((1,) * (x.ndim - 1) + (-1,))
    inv = (scale.reshape(shape)
           / jnp.sqrt(var.reshape(shape) + eps))
    out = (x - mean.reshape(shape)) * inv + offset.reshape(shape)
    # batch_mean/batch_variance outputs mirror inputs at inference
    return (out, mean, var, mean, var, jnp.zeros_like(mean))


def _tf_reduce(fn_name):
    def run(node, env, x, axes):
        import jax.numpy as jnp

        keep = bool(node.attrs.get("keep_dims")
                    or node.attrs.get("keepdims"))
        ax = tuple(int(a) for a in np.atleast_1d(_np_const(axes)))
        return getattr(jnp, fn_name)(x, axis=ax or None, keepdims=keep)

    return run


def _tf_strided_slice(node, env, x, begin, end, strides):
    a = node.attrs
    begin = _np_const(begin).tolist()
    end = _np_const(end).tolist()
    strides = _np_const(strides).tolist()
    bm = int(a.get("begin_mask") or 0)
    em = int(a.get("end_mask") or 0)
    sm = int(a.get("shrink_axis_mask") or 0)
    nm = int(a.get("new_axis_mask") or 0)
    el = int(a.get("ellipsis_mask") or 0)
    if el or nm:
        raise ValueError("StridedSlice ellipsis/new_axis masks are not "
                         "supported")
    idx = []
    for i in range(len(begin)):
        if sm & (1 << i):
            idx.append(int(begin[i]))
            continue
        b = None if bm & (1 << i) else int(begin[i])
        e = None if em & (1 << i) else int(end[i])
        idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


def _tf_concat(node, env, *args):
    import jax.numpy as jnp

    if node.op == "ConcatV2":
        axis = int(_np_const(args[-1]))
        return jnp.concatenate(args[:-1], axis=axis)
    axis = int(_np_const(args[0]))
    return jnp.concatenate(args[1:], axis=axis)


def _unary(fn):
    return lambda node, env, x: fn(x)


def _binary(fn):
    return lambda node, env, a, b: fn(a, b)


def _make_tf_ops() -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    ops: Dict[str, Callable] = {
        "Identity": _unary(lambda x: x),
        "StopGradient": _unary(jax.lax.stop_gradient),
        "Relu": _unary(jax.nn.relu),
        "Relu6": _unary(lambda x: jnp.clip(x, 0, 6)),
        "LeakyRelu": lambda n, e, x: jax.nn.leaky_relu(
            x, _attr(n.attrs, "alpha", 0.2)),
        "Elu": _unary(jax.nn.elu),
        "Selu": _unary(jax.nn.selu),
        "Softplus": _unary(jax.nn.softplus),
        "Sigmoid": _unary(jax.nn.sigmoid),
        "Tanh": _unary(jnp.tanh),
        "Softmax": _unary(lambda x: jax.nn.softmax(x, axis=-1)),
        "LogSoftmax": _unary(lambda x: jax.nn.log_softmax(x, axis=-1)),
        "Erf": _unary(jax.lax.erf),
        "Sqrt": _unary(jnp.sqrt),
        "Rsqrt": _unary(jax.lax.rsqrt),
        "Square": _unary(jnp.square),
        "Exp": _unary(jnp.exp),
        "Log": _unary(jnp.log),
        "Neg": _unary(jnp.negative),
        "Abs": _unary(jnp.abs),
        "Floor": _unary(jnp.floor),
        "Add": _binary(jnp.add), "AddV2": _binary(jnp.add),
        "Sub": _binary(jnp.subtract), "Mul": _binary(jnp.multiply),
        "RealDiv": _binary(jnp.divide), "Div": _binary(jnp.divide),
        "Maximum": _binary(jnp.maximum),
        "Minimum": _binary(jnp.minimum),
        "Pow": _binary(jnp.power),
        "SquaredDifference": _binary(lambda a, b: jnp.square(a - b)),
        "FloorDiv": _binary(jnp.floor_divide),
        "Greater": _binary(jnp.greater),
        "GreaterEqual": _binary(jnp.greater_equal),
        "Less": _binary(jnp.less),
        "Equal": _binary(jnp.equal),
        "LogicalAnd": _binary(jnp.logical_and),
        "Select": lambda n, e, c, a, b: jnp.where(c, a, b),
        "SelectV2": lambda n, e, c, a, b: jnp.where(c, a, b),
        "AddN": lambda n, e, *xs: sum(xs[1:], xs[0]),
        "BiasAdd": _tf_bias_add,
        "MatMul": lambda n, e, a, b: jnp.matmul(
            a.T if n.attrs.get("transpose_a") else a,
            b.T if n.attrs.get("transpose_b") else b),
        "BatchMatMul": lambda n, e, a, b: jnp.matmul(
            jnp.swapaxes(a, -1, -2) if n.attrs.get("adj_x") else a,
            jnp.swapaxes(b, -1, -2) if n.attrs.get("adj_y") else b),
        "Conv2D": _tf_conv,
        "DepthwiseConv2dNative": _tf_conv,
        "MaxPool": lambda n, e, x: _tf_pool(n, e, x, "max"),
        "AvgPool": lambda n, e, x: _tf_pool(n, e, x, "avg"),
        "FusedBatchNorm": _tf_fused_bn,
        "FusedBatchNormV2": _tf_fused_bn,
        "FusedBatchNormV3": _tf_fused_bn,
        "Reshape": lambda n, e, x, s: jnp.reshape(
            x, [int(v) for v in _np_const(s)]),
        "Squeeze": lambda n, e, x: jnp.squeeze(
            x, axis=tuple(n.attrs.get("squeeze_dims") or []) or None),
        "ExpandDims": lambda n, e, x, ax: jnp.expand_dims(
            x, int(_np_const(ax))),
        "Transpose": lambda n, e, x, p: jnp.transpose(
            x, [int(v) for v in _np_const(p)]),
        "Concat": _tf_concat, "ConcatV2": _tf_concat,
        "Pack": lambda n, e, *xs: jnp.stack(
            xs, axis=int(n.attrs.get("axis") or 0)),
        "Unpack": lambda n, e, x: tuple(
            jnp.moveaxis(x, int(n.attrs.get("axis") or 0), 0)),
        "Pad": lambda n, e, x, p: jnp.pad(
            x, [(int(a), int(b)) for a, b in _np_const(p)]),
        "PadV2": lambda n, e, x, p, c: jnp.pad(
            x, [(int(a), int(b)) for a, b in _np_const(p)],
            constant_values=float(_np_const(c))),
        "Mean": _tf_reduce("mean"), "Sum": _tf_reduce("sum"),
        "Max": _tf_reduce("max"), "Min": _tf_reduce("min"),
        "Prod": _tf_reduce("prod"),
        "ArgMax": lambda n, e, x, ax: jnp.argmax(x, int(_np_const(ax))),
        "ArgMin": lambda n, e, x, ax: jnp.argmin(x, int(_np_const(ax))),
        "StridedSlice": _tf_strided_slice,
        "Slice": lambda n, e, x, b, s: jax.lax.dynamic_slice(
            x, [int(v) for v in _np_const(b)],
            [int(v) if v >= 0 else x.shape[i] - int(_np_const(b)[i])
             for i, v in enumerate(_np_const(s))]),
        "GatherV2": lambda n, e, p, i, ax: jnp.take(
            p, i.astype(jnp.int32), axis=int(_np_const(ax))),
        "Gather": lambda n, e, p, i: jnp.take(
            p, i.astype(jnp.int32), axis=0),
        "Cast": lambda n, e, x: x.astype(
            _tf_dtype(n.attrs["DstT"][1])
            if isinstance(n.attrs.get("DstT"), tuple) else x.dtype),
        "Shape": lambda n, e, x: jnp.asarray(x.shape, jnp.int32),
        "Tile": lambda n, e, x, m: jnp.tile(
            x, [int(v) for v in _np_const(m)]),
        "Fill": lambda n, e, s, v: jnp.full(
            [int(d) for d in _np_const(s)], v),
        "Range": lambda n, e, a, b, d: jnp.arange(
            int(_np_const(a)), int(_np_const(b)), int(_np_const(d))),
        "Rank": lambda n, e, x: jnp.asarray(x.ndim, jnp.int32),
        "ZerosLike": _unary(jnp.zeros_like),
        "OnesLike": _unary(jnp.ones_like),
    }
    return ops


# ========================================================== ONNX ====
# Wire schema (public onnx.proto): ModelProto.graph=7;
# GraphProto: node=1, initializer=5, input=11, output=12;
# NodeProto: input=1, output=2, name=3, op_type=4, attribute=5;
# AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8;
# ValueInfoProto.name=1.


def _parse_onnx_attr(buf: bytes) -> Tuple[str, Any]:
    from analytics_zoo_tpu.inference.importers import _parse_tensor_proto

    name = ""
    val: Any = None
    ints: List[int] = []
    floats: List[float] = []
    for field, wire, v in _iter_fields(buf):
        if field == 1:
            name = v.decode("utf-8")
        elif field == 2:
            val = struct.unpack("<f", v)[0]
        elif field == 3:
            val = _signed(v)
        elif field == 4:
            val = v.decode("utf-8", "replace")
        elif field == 5:
            val = _parse_tensor_proto(v)[1]
        elif field == 7:
            if wire == 5:
                floats.append(struct.unpack("<f", v)[0])
            else:
                floats.extend(np.frombuffer(v, "<f4").tolist())
        elif field == 8:
            if wire == 0:
                ints.append(_signed(v))
            else:
                p = 0
                while p < len(v):
                    d, p = _read_varint(v, p)
                    ints.append(_signed(d))
    if ints:
        val = ints
    elif floats:
        val = floats
    return name, val


def _parse_onnx_node(buf: bytes) -> _Node:
    inputs: List[str] = []
    outputs: List[str] = []
    name = op = ""
    attrs: Dict[str, Any] = {}
    for field, _, val in _iter_fields(buf):
        if field == 1:
            inputs.append(val.decode("utf-8"))
        elif field == 2:
            outputs.append(val.decode("utf-8"))
        elif field == 3:
            name = val.decode("utf-8")
        elif field == 4:
            op = val.decode("utf-8")
        elif field == 5:
            k, v = _parse_onnx_attr(val)
            attrs[k] = v
    # empty-string inputs are omitted OPTIONAL inputs (e.g. Clip with
    # no min); keep them as None deps so later positional args stay in
    # their correct slots
    deps = [((i, 0) if i else None) for i in inputs]
    while deps and deps[-1] is None:
        deps.pop()  # trailing omissions carry no positional info
    node = _Node(name or (outputs[0] if outputs else op), op, deps,
                 attrs, outputs)
    return node


def _value_info_name(buf: bytes) -> str:
    for field, _, val in _iter_fields(buf):
        if field == 1:
            return val.decode("utf-8")
    return ""


def load_onnx_model(path_or_bytes) -> GraphFunction:
    """ONNX ModelProto -> executable :class:`GraphFunction`
    (the execution analog of onnx_loader.py:32-128, which maps nodes
    onto zoo layers; here nodes lower to jnp/lax and compile as one
    XLA program). Inference semantics: Dropout is identity,
    BatchNormalization uses stored statistics.
    """
    from analytics_zoo_tpu.inference.importers import _parse_tensor_proto

    data = _read_bytes(path_or_bytes)
    graph = None
    for field, _, val in _iter_fields(data):
        if field == 7:
            graph = val
            break
    if graph is None:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    nodes: List[_Node] = []
    constants: Dict[str, np.ndarray] = {}
    g_inputs: List[str] = []
    g_outputs: List[str] = []
    for field, _, val in _iter_fields(graph):
        if field == 1:
            nodes.append(_parse_onnx_node(val))
        elif field == 5:
            name, arr = _parse_tensor_proto(val)
            constants[name] = arr
        elif field == 11:
            g_inputs.append(_value_info_name(val))
        elif field == 12:
            g_outputs.append(_value_info_name(val))
    in_names = [n for n in g_inputs if n not in constants]
    out_refs = [(n, 0) for n in g_outputs]
    # Constant nodes become initializers
    rest: List[_Node] = []
    for n in nodes:
        if n.op == "Constant":
            v = n.attrs.get("value")
            if v is None:
                v = np.asarray(n.attrs.get("value_float",
                                           n.attrs.get("value_int", 0)))
            constants[n.outputs[0]] = np.asarray(v)
        else:
            rest.append(n)
    rest = _topo_order(rest, set(constants) | set(in_names))
    return GraphFunction(rest, constants, in_names, out_refs,
                         _ONNX_OPS, "ONNX")


# ---------------------------------------------------- ONNX op registry

def _onnx_pads(attrs, spatial: int, in_sizes=None, kernel=None,
               strides=None, dil=None):
    pads = attrs.get("pads")
    if not pads:
        auto = attrs.get("auto_pad", "NOTSET")
        if auto == "SAME_UPPER":
            return "SAME"
        if auto == "SAME_LOWER":
            # lax's "SAME" puts the odd pad at the END; SAME_LOWER puts
            # it at the START -- compute explicit per-dim pads
            out = []
            for i in range(spatial):
                st = int((strides or [1] * spatial)[i])
                dl = int((dil or [1] * spatial)[i])
                eff_k = (int(kernel[i]) - 1) * dl + 1
                size = int(in_sizes[i])
                total = max((-(-size // st) - 1) * st + eff_k - size, 0)
                out.append((total - total // 2, total // 2))
            return out
        return [(0, 0)] * spatial
    return [(int(pads[i]), int(pads[i + spatial]))
            for i in range(spatial)]


def _attr(attrs, name, default):
    """Numeric attribute with a default -- explicit 0.0 is preserved
    (`or`-style defaults wrongly coerce falsy zeros)."""
    return float(attrs[name]) if name in attrs else float(default)


def _onnx_conv(node, env, x, w, *maybe_b):
    import jax.lax as lax

    a = node.attrs
    spatial = x.ndim - 2
    strides = a.get("strides") or [1] * spatial
    dil = a.get("dilations") or [1] * spatial
    groups = int(a.get("group") or 1)
    # channel-first specs per rank: 1-D uses the H label (any single
    # spatial letter works for lax), 3-D appends D
    specs = {1: ("NCH", "OIH", "NCH"),
             2: ("NCHW", "OIHW", "NCHW"),
             3: ("NCHWD", "OIHWD", "NCHWD")}
    if spatial not in specs:
        raise ValueError(f"Conv with {spatial} spatial dims unsupported")
    dn = lax.conv_dimension_numbers(x.shape, w.shape, specs[spatial])
    out = lax.conv_general_dilated(
        x, w, window_strides=[int(s) for s in strides],
        padding=_onnx_pads(a, spatial, in_sizes=x.shape[2:],
                           kernel=w.shape[2:], strides=strides,
                           dil=dil),
        rhs_dilation=[int(d) for d in dil], dimension_numbers=dn,
        feature_group_count=groups)
    if maybe_b:
        out = out + maybe_b[0].reshape((1, -1) + (1,) * spatial)
    return out


def _onnx_gemm(node, env, a, b, *maybe_c):
    import jax.numpy as jnp

    at = node.attrs
    alpha = _attr(at, "alpha", 1.0)
    beta = _attr(at, "beta", 1.0)
    if at.get("transA"):
        a = a.T
    if at.get("transB"):
        b = b.T
    out = alpha * (a @ b)
    if maybe_c:
        out = out + beta * maybe_c[0]
    return out


def _onnx_pool(node, env, x, kind):
    import jax.lax as lax
    import jax.numpy as jnp

    a = node.attrs
    spatial = x.ndim - 2
    ks = [1, 1] + [int(k) for k in a["kernel_shape"]]
    st = [1, 1] + [int(s) for s in (a.get("strides")
                                    or [1] * spatial)]
    pads = _onnx_pads(a, spatial, in_sizes=x.shape[2:],
                      kernel=a["kernel_shape"],
                      strides=a.get("strides"))
    if isinstance(pads, str):
        pad = pads
    else:
        pad = [(0, 0), (0, 0)] + pads
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, ks, st, pad)
    out = lax.reduce_window(x, 0.0, lax.add, ks, st, pad)
    if a.get("count_include_pad"):
        denom = float(np.prod(ks))
        return out / denom
    cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, ks, st, pad)
    return out / cnt


def _onnx_bn(node, env, x, scale, bias, mean, var):
    import jax.numpy as jnp

    eps = _attr(node.attrs, "epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return ((x - mean.reshape(shape))
            * (scale.reshape(shape)
               / jnp.sqrt(var.reshape(shape) + eps))
            + bias.reshape(shape))


def _onnx_reshape(node, env, x, shape):
    import jax.numpy as jnp

    target = [int(v) for v in _np_const(shape)]
    # ONNX: 0 means "copy input dim" (unless allowzero)
    if not node.attrs.get("allowzero"):
        target = [x.shape[i] if v == 0 else v
                  for i, v in enumerate(target)]
    return jnp.reshape(x, target)


def _onnx_axes(node, env, extra) -> Optional[Tuple[int, ...]]:
    axes = node.attrs.get("axes")
    if axes is None and extra and extra[0] is not None:
        axes = [int(v) for v in _np_const(extra[0])]
    return tuple(int(a) for a in axes) if axes is not None else None


def _onnx_clip(node, env, x, *bounds):
    import jax.numpy as jnp

    lo = node.attrs.get("min")
    hi = node.attrs.get("max")
    # omitted optional inputs arrive as None and leave the attr/default
    if len(bounds) > 0 and bounds[0] is not None:
        lo = bounds[0]
    if len(bounds) > 1 and bounds[1] is not None:
        hi = bounds[1]
    return jnp.clip(x, lo, hi)


def _make_onnx_ops() -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    ops: Dict[str, Callable] = {
        "Identity": _unary(lambda x: x),
        "Relu": _unary(jax.nn.relu),
        "LeakyRelu": lambda n, e, x: jax.nn.leaky_relu(
            x, _attr(n.attrs, "alpha", 0.01)),
        "Elu": _unary(jax.nn.elu),
        "Selu": _unary(jax.nn.selu),
        "Sigmoid": _unary(jax.nn.sigmoid),
        "HardSigmoid": lambda n, e, x: jnp.clip(
            _attr(n.attrs, "alpha", 0.2) * x
            + _attr(n.attrs, "beta", 0.5), 0, 1),
        "Tanh": _unary(jnp.tanh),
        "Softmax": lambda n, e, x: jax.nn.softmax(
            x, axis=int(n.attrs.get("axis", -1))),
        "LogSoftmax": lambda n, e, x: jax.nn.log_softmax(
            x, axis=int(n.attrs.get("axis", -1))),
        "Softplus": _unary(jax.nn.softplus),
        "Erf": _unary(jax.lax.erf),
        "Gelu": lambda n, e, x: jax.nn.gelu(
            x, approximate=(n.attrs.get("approximate") == "tanh")),
        "Sqrt": _unary(jnp.sqrt),
        "Reciprocal": _unary(jnp.reciprocal),
        "Exp": _unary(jnp.exp), "Log": _unary(jnp.log),
        "Neg": _unary(jnp.negative), "Abs": _unary(jnp.abs),
        "Floor": _unary(jnp.floor), "Ceil": _unary(jnp.ceil),
        "Add": _binary(jnp.add), "Sub": _binary(jnp.subtract),
        "Mul": _binary(jnp.multiply), "Div": _binary(jnp.divide),
        "Pow": _binary(jnp.power), "Max": lambda n, e, *xs:
            __import__("functools").reduce(jnp.maximum, xs),
        "Min": lambda n, e, *xs:
            __import__("functools").reduce(jnp.minimum, xs),
        "MatMul": _binary(jnp.matmul),
        "Gemm": _onnx_gemm,
        "Conv": _onnx_conv,
        "MaxPool": lambda n, e, x: _onnx_pool(n, e, x, "max"),
        "AveragePool": lambda n, e, x: _onnx_pool(n, e, x, "avg"),
        "GlobalAveragePool": lambda n, e, x: jnp.mean(
            x, axis=tuple(range(2, x.ndim)), keepdims=True),
        "GlobalMaxPool": lambda n, e, x: jnp.max(
            x, axis=tuple(range(2, x.ndim)), keepdims=True),
        "BatchNormalization": _onnx_bn,
        "Reshape": _onnx_reshape,
        "Flatten": lambda n, e, x: jnp.reshape(
            x, (int(np.prod(x.shape[:int(n.attrs.get("axis", 1))]))
                if int(n.attrs.get("axis", 1)) else 1, -1)),
        "Transpose": lambda n, e, x: jnp.transpose(
            x, n.attrs.get("perm")),
        "Concat": lambda n, e, *xs: jnp.concatenate(
            xs, axis=int(n.attrs.get("axis", 0))),
        "Unsqueeze": lambda n, e, x, *ax: jnp.reshape(
            x, _unsqueeze_shape(x.shape, _onnx_axes(n, e, ax))),
        "Squeeze": lambda n, e, x, *ax: jnp.squeeze(
            x, axis=_onnx_axes(n, e, ax)),
        "Clip": _onnx_clip,
        "Dropout": lambda n, e, x, *_: x,  # inference: identity
                                           # (ratio/mode inputs ignored)
        "Cast": lambda n, e, x: x.astype(
            np.dtype(_ONNX_CAST.get(int(n.attrs.get("to", 1)),
                                    np.float32))),
        "Shape": lambda n, e, x: jnp.asarray(x.shape, jnp.int64),
        "Gather": lambda n, e, p, i: jnp.take(
            p, i.astype(jnp.int32),
            axis=int(n.attrs.get("axis", 0))),
        "Slice": _onnx_slice,
        "ReduceMean": _onnx_reduce("mean"),
        "ReduceSum": _onnx_reduce("sum"),
        "ReduceMax": _onnx_reduce("max"),
        "ReduceMin": _onnx_reduce("min"),
        "ArgMax": lambda n, e, x: _onnx_argmax(n, x, jnp.argmax),
        "ArgMin": lambda n, e, x: _onnx_argmax(n, x, jnp.argmin),
        "Pad": _onnx_pad,
        "Expand": lambda n, e, x, s: jnp.broadcast_to(
            x, np.broadcast_shapes(x.shape,
                                   tuple(int(v) for v in _np_const(s)))),
        "Tile": lambda n, e, x, r: jnp.tile(
            x, [int(v) for v in _np_const(r)]),
        "ConstantOfShape": lambda n, e, s: jnp.full(
            [int(v) for v in _np_const(s)],
            float(n.attrs["value"].ravel()[0])
            if n.attrs.get("value") is not None else 0.0),
        "Where": lambda n, e, c, a, b: jnp.where(c, a, b),
        "Equal": _binary(jnp.equal),
        "Greater": _binary(jnp.greater),
        "Less": _binary(jnp.less),
        "Range": lambda n, e, a, b, d: jnp.arange(
            _np_const(a).item(), _np_const(b).item(),
            _np_const(d).item()),
    }
    return ops


_ONNX_CAST = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
              7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def _unsqueeze_shape(shape, axes):
    out = list(shape)
    rank = len(shape) + len(axes)
    for a in sorted(a % rank for a in axes):
        out.insert(a, 1)
    return out


def _onnx_reduce(fn_name):
    def run(node, env, x, *extra):
        import jax.numpy as jnp

        axes = _onnx_axes(node, env, extra)
        keep = bool(node.attrs.get("keepdims", 1))
        return getattr(jnp, fn_name)(x, axis=axes, keepdims=keep)

    return run


def _onnx_argmax(node, x, fn):
    axis = int(node.attrs.get("axis", 0))
    keep = bool(node.attrs.get("keepdims", 1))
    out = fn(x, axis=axis)
    if keep:
        import jax.numpy as jnp

        out = jnp.expand_dims(out, axis)
    return out


def _onnx_pad(node, env, x, *extra):
    import jax.numpy as jnp

    mode = node.attrs.get("mode", "constant") or "constant"
    if extra:  # opset >= 11: pads (and optional value) as inputs
        pads = [int(v) for v in _np_const(extra[0])]
        cval = float(_np_const(extra[1])) if len(extra) > 1 else 0.0
    else:
        pads = [int(v) for v in node.attrs.get("pads", [])]
        cval = float(node.attrs.get("value", 0.0) or 0.0)
    half = len(pads) // 2
    width = [(pads[i], pads[i + half]) for i in range(half)]
    if mode == "constant":
        return jnp.pad(x, width, constant_values=cval)
    return jnp.pad(x, width,
                   mode={"reflect": "reflect", "edge": "edge"}[mode])


def _onnx_slice(node, env, x, *extra):
    a = node.attrs
    if extra:  # opset >= 10: starts/ends[/axes/steps] as inputs
        starts = [int(v) for v in _np_const(extra[0])]
        ends = [int(v) for v in _np_const(extra[1])]
        axes = ([int(v) for v in _np_const(extra[2])]
                if len(extra) > 2 and extra[2] is not None
                else list(range(len(starts))))
        steps = ([int(v) for v in _np_const(extra[3])]
                 if len(extra) > 3 and extra[3] is not None
                 else [1] * len(starts))
    else:
        starts = [int(v) for v in a.get("starts", [])]
        ends = [int(v) for v in a.get("ends", [])]
        axes = [int(v) for v in (a.get("axes")
                                 or range(len(starts)))]
        steps = [1] * len(starts)
    idx: List[Any] = [slice(None)] * x.ndim
    big = np.iinfo(np.int64).max
    for s, e, ax, st in zip(starts, ends, axes, steps):
        e = None if e >= big or e <= -big else e
        idx[ax] = slice(s, e, st)
    return x[tuple(idx)]


_TF_OPS = _make_tf_ops()
_ONNX_OPS = _make_onnx_ops()
