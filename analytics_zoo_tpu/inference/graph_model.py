"""Train imported graphs: fine-tune a frozen TF GraphDef / ONNX model
through the Estimator.

The reference's north-star interop path is not just *running* customer
graphs but *training* them: ``TFTrainingHelper`` exposes a TF graph's
variables to the BigDL allreduce engine (ref: zoo/src/main/scala/com/
intel/analytics/zoo/tfpark/TFTrainingHelper.scala:33-310) and
``TFOptimizer.from_loss/from_keras`` drives distributed fine-tuning of
an arbitrary imported graph (ref: pyzoo/zoo/tfpark/tf_optimizer.py:
346-747), shuttling gradients across the JVM/TF boundary every step.

The TPU-native equivalent needs no bridge at all: the imported graph
already executes as a pure jnp program (``GraphFunction``), so its
weight constants ARE differentiable inputs -- ``jax.grad`` flows
through the interpreter like any hand-written model. :class:`GraphModel`
adapts a ``GraphFunction`` to the Estimator's (init, apply) contract,
promoting the graph's floating-point weight constants to trainable
parameters. The whole SPMD machinery (dp batch sharding, psum-inserted
allreduce, param_spec_fn tensor sharding, checkpoints, retry) applies
unchanged.

BatchNorm caveat: a frozen graph carries batch-norm in INFERENCE form
(moving mean/variance baked in as constants; ``FusedBatchNorm*`` /
``BatchNormalization`` nodes normalize with stored statistics). Those
statistics are NOT gradient-trained in the source frameworks either, so
by default they are frozen (left as concrete constants) while the
affine scale/offset remain trainable -- the standard "fine-tune with
frozen BN stats" recipe. There is no update of the moving statistics
during fine-tuning; for small-LR fine-tuning this matches the common
``layer.trainable=False``-on-BN Keras idiom.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Union

import numpy as np

from analytics_zoo_tpu.inference.graph_executor import GraphFunction

__all__ = ["GraphModel"]

# ops whose trailing inputs are running statistics, not weights:
# (op name) -> input positions holding mean / variance
_BN_STAT_POSITIONS = {
    "FusedBatchNorm": (3, 4),
    "FusedBatchNormV2": (3, 4),
    "FusedBatchNormV3": (3, 4),
    "BatchNormalization": (3, 4),  # ONNX: X, scale, B, mean, var
}


class GraphModel:
    """Estimator adapter over a :class:`GraphFunction`: the imported
    graph's weight constants become the trainable ``params`` tree.

    Usage::

        fn = load_tf_frozen_graph("model.pb")
        est = Estimator(GraphModel(fn), loss="sparse_categorical_...")
        est.fit(data, batch_size=32)          # fine-tunes the graph

    Args:
      fn: an imported :class:`GraphFunction` (TF or ONNX).
      trainable: restrict which weight constants train. A callable
        ``name -> bool``, or an iterable of names. Untrainable weights
        stay at their imported values (still part of the forward).
      freeze_batchnorm_stats: keep batch-norm running mean/variance
        constants out of ``params`` (default True; see module note).
      output: for multi-output graphs, the output to train on -- an
        output name or positional index. Single-output graphs ignore it.
    """

    def __init__(self, fn: GraphFunction,
                 trainable: Union[Callable[[str], bool],
                                  Iterable[str], None] = None,
                 freeze_batchnorm_stats: bool = True,
                 output: Union[str, int, None] = None):
        self.fn = fn
        self._out_idx = self._resolve_output(fn, output)
        frozen = (self._batchnorm_stat_names(fn)
                  if freeze_batchnorm_stats else set())
        weights = {n: w for n, w in fn.weight_constants().items()
                   if n not in frozen}
        if trainable is not None:
            if callable(trainable):
                keep = {n for n in weights if trainable(n)}
            else:
                keep = set(trainable)
                unknown = keep - set(fn.weight_constants())
                if unknown:
                    raise ValueError(
                        f"trainable names not found among the graph's "
                        f"weight constants: {sorted(unknown)}")
                frozen_named = keep & frozen
                if frozen_named:
                    raise ValueError(
                        f"{sorted(frozen_named)} are batch-norm running "
                        "statistics, frozen by default; pass "
                        "freeze_batchnorm_stats=False to train them")
            weights = {n: w for n, w in weights.items() if n in keep}
        if not weights:
            raise ValueError(
                "imported graph has no trainable weight constants "
                "(all floating-point constants are frozen or the graph "
                "carries no weights)")
        self._init_weights = {n: np.asarray(w) for n, w in weights.items()}

    @staticmethod
    def _resolve_output(fn: GraphFunction, output) -> Optional[int]:
        if len(fn.output_names) <= 1:
            return None
        if output is None:
            return 0
        if isinstance(output, int):
            if not -len(fn.output_names) <= output < len(fn.output_names):
                raise ValueError(
                    f"output index {output} out of range for graph "
                    f"outputs {fn.output_names}")
            return output
        if output in fn.output_names:
            return fn.output_names.index(output)
        raise ValueError(f"output {output!r} not among graph outputs "
                         f"{fn.output_names}")

    @staticmethod
    def _batchnorm_stat_names(fn: GraphFunction) -> set:
        """Constant names holding batch-norm running statistics, frozen
        during fine-tuning. Covers the fused node forms (FusedBatchNorm*,
        ONNX BatchNormalization: stats at input slots 3/4) and the
        decomposed inference form modern freezing emits
        (``y = x*g*rsqrt(var+eps) + (beta - mean*g*rsqrt(var+eps))``):
        variance is the vector constant inside ``Rsqrt(Add(var, eps))``,
        mean the constant multiplied by that scale whose product feeds a
        ``Sub`` (the x-branch product feeds the final Add instead)."""
        stats = set()
        consts = fn.constants
        produced: Dict[str, Any] = {}
        consumers: Dict[str, list] = {}
        for node in fn.nodes:
            for out in (node.outputs or (node.name,)):
                if out:
                    produced[out] = node
            for dep in node.inputs:
                if dep:
                    consumers.setdefault(dep[0], []).append(node)

        def _out(node):
            return node.outputs[0] if node.outputs else node.name

        def _const_source(name):
            """Resolve through Identity chains to the underlying
            constant name (frozen graphs wrap every variable constant in
            a ReadVariableOp Identity)."""
            seen = set()
            while name not in consts:
                node = produced.get(name)
                if (node is None or node.op != "Identity"
                        or not node.inputs or not node.inputs[0]
                        or name in seen):
                    return None
                seen.add(name)
                name = node.inputs[0][0]
            return name

        def _is_vec(name):
            name = _const_source(name)
            return (name is not None
                    and np.asarray(consts[name]).ndim >= 1
                    and np.issubdtype(np.asarray(consts[name]).dtype,
                                      np.floating))

        # fused node forms -- stats arrive via '/read' Identity
        # wrappers in classic frozen graphs, so resolve the chain
        for node in fn.nodes:
            positions = _BN_STAT_POSITIONS.get(node.op)
            if not positions:
                continue
            for pos in positions:
                if pos < len(node.inputs) and node.inputs[pos]:
                    name = _const_source(node.inputs[pos][0])
                    if name is not None:
                        stats.add(name)

        for node in fn.nodes:
            if node.op != "Rsqrt" or not node.inputs or not node.inputs[0]:
                continue
            add = produced.get(node.inputs[0][0])
            if add is None or add.op not in ("Add", "AddV2"):
                continue
            ins = [d[0] for d in add.inputs if d]
            vecs = [n for n in ins if _is_vec(n)]
            scalars = [n for n in ins
                       if _const_source(n) is not None
                       and np.asarray(consts[_const_source(n)]).ndim == 0]
            if len(vecs) != 1 or len(scalars) != 1:
                continue
            stats.add(_const_source(vecs[0]))  # the variance
            # rsqrt -> Mul (by gamma) = scale; Mul(mean, scale) -> Sub
            for mul in consumers.get(_out(node), []):
                if mul.op != "Mul":
                    continue
                for mul2 in consumers.get(_out(mul), []):
                    if mul2.op != "Mul":
                        continue
                    if not any(c.op == "Sub"
                               for c in consumers.get(_out(mul2), [])):
                        continue
                    for dep in mul2.inputs:
                        if dep and dep[0] != _out(mul) and _is_vec(dep[0]):
                            stats.add(_const_source(dep[0]))  # the mean
        return stats

    @property
    def trainable_names(self):
        return sorted(self._init_weights)

    # -------------------------------------------- Estimator contract --
    def init(self, rng, x) -> Dict[str, Any]:
        """Imported weights ARE the initialization; rng/x unused (kept
        for the adapter signature)."""
        del rng, x
        return {"params": dict(self._init_weights)}

    def apply(self, variables, x, training: bool, rng=None):
        del training, rng  # imported graphs run in inference form
        feed = self._feed(x)
        out = self.fn.execute(feed, constants=variables["params"])
        if self._out_idx is not None and isinstance(out, tuple):
            out = out[self._out_idx]
        return out, {k: v for k, v in variables.items() if k != "params"}

    def _feed(self, x) -> Dict[str, Any]:
        names = self.fn.input_names
        if isinstance(x, dict):
            return dict(x)
        parts = x if isinstance(x, tuple) else (x,)
        if len(parts) != len(names):
            raise ValueError(
                f"graph expects {len(names)} inputs {names}, "
                f"got {len(parts)}")
        return dict(zip(names, parts))
