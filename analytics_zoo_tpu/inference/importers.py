"""Foreign-framework weight importers.

The analog of the reference's interop loaders (TFNet frozen graphs,
TorchNet/TorchModel, ONNX -- ref: zoo/.../pipeline/api/net/,
pyzoo/zoo/pipeline/api/onnx). The TPU stack is single-framework, so
interop is *weight import*, not execution bridging (SURVEY.md section
2.4: "keep a torch->JAX weight importer").
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np


def import_torch_state_dict(state_dict, key_map: Optional[Dict[str, str]]
                            = None,
                            transpose_linear: bool = True) -> Dict:
    """torch ``state_dict`` (or path to a ``torch.save`` file) -> nested
    flax-style params dict.

    - dots become nesting: ``enc.fc.weight`` -> params[enc][fc][...]
    - ``weight``/``bias`` become flax's ``kernel``/``bias``; 2-D linear
      weights are transposed ([out, in] -> [in, out]);
    - 4-D conv weights go OIHW -> HWIO (channels-last);
    - ``key_map`` renames torch prefixes to flax module paths first.
    """
    if isinstance(state_dict, str):
        import torch

        state_dict = torch.load(state_dict, map_location="cpu",
                                weights_only=True)
    out: Dict = {}
    for key, value in state_dict.items():
        arr = np.asarray(value.detach().cpu().numpy()
                         if hasattr(value, "detach") else value)
        if key_map:
            for src, dst in key_map.items():
                if key.startswith(src):
                    key = dst + key[len(src):]
                    break
        parts = key.split(".")
        leaf = parts[-1]
        if leaf == "weight":
            if arr.ndim == 2 and transpose_linear:
                arr = arr.T
            elif arr.ndim == 4:
                arr = arr.transpose(2, 3, 1, 0)  # OIHW -> HWIO
            leaf = "kernel" if arr.ndim >= 2 else "scale"
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[leaf] = arr
    return out
