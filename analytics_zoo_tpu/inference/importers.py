"""Foreign-framework weight importers.

The analog of the reference's interop loaders (TFNet frozen graphs /
SavedModels via JNI sessions, TorchNet/TorchModel via Jep, ONNX loader --
ref: zoo/.../pipeline/api/net/TFNet.scala:56-719,
pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-128). The TPU stack is
single-framework, so interop is *weight import*, not execution bridging
(SURVEY.md section 2.4): each importer returns a nested flax-style
params dict to load into the JAX re-implementation of the model.

- ``import_torch_state_dict`` -- torch state_dict / .pt file
- ``import_tf_saved_model`` -- TF2 SavedModel variable bundle
- ``import_tf_frozen_graph`` -- TF1 frozen GraphDef constants
- ``import_onnx`` -- ONNX initializer tensors (dependency-free
  protobuf wire parser, same approach as utils/summary.py's writer)
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


def import_torch_state_dict(state_dict, key_map: Optional[Dict[str, str]]
                            = None,
                            transpose_linear: bool = True) -> Dict:
    """torch ``state_dict`` (or path to a ``torch.save`` file) -> nested
    flax-style params dict.

    - dots become nesting: ``enc.fc.weight`` -> params[enc][fc][...]
    - ``weight``/``bias`` become flax's ``kernel``/``bias``; 2-D linear
      weights are transposed ([out, in] -> [in, out]);
    - 4-D conv weights go OIHW -> HWIO (channels-last);
    - ``key_map`` renames torch prefixes to flax module paths first.
    """
    if isinstance(state_dict, str):
        import torch

        state_dict = torch.load(state_dict, map_location="cpu",
                                weights_only=True)
    out: Dict = {}
    for key, value in state_dict.items():
        arr = np.asarray(value.detach().cpu().numpy()
                         if hasattr(value, "detach") else value)
        parts = _apply_key_map(key, key_map).split(".")
        leaf, arr = _remap_torch_weight(parts[-1], arr, transpose_linear)
        _nest(out, parts[:-1], leaf, arr)
    return out


def import_torch_bert(state_dict) -> Dict:
    """HuggingFace-layout torch BERT encoder -> ``BERTModule`` params.

    Structural transforms beyond key renames (which is why the generic
    ``import_torch_state_dict`` cannot do this): the separate
    query/key/value linears stack into the fused [H, 3, H] qkv kernel,
    attention.output/intermediate/output map onto proj/ffn_in/ffn_out,
    and the embedding tables land on token/position/segment_embed.
    Accepts a ``BertModel.state_dict()`` (or ``bert.``-prefixed keys
    from a task model). End-to-end golden: logits parity vs torch in
    ``tests/test_bert_golden.py`` (the KerasRunner pattern,
    ref: zoo/src/test/.../KerasRunner.scala:40-120).
    """
    sd = {}
    for k, v in state_dict.items():
        arr = np.asarray(v.detach().cpu().numpy()
                         if hasattr(v, "detach") else v)
        sd[k[5:] if k.startswith("bert.") else k] = arr

    def lin(prefix):
        return {"kernel": sd[prefix + ".weight"].T,
                "bias": sd[prefix + ".bias"]}

    def ln(prefix):
        return {"scale": sd[prefix + ".weight"],
                "bias": sd[prefix + ".bias"]}

    params: Dict = {
        "token_embed": {
            "embedding": sd["embeddings.word_embeddings.weight"]},
        "position_embed": sd["embeddings.position_embeddings.weight"],
        "segment_embed": {
            "embedding": sd["embeddings.token_type_embeddings.weight"]},
        "embed_ln": ln("embeddings.LayerNorm"),
    }
    n_layers = 1 + max(
        int(k.split(".")[2]) for k in sd if k.startswith("encoder.layer."))
    for i in range(n_layers):
        p = f"encoder.layer.{i}"
        qkv_kernel = np.stack(
            [sd[f"{p}.attention.self.{n}.weight"].T
             for n in ("query", "key", "value")], axis=1)  # [H, 3, H]
        qkv_bias = np.stack(
            [sd[f"{p}.attention.self.{n}.bias"]
             for n in ("query", "key", "value")], axis=0)  # [3, H]
        params[f"encoder_{i}"] = {
            "attention": {
                "qkv": {"kernel": qkv_kernel, "bias": qkv_bias},
                "proj": lin(f"{p}.attention.output.dense"),
            },
            "ln_attn": ln(f"{p}.attention.output.LayerNorm"),
            "ffn_in": lin(f"{p}.intermediate.dense"),
            "ffn_out": lin(f"{p}.output.dense"),
            "ln_ffn": ln(f"{p}.output.LayerNorm"),
        }
    if "pooler.dense.weight" in sd:
        params["pooler"] = lin("pooler.dense")
    return params


_TF_RENAMES = {"gamma": "scale", "beta": "bias", "moving_mean": "mean",
               "moving_variance": "var"}


def _nest(out: Dict, parts, leaf_name: str, arr) -> None:
    node = out
    for p in parts:
        node = node.setdefault(p, {})
    node[leaf_name] = arr


def _apply_key_map(key: str, key_map: Optional[Dict[str, str]]) -> str:
    if key_map:
        for src, dst in key_map.items():
            if key.startswith(src):
                return dst + key[len(src):]
    return key


def _remap_torch_weight(leaf: str, arr: np.ndarray,
                        transpose_linear: bool) -> Tuple[str, np.ndarray]:
    """torch/onnx ``weight`` -> flax ``kernel``/``scale`` with layout
    fixes: 2-D [out, in] -> [in, out], 4-D OIHW -> HWIO."""
    if leaf != "weight":
        return leaf, arr
    if arr.ndim == 2 and transpose_linear:
        arr = arr.T
    elif arr.ndim == 4:
        arr = arr.transpose(2, 3, 1, 0)
    return ("kernel" if arr.ndim >= 2 else "scale"), arr


def import_tf_saved_model(path: str,
                          key_map: Optional[Dict[str, str]] = None
                          ) -> Dict:
    """TF2 SavedModel -> nested flax-style params dict.

    Restores the SavedModel object graph (``tf.saved_model.load``) and
    reads its variables by their real names (``model/fc1/kernel``) --
    the variable *bundle* alone anonymizes Keras-3 exports to
    ``variables/N``. Mirrors the weight-import stance (the reference
    instead spins up a JNI session, TFNet.scala:56-719). TF stores
    dense kernels [in, out] and conv kernels HWIO -- flax's layouts --
    so no transposes are needed (unlike torch import). BatchNorm names
    map gamma/beta/moving_* -> scale/bias/mean/var.
    """
    import tensorflow as tf  # CPU-only, host-side read

    loaded = tf.saved_model.load(path)
    variables = getattr(loaded, "variables", None) or []
    if not variables:
        raise ValueError(
            f"SavedModel at {path!r} exposes no variables to import "
            "(signature-only or non-Keras trackable export)")
    out: Dict = {}
    seen = set()
    for v in variables:
        name = v.name.split(":")[0]
        if name in seen or ".OPTIMIZER_SLOT" in name \
                or name.startswith("optimizer"):
            continue
        seen.add(name)
        parts = _apply_key_map(name, key_map).split("/")
        leaf = _TF_RENAMES.get(parts[-1], parts[-1])
        _nest(out, parts[:-1], leaf, np.asarray(v.numpy()))
    return out


def import_tf_frozen_graph(path: str,
                           key_map: Optional[Dict[str, str]] = None
                           ) -> Dict:
    """TF1 frozen GraphDef -> nested params dict of its Const tensors
    (the weight side of TFNet's frozen-graph loading,
    ref: TFNet.scala doLoadTensorflow frozen path). Names are nested on
    '/'; ``<name>/read`` identity nodes are skipped."""
    import tensorflow as tf
    from tensorflow.python.framework import tensor_util

    gd = tf.compat.v1.GraphDef()
    with open(path, "rb") as f:
        gd.ParseFromString(f.read())
    out: Dict = {}
    for node in gd.node:
        if node.op != "Const" or "value" not in node.attr:
            continue
        arr = tensor_util.MakeNdarray(node.attr["value"].tensor)
        if not isinstance(arr, np.ndarray) or arr.dtype == object:
            continue
        parts = _apply_key_map(node.name, key_map).split("/")
        leaf = _TF_RENAMES.get(parts[-1], parts[-1])
        _nest(out, parts[:-1], leaf, arr)
    return out


# --------------------------------------------------------------- ONNX --
# Minimal protobuf wire reader: enough of onnx.proto to pull the graph
# initializers out of a ModelProto. Field numbers from the public ONNX
# schema: ModelProto.graph=7; GraphProto.initializer=5;
# TensorProto.dims=1, .data_type=2, .float_data=4, .int32_data=5,
# .int64_data=7, .name=8, .raw_data=9, .double_data=10.

_ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
                7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated protobuf: varint past end")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's fields.
    Raises ValueError on truncation -- silently importing a partial
    file would drop trailing initializers."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wire in (1, 5):  # fixed64 / fixed32
            width = 8 if wire == 1 else 4
            if pos + width > n:
                raise ValueError("truncated protobuf: short fixed field")
            val = buf[pos:pos + width]
            pos += width
        elif wire == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                raise ValueError("truncated protobuf: field past end")
            val = buf[pos:pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _signed(v: int) -> int:
    """Two's-complement interpretation of a protobuf varint (negative
    ints are encoded as 10-byte varints of their 64-bit pattern)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_tensor_proto(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype = np.float32
    name = ""
    raw = None
    floats: List[float] = []
    int32s: List[int] = []
    int64s: List[int] = []
    doubles: List[float] = []
    for field, wire, val in _iter_fields(buf):
        if field == 1:  # dims (repeated int64, varint or packed)
            if wire == 0:
                dims.append(val)
            else:
                p = 0
                while p < len(val):
                    d, p = _read_varint(val, p)
                    dims.append(d)
        elif field == 2:
            if val not in _ONNX_DTYPES:
                raise ValueError(
                    f"unsupported ONNX tensor data_type {val} (bf16/fp8 "
                    "initializers are not importable)")
            dtype = _ONNX_DTYPES[val]
        elif field == 4:
            if wire == 5:
                floats.append(struct.unpack("<f", val)[0])
            else:  # packed
                floats.extend(np.frombuffer(val, "<f4").tolist())
        elif field == 5:
            if wire == 0:
                int32s.append(_signed(val))
            else:
                p = 0
                while p < len(val):
                    d, p = _read_varint(val, p)
                    int32s.append(_signed(d))
        elif field == 7:
            if wire == 0:
                int64s.append(_signed(val))
            else:
                p = 0
                while p < len(val):
                    d, p = _read_varint(val, p)
                    int64s.append(_signed(d))
        elif field == 8:
            name = val.decode("utf-8")
        elif field == 9:
            raw = val
        elif field == 10:
            if wire == 1:
                doubles.append(struct.unpack("<d", val)[0])
            else:
                doubles.extend(np.frombuffer(val, "<f8").tolist())
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np.dtype(dtype).newbyteorder("<"))
    elif floats:
        arr = np.asarray(floats, np.float32)
    elif doubles:
        arr = np.asarray(doubles, np.float64)
    elif int64s:
        arr = np.asarray(int64s, np.int64)
    elif int32s:
        arr = np.asarray(int32s, np.int32)
    else:
        arr = np.zeros(0, dtype)
    return name, arr.astype(dtype, copy=False).reshape(dims)


def _onnx_initializers(model_bytes: bytes) -> Dict[str, np.ndarray]:
    graph = None
    for field, _, val in _iter_fields(model_bytes):
        if field == 7:  # ModelProto.graph
            graph = val
            break
    if graph is None:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    out: Dict[str, np.ndarray] = {}
    for field, _, val in _iter_fields(graph):
        if field == 5:  # GraphProto.initializer
            name, arr = _parse_tensor_proto(val)
            out[name] = arr
    return out


def import_onnx(path_or_bytes, key_map: Optional[Dict[str, str]] = None,
                transpose_linear: bool = True) -> Dict:
    """ONNX model -> nested flax-style params dict from its graph
    initializers (ref: pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-128
    maps ONNX nodes to zoo layers; here only the weights transfer).

    Dependency-free: parses the protobuf wire format directly (the
    ``onnx`` package is not required). Torch-exported models use
    ``<module>.weight`` names with [out, in] linears and OIHW convs, so
    the same remapping as ``import_torch_state_dict`` applies.
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    out: Dict = {}
    for key, arr in _onnx_initializers(data).items():
        key = _apply_key_map(key, key_map)
        parts = key.replace("/", ".").split(".")
        leaf, arr = _remap_torch_weight(parts[-1], arr, transpose_linear)
        _nest(out, parts[:-1], leaf, arr)
    return out


# -------------------------------------------------------------- Caffe --
# caffe.proto field numbers (public schema): NetParameter.layer=100
# (LayerParameter) / .layers=2 (legacy V1LayerParameter);
# LayerParameter: name=1, blobs=7; V1LayerParameter: name=4, blobs=6;
# BlobProto: data=5 (packed float), shape=7 (BlobShape.dim=1),
# legacy dims num/channels/height/width=1..4.


def _parse_caffe_blob(buf: bytes) -> np.ndarray:
    dims: List[int] = []
    legacy = [None, None, None, None]
    chunks: List[bytes] = []
    for field, wire, val in _iter_fields(buf):
        if field == 5:  # data (packed in practice; one frombuffer)
            chunks.append(val if wire == 2 else bytes(val))
        elif field == 7:  # shape: BlobShape
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1:
                    if w2 == 0:
                        dims.append(_signed(v2))
                    else:
                        p = 0
                        while p < len(v2):
                            d, p = _read_varint(v2, p)
                            dims.append(_signed(d))
        elif field in (1, 2, 3, 4) and wire == 0:  # legacy n/c/h/w
            legacy[field - 1] = val
    arr = np.frombuffer(b"".join(chunks), "<f4").astype(np.float32)
    if dims:
        return arr.reshape(dims)  # shape field is authoritative
    if any(v is not None for v in legacy):
        arr = arr.reshape([v for v in legacy if v is not None])
        # ONLY legacy dims carry redundant leading 1-dims (a bias is
        # stored [1, 1, 1, N]); drop them so it lands 1-D/2-D.
        # (Inherent legacy ambiguity: a conv kernel with num=1 output
        # channels is indistinguishable from padding dims -- modern
        # shape-field caffemodels are unaffected.)
        while arr.ndim > 1 and arr.shape[0] == 1:
            arr = arr[0]
    return arr


# caffe.proto V1LayerParameter.LayerType values for layers that carry
# weights (the rest parse fine as plain weight/bias layers or have none)
_V1_LAYER_TYPES = {
    4: "Convolution", 14: "InnerProduct", 39: "Deconvolution",
}


def import_caffe(path_or_bytes,
                 key_map: Optional[Dict[str, str]] = None) -> Dict:
    """``.caffemodel`` -> nested flax-style params dict
    (ref: zoo/.../models/common/caffe CaffeLoader role -- the reference
    executes caffe graphs via BigDL; here the weights import into the
    JAX re-implementation). Handles both LayerParameter (new) and
    V1LayerParameter (legacy) layer lists; blob 0 becomes ``kernel``
    (OIHW -> HWIO for convs, [out, in] -> [in, out] for inner product),
    blob 1 becomes ``bias``.
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    out: Dict = {}
    found_layer = False
    for field, _, val in _iter_fields(data):
        if field not in (2, 100):  # layers (V1) / layer (new)
            continue
        found_layer = True
        name_field = 4 if field == 2 else 1
        name = ""
        ltype = ""
        blobs: List[np.ndarray] = []
        for f2, w2, v2 in _iter_fields(val):
            if f2 == name_field and isinstance(v2, bytes):
                name = v2.decode("utf-8", "replace")
            elif field == 100 and f2 == 2 and isinstance(v2, bytes):
                ltype = v2.decode("utf-8", "replace")
            elif field == 2 and f2 == 5 and w2 == 0:
                # V1LayerParameter.type enum (caffe.proto LayerType);
                # BVLC V1 has no BatchNorm/Scale values -- forks that
                # back-ported BN disagree on the enum, so BN is instead
                # recognized below by its blob signature
                ltype = _V1_LAYER_TYPES.get(int(v2), "")
            elif f2 == (6 if field == 2 else 7):
                blobs.append(_parse_caffe_blob(v2))
        if not name or not blobs:
            continue
        if (field == 2 and not ltype and len(blobs) == 3
                and blobs[2].size == 1 and blobs[0].ndim <= 1
                and blobs[0].shape == blobs[1].shape):
            # legacy 3-blob (mean-sum, var-sum, scalar factor) is the BN
            # statistical layout regardless of the fork's enum value
            ltype = "BatchNorm"
        parts = _apply_key_map(name, key_map).split("/")
        if ltype == "BatchNorm":
            # blobs: mean-sum, variance-sum, moving-average factor; the
            # stats are the sums divided by the factor
            factor = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 \
                else 1.0
            factor = factor if factor != 0 else 1.0
            _nest(out, parts, "mean", blobs[0] / factor)
            _nest(out, parts, "var", blobs[1] / factor)
        elif ltype == "Scale":
            _nest(out, parts, "scale", blobs[0])
            if len(blobs) > 1:
                _nest(out, parts, "bias", blobs[1])
        else:
            if len(blobs) > 2:
                raise ValueError(
                    f"layer {name!r} ({ltype or 'V1'}) has "
                    f"{len(blobs)} blobs; only BatchNorm/Scale "
                    "multi-blob layers are understood")
            leaf, kernel = _remap_torch_weight("weight", blobs[0], True)
            _nest(out, parts, leaf, kernel)
            if len(blobs) > 1:
                _nest(out, parts, "bias", blobs[1])
    if not found_layer:
        raise ValueError("not a caffemodel (no layer fields)")
    return out
