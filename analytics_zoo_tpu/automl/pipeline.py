"""TimeSequencePipeline: fitted feature transformer + model as one unit.

The analog of ``TimeSequencePipeline`` (ref: pyzoo/zoo/automl/pipeline/
time_sequence.py:26-222 -- describe/fit/evaluate/predict/
predict_with_uncertainty/save + load_ts_pipeline).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl import metrics as automl_metrics
from analytics_zoo_tpu.automl.feature import TimeSequenceFeatureTransformer
from analytics_zoo_tpu.automl.models import TimeSequenceModel
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)


class TimeSequencePipeline:
    def __init__(self, feature_transformers: TimeSequenceFeatureTransformer,
                 model: TimeSequenceModel,
                 config: Optional[Dict[str, Any]] = None,
                 name: str = "ts_pipeline"):
        self.feature_transformers = feature_transformers
        self.model = model
        self.config = dict(config or {})
        self.name = name

    def describe(self) -> Dict[str, Any]:
        show = ("model", "past_seq_len", "selected_features", "lr",
                "batch_size", "epochs")
        return {k: self.config[k] for k in show if k in self.config}

    # ------------------------------------------------------------- fit --
    def fit(self, input_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            epoch_num: int = 20) -> "TimeSequencePipeline":
        """Incremental training with the already-found config
        (ref: time_sequence.py fit)."""
        ft = self.feature_transformers
        x, y = ft.transform(input_df, is_train=True)
        val = None
        if validation_df is not None:
            val = ft.transform(validation_df, is_train=True)
        from analytics_zoo_tpu.automl.predictor import _unscaler

        config = dict(self.config)
        config["epochs"] = epoch_num
        reward = self.model.fit_eval(x, y, validation_data=val,
                                     unscale_fn=_unscaler(ft), **config)
        logger.info("pipeline fit: %s=%.6g",
                    config.get("metric", "mse"), reward)
        return self

    def fit_with_fixed_configs(self, input_df: pd.DataFrame,
                               validation_df: Optional[pd.DataFrame] = None,
                               **user_configs) -> "TimeSequencePipeline":
        """Fit from scratch with explicit configs (ref: time_sequence.py
        fit_with_fixed_configs)."""
        config = {**self.config, **user_configs}
        ft = self.feature_transformers
        x, y = ft.fit_transform(input_df, **config)
        val = None
        if validation_df is not None:
            val = ft.transform(validation_df, is_train=True)
        self.model.fit_eval(x, y, validation_data=val, **config)
        self.config = config
        return self

    # ------------------------------------------------------- inference --
    def predict(self, input_df: pd.DataFrame) -> pd.DataFrame:
        ft = self.feature_transformers
        x = ft.transform(input_df, is_train=False)
        y_pred = self.model.predict(x)
        return ft.post_processing(input_df, y_pred, is_train=False)

    def predict_with_uncertainty(self, input_df: pd.DataFrame,
                                 n_iter: int = 10):
        ft = self.feature_transformers
        x = ft.transform(input_df, is_train=False)
        mean, std = self.model.predict_with_uncertainty(x, n_iter)
        pred_df = ft.post_processing(input_df, mean, is_train=False)
        t = len(ft.target_col)
        std = std.reshape(len(std), ft.future_seq_len, t)
        return pred_df, ft.unscale_uncertainty(std)

    def evaluate(self, input_df: pd.DataFrame,
                 metrics: List[str] = ("mse",)) -> Dict[str, float]:
        ft = self.feature_transformers
        x, _ = ft.transform(input_df, is_train=True)
        y_pred = self.model.predict(x)
        y_pred_unscaled, y_true = ft.post_processing(input_df, y_pred,
                                                     is_train=True)
        return automl_metrics.evaluate_all(metrics, y_true,
                                           y_pred_unscaled)

    # ----------------------------------------------------- persistence --
    def save(self, dir_path: str) -> None:
        os.makedirs(dir_path, exist_ok=True)
        self.feature_transformers.save(dir_path)
        self.model.save(os.path.join(dir_path, "model"))
        from analytics_zoo_tpu.automl.feature import _jsonable

        with open(os.path.join(dir_path, "pipeline.json"), "w") as f:
            json.dump({"name": self.name,
                       "config": _jsonable(self.config)}, f)
        logger.info("pipeline saved to %s", dir_path)


def load_ts_pipeline(dir_path: str) -> TimeSequencePipeline:
    """(ref: time_sequence.py load_ts_pipeline)."""
    with open(os.path.join(dir_path, "pipeline.json")) as f:
        meta = json.load(f)
    ft = TimeSequenceFeatureTransformer.restore(dir_path)
    model = TimeSequenceModel.restore(os.path.join(dir_path, "model"))
    return TimeSequencePipeline(ft, model, config=meta["config"],
                                name=meta["name"])
