"""Search-space recipes.

The analog of the reference recipe set (ref: pyzoo/zoo/automl/config/
recipe.py:620 -- SmokeRecipe, GridRandomRecipe, LSTMGridRandomRecipe,
MTNetGridRandomRecipe...), rewritten against :mod:`space` samplers. A
recipe = a search space over (features, model hyperparameters, training
params) + runtime parameters (num_samples per grid point, epochs per
trial).
"""

from __future__ import annotations

from typing import Any, Dict, List

from analytics_zoo_tpu.automl.space import (Choice, FeatureSubset, Grid,
                                            SampleFrom, Uniform)


class Recipe:
    """(ref: recipe.py Recipe)."""

    def __init__(self):
        self.training_iteration = 1
        self.num_samples = 1
        self.reward_metric = None

    def search_space(self, all_available_features: List[str]
                     ) -> Dict[str, Any]:
        raise NotImplementedError

    def runtime_params(self) -> Dict[str, Any]:
        out = {"training_iteration": self.training_iteration,
               "num_samples": self.num_samples}
        if self.reward_metric is not None:
            out["reward_metric"] = self.reward_metric
        return out


class SmokeRecipe(Recipe):
    """One random LSTM config, one epoch (ref: recipe.py SmokeRecipe)."""

    def search_space(self, all_available_features):
        return {
            "selected_features": list(all_available_features),
            "model": "LSTM",
            "lstm_1_units": Choice([32, 64]),
            "dropout_1": Uniform(0.2, 0.5),
            "lstm_2_units": Choice([32, 64]),
            "dropout_2": Uniform(0.2, 0.5),
            "lr": 0.001,
            "batch_size": 64,
            "epochs": 1,
            "past_seq_len": 2,
        }


class GridRandomRecipe(Recipe):
    """Random feature subsets x a small LSTM grid
    (ref: recipe.py GridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2):
        super().__init__()
        self.num_samples = num_rand_samples
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "model": "LSTM",
            "lstm_1_units": Grid([16, 32]),
            "dropout_1": Uniform(0.2, 0.5),
            "lstm_2_units": Grid([16, 32]),
            "dropout_2": Uniform(0.2, 0.5),
            "lr": 0.001,
            "batch_size": 64,
            "epochs": 1,
            "past_seq_len": self.look_back,
        }


class LSTMGridRandomRecipe(GridRandomRecipe):
    """(ref: recipe.py LSTMGridRandomRecipe -- wider LSTM grid)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2,
                 lstm_1_units=(16, 32, 64), lstm_2_units=(16, 32, 64),
                 batch_size=(32, 64)):
        super().__init__(num_rand_samples, look_back)
        self.lstm_1_units = list(lstm_1_units)
        self.lstm_2_units = list(lstm_2_units)
        self.batch_size = list(batch_size)

    def search_space(self, all_available_features):
        s = super().search_space(all_available_features)
        s.update({
            "lstm_1_units": Grid(self.lstm_1_units),
            "lstm_2_units": Grid(self.lstm_2_units),
            "batch_size": Choice(self.batch_size),
        })
        return s


class Seq2SeqRandomRecipe(Recipe):
    def __init__(self, num_rand_samples: int = 1, look_back: int = 8):
        super().__init__()
        self.num_samples = num_rand_samples
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "model": "Seq2Seq",
            "latent_dim": Choice([32, 64, 128]),
            "dropout": Uniform(0.1, 0.4),
            "lr": 0.001,
            "batch_size": 64,
            "epochs": 1,
            "past_seq_len": self.look_back,
        }


class MTNetGridRandomRecipe(Recipe):
    """(ref: recipe.py MTNetGridRandomRecipe -- past_seq_len depends on
    the sampled long_num and time_step)."""

    def __init__(self, num_rand_samples: int = 1,
                 time_step=(3, 4), long_num=(3, 4), ar_size=(2, 3),
                 cnn_height=(2, 3), cnn_hidden=(32,), rnn_hidden=(32,)):
        super().__init__()
        self.num_samples = num_rand_samples
        self.time_step = list(time_step)
        self.long_num = list(long_num)
        self.ar_size = list(ar_size)
        self.cnn_height = list(cnn_height)
        self.cnn_hidden = list(cnn_hidden)
        self.rnn_hidden = list(rnn_hidden)

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "model": "MTNet",
            "time_step": Choice(self.time_step),
            "long_num": Choice(self.long_num),
            "ar_size": Choice(self.ar_size),
            "cnn_height": Choice(self.cnn_height),
            "cnn_hidden": Choice(self.cnn_hidden),
            "rnn_hidden": Choice(self.rnn_hidden),
            "cnn_dropout": Uniform(0.1, 0.3),
            "rnn_dropout": Uniform(0.1, 0.3),
            "lr": 0.001,
            "batch_size": 64,
            "epochs": 1,
            "past_seq_len": SampleFrom(
                lambda c: (c["long_num"] + 1) * c["time_step"]),
        }


class TCNGridRandomRecipe(Recipe):
    def __init__(self, num_rand_samples: int = 1, look_back: int = 16):
        super().__init__()
        self.num_samples = num_rand_samples
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "model": "TCN",
            "levels": Choice([2, 3]),
            "hidden": Choice([16, 30]),
            "kernel_size": Choice([2, 3]),
            "dropout": Uniform(0.05, 0.25),
            "lr": 0.001,
            "batch_size": 64,
            "epochs": 1,
            "past_seq_len": self.look_back,
        }


class XgbRegressorGridRandomRecipe(Recipe):
    """Grid/random space over the XGBoost regressor's tree params
    (ref: the reference searches automl/model/XGBoost.py through the
    same recipe mechanism)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2,
                 n_estimators=(50, 100), max_depth=(3, 5)):
        super().__init__()
        self.num_samples = num_rand_samples
        self.look_back = look_back
        self.n_estimators = list(n_estimators)
        self.max_depth = list(max_depth)

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "model": "XGBoost",
            "n_estimators": Grid(self.n_estimators),
            "max_depth": Grid(self.max_depth),
            "learning_rate": Uniform(0.05, 0.3),
            "subsample": Uniform(0.7, 1.0),
            "past_seq_len": self.look_back,
        }
